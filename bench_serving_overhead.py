#!/usr/bin/env python
"""Serving-plane overhead bench: frontend → router → worker → SSE on CPU.

Measures the token path the ISSUE-4 serving-gap work targets, WITHOUT a
TPU: mocker workers decode at a known synthetic rate, so everything above
the engine — slot queues, request-plane frames, detokenization, SSE
assembly — is what the measured throughput actually prices. Reports:

  * aggregate streamed tok/s across N concurrent SSE streams
  * serving-plane overhead in µs/token (wall time minus the mocker's
    synthetic engine time, over total streamed tokens)
  * frontend/worker process CPU µs per token (scraped from /proc —
    the direct cost the fleet/codec arms move)
  * mean tokens per SSE event (frontend-side batching signal)
  * worker-side items/frames ratio (request-plane coalescing signal,
    scraped from the frontend's tokens-per-frame histogram + the metrics
    topic republished by WorkerMetricsPublisher)
  * TTFT p50/p99 per stream

Fleet scale-out (ISSUE 13, docs/frontend_scaleout.md): `--frontends N`
runs N stateless frontend replicas on the shared discovery plane with
client streams split round-robin; `--fleet` sweeps 1→2→4 and reports the
scaling ratios. `--codec-ab` A/Bs the ENC_TOK binary token wire path
(DYN_WIRE_BINARY_TOKENS=1) against the msgpack arm. NOTE: the scaling
ratio is core-bound — on a 2-core dev host the whole fleet (frontends +
mocker + client) shares 2 cores and 1→2 cannot approach 2x no matter how
stateless the frontends are; the CI gate runs on 4-vCPU runners and the
real 1→2→4 claim rides the bench_watchdog `engine_fleet` hardware phase.

Usage:
  python bench_serving_overhead.py                      # default load
  python bench_serving_overhead.py --streams 16 --osl 128
  python bench_serving_overhead.py --frontends 2 --streams 32
  python bench_serving_overhead.py --fleet --streams 32
  python bench_serving_overhead.py --codec-ab --streams 32
  python bench_serving_overhead.py --smoke --min-tok-s 300   # CI gate
  python bench_serving_overhead.py --fleet-smoke             # CI gate
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import socket
import statistics
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_CLK_TCK = os.sysconf("SC_CLK_TCK")


def proc_cpu_s(pid: int) -> float:
    """utime+stime seconds of one process from /proc/<pid>/stat (0.0 when
    the process is gone — a dead child contributes nothing)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            after_comm = f.read().rsplit(")", 1)[1].split()
        # fields 14/15 (1-based) are utime/stime; after the comm split the
        # first remaining field is 3 (state), so they land at index 11/12
        return (int(after_comm[11]) + int(after_comm[12])) / _CLK_TCK
    except (OSError, IndexError, ValueError):
        return 0.0


def spawn(args, name, env=None):
    full_env = dict(os.environ)
    full_env["JAX_PLATFORMS"] = "cpu"
    prev = ":".join(
        p for p in full_env.get("PYTHONPATH", "").split(":")
        if p and ".axon_site" not in p
    )
    full_env["PYTHONPATH"] = f"{REPO}:{prev}" if prev else str(REPO)
    if env:
        full_env.update(env)
    log = open(f"/tmp/bench_overhead_{name}.log", "wb")
    return subprocess.Popen(
        [sys.executable, *args], env=full_env, stdout=log, stderr=subprocess.STDOUT
    )


async def wait_ready(base: str, timeout: float = 30.0):
    import aiohttp

    deadline = time.monotonic() + timeout
    async with aiohttp.ClientSession() as sess:
        while time.monotonic() < deadline:
            try:
                async with sess.get(base + "/v1/models") as r:
                    if r.status == 200 and (await r.json())["data"]:
                        return
            except aiohttp.ClientError:
                pass
            await asyncio.sleep(0.2)
    raise TimeoutError("frontend/model never became ready")


async def one_stream(sess, base: str, idx: int, osl: int) -> dict:
    """Run one streaming chat completion; returns per-stream measurements."""
    body = {
        "model": "bench-model",
        "messages": [
            {"role": "user", "content": f"serving overhead bench prompt {idx} "
             + "q" * 64}
        ],
        "stream": True,
        "max_tokens": osl,
        "stream_options": {"include_usage": True},
    }
    t0 = time.monotonic()
    ttft = None
    events = 0
    completion_tokens = 0
    async with sess.post(base + "/v1/chat/completions", json=body) as resp:
        assert resp.status == 200, await resp.text()
        async for raw in resp.content:
            line = raw.decode().strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            chunk = json.loads(line[6:])
            if chunk.get("usage"):
                completion_tokens = chunk["usage"]["completion_tokens"]
                continue
            delta = (chunk.get("choices") or [{}])[0].get("delta", {})
            if delta.get("content"):
                events += 1
                if ttft is None:
                    ttft = time.monotonic() - t0
    return {
        "wall_s": time.monotonic() - t0,
        "ttft_s": ttft,
        "sse_events": events,
        "completion_tokens": completion_tokens,
    }


def scrape_tokens_per_frame(metrics_text: str) -> float | None:
    """Mean of the frontend's dynamo_frontend_tokens_per_frame histogram."""
    total = count = None
    for line in metrics_text.splitlines():
        if line.startswith("dynamo_frontend_tokens_per_frame_sum"):
            total = float(line.rsplit(" ", 1)[1])
        elif line.startswith("dynamo_frontend_tokens_per_frame_count"):
            count = float(line.rsplit(" ", 1)[1])
    if total is not None and count:
        return total / count
    return None


async def run_bench(args, extra_env=None) -> dict:
    import aiohttp

    n_fe = max(getattr(args, "frontends", 1), 1)
    disc = f"tcp://127.0.0.1:{free_port()}"
    fe_ports = [free_port() for _ in range(n_fe)]
    fe_procs = []
    for i, port in enumerate(fe_ports):
        fe_procs.append(
            spawn(
                ["-m", "dynamo_tpu.frontend", "--http-port", str(port),
                 "--discovery", disc]
                + (["--embed-discovery"] if i == 0 else []),
                f"frontend{i}",
                # the codec knob (DYN_WIRE_BINARY_TOKENS) is CLIENT-side:
                # the frontend advertises ENC_TOK per stream, so the A/B
                # env must land here, not only on the workers
                env=dict(extra_env or {}),
            )
        )
    worker_procs = []
    for i in range(args.workers):
        worker_procs.append(
            spawn(
                ["-m", "dynamo_tpu.mocker", "--model-name", "bench-model",
                 "--discovery", disc, "--speedup-ratio", str(args.speedup),
                 "--block-size", "16"],
                f"mocker{i}",
                # the mocker decodes one token per step (worst case for the
                # serving plane); a small coalesce window is what turns its
                # singleton emissions into multi-item frames — the real
                # engine's K-step blocks batch with the window at 0
                env={"DYN_STREAM_COALESCE_MS": str(args.coalesce_ms),
                     **(extra_env or {})},
            )
        )
    procs = fe_procs + worker_procs
    bases = [f"http://127.0.0.1:{p}" for p in fe_ports]
    try:
        for base in bases:
            await wait_ready(base)
        conn = aiohttp.TCPConnector(limit=args.streams + 4)
        async with aiohttp.ClientSession(connector=conn) as sess:
            # tiny warmup round so connection setup/compile-analogous costs
            # don't pollute the measured window (touch every replica)
            await asyncio.gather(
                *(one_stream(sess, bases[i % n_fe], 900 + i, 4)
                  for i in range(max(min(args.streams, 4), n_fe)))
            )
            cpu_fe0 = sum(proc_cpu_s(p.pid) for p in fe_procs)
            cpu_wk0 = sum(proc_cpu_s(p.pid) for p in worker_procs)
            t0 = time.monotonic()
            results = await asyncio.gather(
                *(one_stream(sess, bases[i % n_fe], i, args.osl)
                  for i in range(args.streams))
            )
            wall = time.monotonic() - t0
            cpu_fe = sum(proc_cpu_s(p.pid) for p in fe_procs) - cpu_fe0
            cpu_wk = sum(proc_cpu_s(p.pid) for p in worker_procs) - cpu_wk0
            tpfs = []
            for base in bases:
                async with sess.get(base + "/metrics") as r:
                    v = scrape_tokens_per_frame(await r.text())
                    if v:
                        tpfs.append(v)
            tpf = statistics.mean(tpfs) if tpfs else None
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()

    total_tokens = sum(r["completion_tokens"] for r in results)
    total_events = sum(r["sse_events"] for r in results)
    ttfts = sorted(r["ttft_s"] for r in results if r["ttft_s"] is not None)
    # the mocker's synthetic engine time for the measured window: osl decode
    # steps, each decoding every concurrent stream in one step
    per_step = (0.008 + args.streams * 60e-6) / args.speedup
    ideal_s = args.osl * per_step
    overhead_us = (
        (wall - ideal_s) / total_tokens * 1e6 if total_tokens else None
    )
    return {
        "streams": args.streams,
        "osl": args.osl,
        "workers": args.workers,
        "frontends": n_fe,
        "speedup": args.speedup,
        "wall_s": round(wall, 3),
        "total_tokens": total_tokens,
        "tok_s": round(total_tokens / wall, 1) if wall else None,
        "engine_ideal_s": round(ideal_s, 3),
        "serving_overhead_us_per_tok": round(overhead_us, 1)
        if overhead_us is not None else None,
        "frontend_cpu_s": round(cpu_fe, 3),
        "frontend_cpu_us_per_tok": round(cpu_fe / total_tokens * 1e6, 1)
        if total_tokens else None,
        "worker_cpu_us_per_tok": round(cpu_wk / total_tokens * 1e6, 1)
        if total_tokens else None,
        "sse_events": total_events,
        "tokens_per_sse_event": round(total_tokens / total_events, 2)
        if total_events else None,
        "frontend_tokens_per_frame": round(tpf, 2) if tpf else None,
        "ttft_p50_s": round(statistics.median(ttfts), 4) if ttfts else None,
        "ttft_p99_s": round(ttfts[max(0, int(len(ttfts) * 0.99) - 1)], 4)
        if ttfts else None,
    }


async def overload_stream(sess, base: str, idx: int, osl: int) -> dict:
    """One streaming chat completion under the admission gate: a 429 is a
    clean rejection (Retry-After recorded), a 200 stream is checked for
    completeness (finish chunk + full token count — a mid-stream kill
    shows up as a truncation here)."""
    body = {
        "model": "bench-model",
        "messages": [{"role": "user", "content":
                      f"overload bench prompt {idx} " + "q" * 48}],
        "stream": True,
        "max_tokens": osl,
        "stream_options": {"include_usage": True},
    }
    t0 = time.monotonic()
    out = {"rejected": False, "retry_after": None, "ttft_s": None,
           "tokens": 0, "finished": False, "error": None}
    try:
        async with sess.post(base + "/v1/chat/completions", json=body) as resp:
            if resp.status == 429:
                out["rejected"] = True
                out["retry_after"] = resp.headers.get("Retry-After")
                await resp.read()
                return out
            if resp.status != 200:
                out["error"] = f"HTTP {resp.status}"
                await resp.read()
                return out
            async for raw in resp.content:
                line = raw.decode().strip()
                if not line.startswith("data: ") or line == "data: [DONE]":
                    continue
                chunk = json.loads(line[6:])
                if chunk.get("usage"):
                    out["tokens"] = chunk["usage"]["completion_tokens"]
                    continue
                for ch in chunk.get("choices") or []:
                    if (ch.get("delta") or {}).get("content") and \
                            out["ttft_s"] is None:
                        out["ttft_s"] = time.monotonic() - t0
                    if ch.get("finish_reason"):
                        out["finished"] = True
    except Exception as e:  # noqa: BLE001 — recorded, judged by the gate
        out["error"] = f"{type(e).__name__}: {e}"
    return out


async def _paced_load(sess, base: str, qps: float, duration_s: float,
                      osl: int, tag: int) -> list:
    tasks = []
    t0 = time.monotonic()
    n = max(1, int(round(qps * duration_s)))
    gap = 1.0 / max(qps, 1e-9)
    for k in range(n):
        delay = t0 + k * gap - time.monotonic()
        if delay > 0:
            await asyncio.sleep(delay)
        tasks.append(asyncio.create_task(
            overload_stream(sess, base, tag * 10_000 + k, osl)))
    return list(await asyncio.gather(*tasks))


def _goodput(results: list, window_s: float, slo_s: float) -> float:
    """SLA-attained tok/s over the offered window (the planner/soak
    goodput definition, docs/overload.md)."""
    attained = [r for r in results
                if r["finished"] and not r["rejected"]
                and r["ttft_s"] is not None and r["ttft_s"] <= slo_s]
    return sum(r["tokens"] for r in attained) / max(window_s, 1e-9)


async def run_overload_bench(args) -> dict:
    """Ramp offered load past a deliberately small-capacity mocker fleet
    with the admission gate live: at-capacity arm, then a ~10x burst.
    The gate must keep SLA-attained tok/s from collapsing, reject with
    429 + Retry-After before tokenization, and never kill a stream
    mid-flight (docs/overload.md)."""
    import aiohttp

    http_port = free_port()
    disc = f"tcp://127.0.0.1:{free_port()}"
    gate_env = {
        "DYN_GATE": "1",
        "DYN_GATE_TTFT_MS": str(args.overload_ttft_ms),
        "DYN_GATE_TTFT_HEADROOM": "1.0",
        "DYN_GATE_MAX_WAIT_MS": "300",
        "DYN_GATE_MAX_QUEUE": "16",
    }
    procs = [
        spawn(
            ["-m", "dynamo_tpu.frontend", "--http-port", str(http_port),
             "--embed-discovery", "--discovery", disc],
            "overload_frontend", env=gate_env,
        ),
        # deliberately tiny capacity: 2 decode slots at ~32ms/step — the
        # burst below is ~10x what this fleet can serve
        spawn(
            ["-m", "dynamo_tpu.mocker", "--model-name", "bench-model",
             "--discovery", disc, "--speedup-ratio", "0.25",
             "--max-num-seqs", "2", "--block-size", "16"],
            "overload_mocker",
        ),
    ]
    base = f"http://127.0.0.1:{http_port}"
    osl = 16
    try:
        await wait_ready(base)
        conn = aiohttp.TCPConnector(limit=256)
        async with aiohttp.ClientSession(connector=conn) as sess:
            capacity = await _paced_load(
                sess, base, qps=3.0, duration_s=6.0, osl=osl, tag=1)
            surge = await _paced_load(
                sess, base, qps=30.0, duration_s=3.0, osl=osl, tag=2)
            # let the admitted tail drain before teardown
            await asyncio.sleep(2.0)
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()

    slo_s = args.overload_slo_ms / 1000.0
    rejected = [r for r in surge if r["rejected"]]
    served = [r for r in capacity + surge if not r["rejected"]]
    kills = [r for r in served if not r["finished"] or r["tokens"] != osl
             or r["error"]]
    g_cap = _goodput(capacity, 6.0, slo_s)
    g_surge = _goodput(surge, 3.0, slo_s)
    return {
        "capacity_requests": len(capacity),
        "surge_requests": len(surge),
        "surge_rejected": len(rejected),
        "rejections_with_retry_after": sum(
            1 for r in rejected
            if r["retry_after"] and int(r["retry_after"]) >= 1),
        "mid_stream_kills": len(kills),
        "kill_detail": [r["error"] for r in kills[:5]],
        "goodput_capacity_tok_s": round(g_cap, 1),
        "goodput_surge_tok_s": round(g_surge, 1),
        "goodput_retention": round(g_surge / g_cap, 3) if g_cap else None,
    }


async def run_codec_identity() -> dict:
    """ENC_TOK byte-identity: with request ids and the wall clock pinned,
    the SSE bytes of a stream served over the binary token wire path must
    be byte-identical to the msgpack arm — same tokens, same chunk
    framing. In-proc (SoakFrontend + InProcMockWorker over the REAL
    request plane) because byte-identity needs deterministic request ids,
    which only pinned ids in one process can provide; the mocker's token
    stream is a function of the request id, so subprocess arms would
    diverge legitimately. Also asserts the binary arm actually used
    ENC_TOK frames (worker-side frames_binary) and the msgpack arm none."""
    import time as _time
    from unittest import mock

    import aiohttp

    from dynamo_tpu.llm.mocker.engine import MockEngineArgs
    from dynamo_tpu.planner.soak import InProcMockWorker, SoakFrontend

    payload = {
        "model": "codec-model",
        "messages": [{"role": "user", "content": "codec identity " + "q" * 48}],
        "stream": True,
        "max_tokens": 48,
        "stream_options": {"include_usage": True},
    }

    async def arm(binary: bool):
        os.environ["DYN_WIRE_BINARY_TOKENS"] = "1" if binary else "0"
        fe = await SoakFrontend().start()
        worker = None
        try:
            worker = await InProcMockWorker(
                fe.cfg,
                MockEngineArgs(model_name="codec-model", block_size=8,
                               speedup_ratio=100.0),
            ).start()
            await fe.wait_model("codec-model")
            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"{fe.base_url}/v1/chat/completions", json=payload
                ) as r:
                    assert r.status == 200, await r.text()
                    body = await r.read()
            stats = worker.drt.server.stats("dynamo.mocker.generate")
            return body, (stats.frames_binary if stats else 0)
        finally:
            if worker is not None:
                await worker.engine.close()  # step loop dies before the runtime
                await worker.stop()
            await fe.stop()

    prev = os.environ.get("DYN_WIRE_BINARY_TOKENS")
    try:
        with mock.patch(
            "dynamo_tpu.llm.preprocessor.secrets.token_hex",
            lambda n=8: "c0dec0dec0dec0de",
        ), mock.patch.object(_time, "time", lambda: 1_700_000_000.0):
            bin_bytes, bin_frames = await arm(True)
            msg_bytes, msg_frames = await arm(False)
    finally:
        if prev is None:
            os.environ.pop("DYN_WIRE_BINARY_TOKENS", None)
        else:
            os.environ["DYN_WIRE_BINARY_TOKENS"] = prev
    return {
        "sse_bytes": len(bin_bytes),
        "identical": bin_bytes == msg_bytes,
        "binary_arm_enc_frames": bin_frames,
        "msgpack_arm_enc_frames": msg_frames,
        "done_seen": b"data: [DONE]" in bin_bytes,
    }


async def run_codec_micro(pairs: int = 5, items: int = 3000,
                          streams: int = 8) -> dict:
    """Per-token frontend CPU of the TOKEN WIRE PATH, isolated: an
    in-proc request-plane server streams singleton token deltas (the
    mocker/per-token worst case, coalesced into ~64-item frames) and the
    consumer runs the frontend's real decode path (client frame decode +
    merge_token_deltas). Interleaved arm pairs, medians — the full-stack
    subprocess A/B is dominated by per-SSE-event socket/eventloop costs
    identical in both arms and swings with ambient load on small hosts,
    so THIS is where the codec's own µs/tok is measurable."""
    import resource
    import statistics as _stats

    from dynamo_tpu.llm.backend import merge_token_deltas
    from dynamo_tpu.runtime.request_plane import (
        RequestPlaneClient,
        RequestPlaneServer,
    )

    async def arm(binary: bool):
        os.environ["DYN_WIRE_BINARY_TOKENS"] = "1" if binary else "0"
        os.environ["DYN_STREAM_COALESCE_MS"] = "1"
        srv = RequestPlaneServer()

        async def handler(req, ctx):
            for i in range(items):
                yield {"data": {"token_ids": [i % 50000]}}
                if i % 64 == 0:
                    await asyncio.sleep(0)

        stats = srv.register("t.gen", handler)
        host, port = await srv.start()
        cli = RequestPlaneClient()

        async def consume():
            stream = await cli.call(f"{host}:{port}", "t.gen", {})
            n = 0
            async for ann in merge_token_deltas(stream):
                d = ann.data
                if isinstance(d, dict):
                    n += len(d.get("token_ids") or [])
            return n

        cpu0 = resource.getrusage(resource.RUSAGE_SELF)
        counts = await asyncio.gather(*(consume() for _ in range(streams)))
        cpu1 = resource.getrusage(resource.RUSAGE_SELF)
        cpu = (cpu1.ru_utime + cpu1.ru_stime) - (cpu0.ru_utime + cpu0.ru_stime)
        total = sum(counts)
        assert total == items * streams
        await cli.close()
        await srv.stop()
        return cpu / total * 1e6, stats.frames_binary

    # restore BOTH touched env vars: a leaked coalesce window would make
    # the identity check's frame composition timing-dependent
    prev_env = {
        k: os.environ.get(k)
        for k in ("DYN_WIRE_BINARY_TOKENS", "DYN_STREAM_COALESCE_MS")
    }
    try:
        await arm(True)  # warmup both arms
        await arm(False)
        msgpack_us, binary_us = [], []
        bin_frames = 0
        for _ in range(pairs):
            us, _n = await arm(False)
            msgpack_us.append(us)
            us, n = await arm(True)
            binary_us.append(us)
            bin_frames += n
    finally:
        for k, v in prev_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    mm = _stats.median(msgpack_us)
    bb = _stats.median(binary_us)
    return {
        "msgpack_us_per_tok": round(mm, 2),
        "binary_us_per_tok": round(bb, 2),
        "drop": round(1.0 - bb / mm, 3) if mm else None,
        "binary_frames_seen": bin_frames,
    }


def check_codec_identity() -> bool:
    out = asyncio.run(run_codec_identity())
    print(json.dumps({"codec_identity": out}, indent=2))
    ok = True
    if not out["identical"]:
        print("CODEC IDENTITY FAIL: binary-arm SSE bytes differ from the "
              "msgpack arm", file=sys.stderr)
        ok = False
    if not out["done_seen"]:
        print("CODEC IDENTITY FAIL: stream truncated", file=sys.stderr)
        ok = False
    if out["binary_arm_enc_frames"] <= 0:
        print("CODEC IDENTITY FAIL: binary arm emitted no ENC_TOK frames "
              "(negotiation broken — the A/B compared msgpack to itself)",
              file=sys.stderr)
        ok = False
    if out["msgpack_arm_enc_frames"] != 0:
        print("CODEC IDENTITY FAIL: msgpack arm emitted ENC_TOK frames",
              file=sys.stderr)
        ok = False
    return ok


async def run_compile_smoke(args) -> dict:
    """Replay a trace against a warmed in-process JaxEngine and read the
    per-surface compile counters (docs/compilation.md). warmup() takes
    the baseline cache-size snapshot; the replay — lone arrivals, cap
    bursts, and staggered mid-decode admissions across every prefill
    bucket — must then mint ZERO new XLA programs. comp-warmup-coverage
    proves surface reachability statically; this gate proves at runtime
    that warmup actually compiled everything the steady-state trace
    needs (a failure means a shape leaked past the bucketing helpers or
    warmup missed a variant)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols import PreprocessedRequest
    from dynamo_tpu.models import llama
    from dynamo_tpu.runtime.engine import Context

    model_cfg = llama.LlamaConfig.tiny(dtype=jnp.float32)
    params = llama.init_params(model_cfg, jax.random.PRNGKey(0))
    cfg = EngineConfig(
        model="tiny", max_num_seqs=4, page_size=8, num_pages=64,
        max_model_len=128, prefill_buckets=(16, 32), max_prefill_chunk=32,
    )
    eng = JaxEngine(cfg, model_config=model_cfg, params=params)
    warmup_reqs = await eng.warmup()
    warm = eng.stats()

    rng = np.random.RandomState(0xC0DE)
    vocab = model_cfg.vocab_size
    replayed = 0
    tokens = [0]

    async def one(isl: int, osl: int):
        req = PreprocessedRequest(
            token_ids=rng.randint(5, max(vocab - 1, 6), size=isl).tolist(),
            stop_conditions={"max_tokens": osl, "ignore_eos": True},
            sampling_options={"temperature": 1.0},
        ).to_dict()
        async for item in eng.generate(req, Context()):
            data = item.get("data")
            if data:
                tokens[0] += len(data.get("token_ids", ()))

    # the replay trace: per bucket a lone arrival (1-lane variant), a
    # burst (the cap-lane variant — plan_prefill lanes are 1-or-cap, so
    # any burst >= 2 lands on the warmed cap shape), and a staggered
    # pair that admits mid-decode (the patch path)
    for b in [x for x in cfg.prefill_buckets if x <= cfg.max_model_len]:
        lengths = [max(b - 8, 4), max(b // 2, 4), max(b - 1, 4)]
        await one(lengths[0], 6)
        replayed += 1
        await asyncio.gather(*[one(n, 4) for n in lengths])
        replayed += len(lengths)
        t1 = asyncio.create_task(one(lengths[1], 8))
        await asyncio.sleep(0.05)
        t2 = asyncio.create_task(one(lengths[2], 4))
        await asyncio.gather(t1, t2)
        replayed += 2
    stats = eng.stats()
    await eng.close()
    return {
        "warmup_requests": warmup_reqs,
        "replayed_requests": replayed,
        "replayed_tokens": tokens[0],
        "compiled_variants_after_warmup": warm["compiled_variants"],
        "compiled_variants": stats["compiled_variants"],
        "compile_surfaces": stats["compile_surfaces"],
        "post_warmup_compiles": stats["post_warmup_compiles"],
    }


def _mk_tiny_engine(mixed: bool, n_adapters: int = 0, slots: int = 8):
    """In-process tiny JaxEngine (the compile-smoke pattern) with an
    optional adapter roster for the lora-sweep / blend smokes."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models import llama, lora

    model_cfg = llama.LlamaConfig.tiny(dtype=jnp.float32)
    params = llama.init_params(model_cfg, jax.random.PRNGKey(0))
    cfg = EngineConfig(
        model="tiny", max_num_seqs=4, page_size=8, num_pages=128,
        max_model_len=256, prefill_buckets=(16, 32), max_prefill_chunk=32,
        mixed_dispatch=mixed, lora_pool_slots=slots,
    )
    eng = JaxEngine(cfg, model_config=model_cfg, params=params)
    if n_adapters:
        eng.register_adapters([
            lora.init_adapter(model_cfg, f"ad{i}", jax.random.PRNGKey(100 + i),
                              rank=4)
            for i in range(1, n_adapters + 1)
        ])
    return eng


async def _tiny_one(eng, prompt, rid, osl, lora_name=None, guided=None,
                    started: asyncio.Event | None = None):
    from dynamo_tpu.llm.protocols import PreprocessedRequest
    from dynamo_tpu.runtime.engine import Context

    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions={"max_tokens": osl,
                         **({} if guided else {"ignore_eos": True})},
        sampling_options={"temperature": 0.0},
        eos_token_ids=[2] if guided else [],
        lora_name=lora_name,
        guided=guided,
        request_id=rid,
    ).to_dict()
    toks = []
    async for item in eng.generate(req, Context()):
        data = item.get("data")
        if data:
            toks.extend(data.get("token_ids", ()))
            if started is not None:
                started.set()
    return toks


async def run_lora_sweep(args) -> dict:
    """N-adapter sweep over a smaller device pool (docs/multi_lora.md).
    Hot switches (adapter resident) are refcount bookkeeping — priced at
    ~0 — while cold switches pay ONE bounded host->device onboard (LRU
    evicting an unpinned resident). Serves a round-robin trace over every
    adapter, then microbenches acquire/release on the pool directly."""
    n, slots = args.lora_adapters, args.lora_slots
    eng = _mk_tiny_engine(mixed=True, n_adapters=n, slots=slots)
    import numpy as np

    rng = np.random.RandomState(7)
    served = 0
    # sequential round-robin: every adapter switch is a hot hit or ONE
    # cold page-in — concurrency beyond the pool is the pinned-full
    # refusal path, which test_mixed_fusion covers, not this sweep
    for rnd in range(2):
        for i in range(1, n + 1):
            r = await _tiny_one(
                eng, rng.randint(5, 200, size=16).tolist(),
                f"r{rnd}-ad{i}", 6, lora_name=f"ad{i}",
            )
            served += 1 if len(r) == 6 else 0
    pool = eng._lora_pool
    # hot switch: acquire/release a RESIDENT adapter (pure bookkeeping)
    resident = pool.known_names()[-1]
    pool.acquire(resident)
    pool.release(resident)
    t0 = time.perf_counter()
    for _ in range(200):
        pool.acquire(resident)
        pool.release(resident)
    hot_ms = (time.perf_counter() - t0) / 200 * 1000.0
    st = eng.stats()
    await eng.close()
    return {
        "adapters": n, "slots": slots, "served_streams": served,
        "expected_streams": 2 * n,
        "hot_acquire_ms": round(hot_ms, 4),
        "cold_onboard_ewma_ms": st.get("lora_pool_onboard_ewma_ms"),
        "lora_pool_hits": st["lora_pool_hits"],
        "lora_pool_misses": st["lora_pool_misses"],
        "lora_pool_evictions": st["lora_pool_evictions"],
        "lora_pool_refusals": st["lora_pool_refusals"],
    }


async def _blend_trace(eng, rounds: int = 2) -> dict:
    """Deterministic staggered blend: plain + lora + guided streams whose
    prefills land beside live decode lanes. Returns rid -> tokens."""
    import numpy as np

    rng = np.random.RandomState(0xB1E)
    out = {}

    async def tag(rid, coro):
        out[rid] = await coro

    for rnd in range(rounds):
        # fresh prompts each round (seeded -> identical across arms):
        # reuse would hand round 2 to the prefix cache instead of the
        # packer this smoke exists to exercise
        prompts = {
            "plain": rng.randint(5, 200, size=24).tolist(),
            "lora": rng.randint(5, 200, size=20).tolist(),
            "guided": rng.randint(5, 200, size=18).tolist(),
        }
        # the plain stream anchors a LONG decode; the lora and guided
        # arrivals are admitted only after the PREVIOUS stream's first
        # token (not a wall-clock stagger — post-warmup step times vary
        # too much for sleeps), so each prefill is guaranteed to land
        # beside a live decode lane
        p_started, l_started = asyncio.Event(), asyncio.Event()
        tasks = [asyncio.create_task(tag(
            f"p{rnd}", _tiny_one(eng, prompts["plain"], f"p{rnd}", 64,
                                 started=p_started)))]
        await p_started.wait()
        tasks.append(asyncio.create_task(tag(
            f"l{rnd}", _tiny_one(eng, prompts["lora"], f"l{rnd}", 12,
                                 lora_name="ad1", started=l_started))))
        await l_started.wait()
        tasks.append(asyncio.create_task(tag(
            f"g{rnd}", _tiny_one(
                eng, prompts["guided"], f"g{rnd}", 12,
                guided={"kind": "choice", "choices": ["yes", "no"]}))))
        await asyncio.gather(*tasks)
    return out


async def run_blend_smoke(args) -> dict:
    """CI gate for the fused blended dispatch (docs/ragged_attention.md):
    warm a mixed-dispatch engine, replay a staggered plain+lora+guided
    trace, and require (a) every stream byte-identical to the split
    reference (the mixed_dispatch=False engine — the DYN_MIXED_DISPATCH=0
    arm), (b) mixed_coverage_frac >= the gate over the replay's
    mixed-opportunity steps, (c) zero post-warmup compiles."""
    eng = _mk_tiny_engine(mixed=True, n_adapters=2)
    await eng.warmup()
    warm = eng.stats()
    fused = await _blend_trace(eng)
    st = eng.stats()
    await eng.close()

    split_eng = _mk_tiny_engine(mixed=False, n_adapters=2)
    split = await _blend_trace(split_eng)
    await split_eng.close()

    mixed_d = st["mixed_steps"] - warm["mixed_steps"]
    split_d = st["split_steps"] - warm["split_steps"]
    coverage = mixed_d / max(mixed_d + split_d, 1)
    mismatched = sorted(
        rid for rid in fused
        if fused[rid] != split.get(rid)
    )
    return {
        "streams": len(fused),
        "byte_identical": not mismatched,
        "mismatched_streams": mismatched,
        "replay_mixed_steps": mixed_d,
        "replay_split_steps": split_d,
        "replay_coverage_frac": round(coverage, 4),
        "mixed_rows": {
            k: st[f"mixed_rows_{k}"] - warm[f"mixed_rows_{k}"]
            for k in ("plain", "guided", "spec", "lora")
        },
        "post_warmup_compiles": st["post_warmup_compiles"],
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--streams", type=int, default=8,
                    help="concurrent SSE streams (acceptance: batch >= 8)")
    ap.add_argument("--osl", type=int, default=96, help="tokens per stream")
    ap.add_argument("--workers", type=int, default=1, help="mocker workers")
    ap.add_argument("--speedup", type=float, default=100.0,
                    help="mocker speedup_ratio (higher = engine further "
                    "from being the bottleneck)")
    ap.add_argument("--coalesce-ms", type=float, default=3.0,
                    help="DYN_STREAM_COALESCE_MS for the workers (0 = "
                    "measure the pure ready-drain path)")
    ap.add_argument("--frontends", type=int, default=1,
                    help="stateless frontend replicas on the shared "
                    "discovery plane; client streams split round-robin "
                    "(docs/frontend_scaleout.md)")
    ap.add_argument("--fleet", action="store_true",
                    help="sweep 1→2→4 frontends at this stream count and "
                    "report the tok/s scaling ratios")
    ap.add_argument("--codec-ab", action="store_true",
                    help="A/B the ENC_TOK binary token wire path against "
                    "the msgpack arm (tok/s + frontend CPU µs/tok) and "
                    "run the pinned-id SSE byte-identity check")
    ap.add_argument("--fleet-smoke", action="store_true",
                    help="CI gate: 2 frontends must reach --fleet-min-ratio "
                    "x the 1-frontend tok/s at >=32 streams, and the "
                    "binary-codec arm must be byte-identical to msgpack")
    ap.add_argument("--fleet-min-ratio", type=float, default=1.6,
                    help="tok/s ratio floor for the 2-frontend smoke arm")
    ap.add_argument("--fleet-min-cores", type=int, default=6,
                    help="gate the fleet tok/s ratio only on hosts with at "
                    "least this many cores (below it the 4-process arm is "
                    "core-bound and the ratio measures contention, not "
                    "scale-out; correctness still gates)")
    ap.add_argument("--codec-min-drop", type=float, default=0.25,
                    help="--codec-ab gate: minimum wire-path per-token "
                    "frontend CPU drop on the binary arm (isolated "
                    "decode+merge measurement, medians of interleaved "
                    "pairs)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: exit 1 below --min-tok-s or if streams "
                    "averaged <= 1 token per frame")
    ap.add_argument("--min-tok-s", type=float, default=300.0,
                    help="generous non-regression floor for --smoke")
    # SLA-attainment smoke (engine/scheduler/): the same load twice —
    # workers under DYN_SCHED_POLICY=fifo then =sla — gating that the sla
    # policy holds TTFT p99 under a generous floor without giving up
    # throughput (catches deferral runaway / EDF starvation regressions)
    ap.add_argument("--sla-smoke", action="store_true",
                    help="CI gate: run fifo and sla arms; exit 1 if the "
                    "sla arm's TTFT p99 exceeds --sla-ttft-p99-floor or "
                    "its tok/s drops below --sla-tok-frac of the fifo arm")
    ap.add_argument("--sla-ttft-ms", type=float, default=1500.0,
                    help="DYN_SLA_TTFT_MS for the sla arm")
    ap.add_argument("--sla-itl-ms", type=float, default=50.0,
                    help="DYN_SLA_ITL_MS for the sla arm")
    ap.add_argument("--sla-ttft-p99-floor", type=float, default=3.0,
                    help="generous TTFT p99 ceiling (seconds) for the sla "
                    "arm")
    ap.add_argument("--sla-tok-frac", type=float, default=0.85,
                    help="sla arm tok/s must stay above this fraction of "
                    "the fifo arm")
    # overload smoke (dynogate, docs/overload.md): offered load ramps to
    # ~10x a deliberately tiny fleet's capacity; gate on goodput retention,
    # clean 429s with Retry-After, and zero mid-stream kills
    ap.add_argument("--overload-smoke", action="store_true",
                    help="CI gate: at-capacity arm then a ~10x burst with "
                    "the admission gate live; exit 1 if goodput retention "
                    "drops below --overload-retention, any served stream "
                    "is killed mid-flight, or no 429s were issued")
    ap.add_argument("--overload-retention", type=float, default=0.8,
                    help="surge goodput must stay above this fraction of "
                    "the at-capacity arm's")
    ap.add_argument("--overload-ttft-ms", type=float, default=1000.0,
                    help="DYN_GATE_TTFT_MS for the overload arm (the "
                    "admission ceiling at headroom 1.0)")
    ap.add_argument("--overload-slo-ms", type=float, default=2000.0,
                    help="TTFT SLO for the goodput (attained tok/s) metric")
    # compile smoke (dynocomp runtime closure, docs/compilation.md):
    # replay a trace against a warmed in-process engine; gate on the
    # per-surface compile counters showing zero post-warmup recompiles
    ap.add_argument("--blend-smoke", action="store_true",
                    help="CI gate: replay a staggered plain+lora+guided "
                    "trace on a warmed mixed-dispatch engine; exit 1 "
                    "unless every stream is byte-identical to the "
                    "mixed_dispatch=False reference, replay coverage >= "
                    "--blend-min-coverage, and zero post-warmup compiles")
    ap.add_argument("--blend-min-coverage", type=float, default=0.9,
                    help="minimum fused fraction of the replay's "
                    "mixed-opportunity steps")
    ap.add_argument("--lora-sweep", action="store_true",
                    help="N-adapter sweep over a smaller device pool: "
                    "hot switches ~0 (refcount only), cold switches one "
                    "bounded onboard; exit 1 on refusals, lost streams, "
                    "or a hot switch above --lora-hot-ms")
    ap.add_argument("--lora-adapters", type=int, default=8,
                    help="roster size for --lora-sweep")
    ap.add_argument("--lora-slots", type=int, default=3,
                    help="device pool slots for --lora-sweep (< adapters "
                    "so the sweep actually pages)")
    ap.add_argument("--lora-hot-ms", type=float, default=2.0,
                    help="hot acquire/release ceiling (ms)")
    ap.add_argument("--compile-smoke", action="store_true",
                    help="CI gate: warm an in-process JaxEngine, replay "
                    "a trace across every prefill bucket (lone arrivals, "
                    "cap bursts, mid-decode admissions); exit 1 if "
                    "stats()['post_warmup_compiles'] != 0 or warmup "
                    "compiled nothing")
    args = ap.parse_args()

    if args.blend_smoke:
        out = asyncio.run(run_blend_smoke(args))
        print(json.dumps(out, indent=2))
        ok = True
        if not out["byte_identical"]:
            print(f"BLEND SMOKE FAIL: fused streams diverged from the "
                  f"split reference: {out['mismatched_streams']} "
                  "(docs/ragged_attention.md parity contract)",
                  file=sys.stderr)
            ok = False
        if out["replay_coverage_frac"] < args.blend_min_coverage:
            print(f"BLEND SMOKE FAIL: replay coverage "
                  f"{out['replay_coverage_frac']} < "
                  f"{args.blend_min_coverage} (mixed-opportunity steps "
                  "falling back to the split path)", file=sys.stderr)
            ok = False
        if out["post_warmup_compiles"] != 0:
            print(f"BLEND SMOKE FAIL: {out['post_warmup_compiles']} XLA "
                  "program(s) compiled after warmup on the blended "
                  "replay (warmup missed a fused variant)",
                  file=sys.stderr)
            ok = False
        if not (out["mixed_rows"]["guided"] and out["mixed_rows"]["lora"]):
            print("BLEND SMOKE FAIL: replay fused no guided/lora rows "
                  "(trace no longer exercises the blend)", file=sys.stderr)
            ok = False
        sys.exit(0 if ok else 1)

    if args.lora_sweep:
        out = asyncio.run(run_lora_sweep(args))
        print(json.dumps(out, indent=2))
        ok = True
        if out["served_streams"] != out["expected_streams"]:
            print(f"LORA SWEEP FAIL: {out['served_streams']}/"
                  f"{out['expected_streams']} streams served",
                  file=sys.stderr)
            ok = False
        if out["lora_pool_refusals"]:
            print(f"LORA SWEEP FAIL: {out['lora_pool_refusals']} pool "
                  "refusals on an unpinned sweep", file=sys.stderr)
            ok = False
        if out["hot_acquire_ms"] > args.lora_hot_ms:
            print(f"LORA SWEEP FAIL: hot acquire {out['hot_acquire_ms']}"
                  f"ms > {args.lora_hot_ms}ms (hot switch must be "
                  "bookkeeping only)", file=sys.stderr)
            ok = False
        if out["lora_pool_evictions"] < 1:
            print("LORA SWEEP FAIL: sweep never paged (roster fits the "
                  "pool — raise --lora-adapters or shrink --lora-slots)",
                  file=sys.stderr)
            ok = False
        sys.exit(0 if ok else 1)

    if args.compile_smoke:
        out = asyncio.run(run_compile_smoke(args))
        print(json.dumps(out, indent=2))
        ok = True
        if out["post_warmup_compiles"] != 0:
            print(f"COMPILE SMOKE FAIL: {out['post_warmup_compiles']} XLA "
                  "program(s) compiled after warmup — a dispatch shape "
                  "leaked past the bucketing helpers or warmup missed a "
                  "variant (docs/compilation.md)", file=sys.stderr)
            ok = False
        if out["compiled_variants_after_warmup"] <= 0:
            print("COMPILE SMOKE FAIL: warmup compiled no surfaces "
                  "(compile-counter plumbing is broken)", file=sys.stderr)
            ok = False
        if out["replayed_tokens"] <= 0:
            print("COMPILE SMOKE FAIL: replay streamed no tokens",
                  file=sys.stderr)
            ok = False
        sys.exit(0 if ok else 1)

    if args.codec_ab:
        import copy

        micro = asyncio.run(run_codec_micro())
        a = copy.copy(args)
        binary = asyncio.run(run_bench(a, {"DYN_WIRE_BINARY_TOKENS": "1"}))
        msgpack = asyncio.run(run_bench(a, {"DYN_WIRE_BINARY_TOKENS": "0"}))
        drop = None
        if binary["frontend_cpu_us_per_tok"] and msgpack["frontend_cpu_us_per_tok"]:
            drop = round(
                1.0 - binary["frontend_cpu_us_per_tok"]
                / msgpack["frontend_cpu_us_per_tok"], 3,
            )
        print(json.dumps({
            "wire_path_micro": micro,
            "binary": binary, "msgpack": msgpack,
            "full_stack_frontend_cpu_drop": drop,
        }, indent=2))
        ok = check_codec_identity()
        if (micro["drop"] or 0) < args.codec_min_drop:
            print(f"CODEC AB FAIL: wire-path µs/tok drop {micro['drop']} < "
                  f"{args.codec_min_drop}", file=sys.stderr)
            ok = False
        sys.exit(0 if ok else 1)

    if args.fleet:
        import copy

        out = {}
        for n in (1, 2, 4):
            a = copy.copy(args)
            a.frontends = n
            out[f"fe{n}"] = asyncio.run(run_bench(a))
        base = out["fe1"]["tok_s"] or 1e-9
        out["ratio_2x"] = round((out["fe2"]["tok_s"] or 0) / base, 2)
        out["ratio_4x"] = round((out["fe4"]["tok_s"] or 0) / base, 2)
        print(json.dumps(out, indent=2))
        sys.exit(0)

    if args.fleet_smoke:
        import copy

        ok = check_codec_identity()
        micro = asyncio.run(run_codec_micro(pairs=3))
        print(json.dumps({"wire_path_micro": micro}, indent=2))
        if (micro["drop"] or 0) < args.codec_min_drop:
            print(f"FLEET SMOKE FAIL: wire-path µs/tok drop {micro['drop']} "
                  f"< {args.codec_min_drop}", file=sys.stderr)
            ok = False

        def _pair():
            a1 = copy.copy(args)
            a1.streams = max(args.streams, 32)
            a1.frontends = 1
            one = asyncio.run(run_bench(a1))
            a2 = copy.copy(a1)
            a2.frontends = 2
            two = asyncio.run(run_bench(a2))
            return one, two

        # the tok/s ratio only measures SCALE-OUT where spare cores exist:
        # 2 frontends + mocker + client need ~4 busy cores, so on smaller
        # hosts (2-core dev boxes, shared CI runners) the fleet arm gates
        # CORRECTNESS (every stream completes through either replica) and
        # reports the ratio; the scaling claim rides the bench_watchdog
        # engine_fleet hardware phase (BENCH_NOTES_r10.md)
        gate_ratio = (os.cpu_count() or 1) >= args.fleet_min_cores
        one, two = _pair()
        ratio = (two["tok_s"] or 0) / max(one["tok_s"] or 1e-9, 1e-9)
        if gate_ratio and ratio < args.fleet_min_ratio:
            # sequential arms race ambient host load (the sla-smoke rule):
            # retry once and keep the better pair; a real scale-out
            # regression fails both rounds
            print(f"fleet ratio {ratio:.2f} below gate; retrying once "
                  "(ambient-load protection)", file=sys.stderr)
            one2, two2 = _pair()
            r2 = (two2["tok_s"] or 0) / max(one2["tok_s"] or 1e-9, 1e-9)
            if r2 > ratio:
                one, two, ratio = one2, two2, r2
        print(json.dumps({
            "one_frontend": one, "two_frontends": two,
            "ratio": round(ratio, 2),
            "ratio_gated": gate_ratio,
        }, indent=2))
        expect = max(args.streams, 32) * args.osl
        for name, arm in (("one", one), ("two", two)):
            if arm["total_tokens"] != expect:
                print(f"FLEET SMOKE FAIL: {name}-frontend arm streamed "
                      f"{arm['total_tokens']} tokens, expected {expect} "
                      "(lost/truncated streams)", file=sys.stderr)
                ok = False
        if gate_ratio and ratio < args.fleet_min_ratio:
            print(f"FLEET SMOKE FAIL: 2-frontend tok/s ratio {ratio:.2f} < "
                  f"{args.fleet_min_ratio}", file=sys.stderr)
            ok = False
        sys.exit(0 if ok else 1)

    if args.overload_smoke:
        out = asyncio.run(run_overload_bench(args))
        print(json.dumps(out, indent=2))
        ok = True
        if out["surge_rejected"] < 10:
            print(f"OVERLOAD SMOKE FAIL: only {out['surge_rejected']} "
                  "rejections at ~10x capacity (gate not engaging)",
                  file=sys.stderr)
            ok = False
        if out["rejections_with_retry_after"] != out["surge_rejected"]:
            print("OVERLOAD SMOKE FAIL: rejections missing Retry-After",
                  file=sys.stderr)
            ok = False
        if out["mid_stream_kills"]:
            print(f"OVERLOAD SMOKE FAIL: {out['mid_stream_kills']} served "
                  f"streams truncated/killed: {out['kill_detail']}",
                  file=sys.stderr)
            ok = False
        if (out["goodput_retention"] or 0) < args.overload_retention:
            print(f"OVERLOAD SMOKE FAIL: goodput retention "
                  f"{out['goodput_retention']} < {args.overload_retention}",
                  file=sys.stderr)
            ok = False
        sys.exit(0 if ok else 1)

    if args.sla_smoke:
        def _arms():
            fifo = asyncio.run(run_bench(args, {"DYN_SCHED_POLICY": "fifo"}))
            sla = asyncio.run(run_bench(args, {
                "DYN_SCHED_POLICY": "sla",
                "DYN_SLA_TTFT_MS": str(args.sla_ttft_ms),
                "DYN_SLA_ITL_MS": str(args.sla_itl_ms),
            }))
            return fifo, sla

        def _ratio(fifo, sla):
            return (sla["tok_s"] or 0) / max(fifo["tok_s"] or 1e-9, 1e-9)

        fifo, sla = _arms()
        if _ratio(fifo, sla) < args.sla_tok_frac:
            # the arms run sequentially, so a noisy ambient-load window
            # during one arm skews the ratio — retry once and keep the
            # better pair; a real policy regression fails both rounds
            print("sla/fifo tok-s ratio below gate; retrying once "
                  "(ambient-load protection)", file=sys.stderr)
            fifo2, sla2 = _arms()
            if _ratio(fifo2, sla2) > _ratio(fifo, sla):
                fifo, sla = fifo2, sla2
        print(json.dumps({"fifo": fifo, "sla": sla}, indent=2))
        ok = True
        if (sla["ttft_p99_s"] or 1e9) > args.sla_ttft_p99_floor:
            print(
                f"SLA SMOKE FAIL: sla TTFT p99 {sla['ttft_p99_s']}s > "
                f"floor {args.sla_ttft_p99_floor}s", file=sys.stderr,
            )
            ok = False
        if (sla["tok_s"] or 0) < args.sla_tok_frac * (fifo["tok_s"] or 0):
            print(
                f"SLA SMOKE FAIL: sla {sla['tok_s']} tok/s < "
                f"{args.sla_tok_frac} x fifo {fifo['tok_s']} tok/s",
                file=sys.stderr,
            )
            ok = False
        sys.exit(0 if ok else 1)

    out = asyncio.run(run_bench(args))
    print(json.dumps(out, indent=2))
    if args.smoke:
        ok = True
        if (out["tok_s"] or 0) < args.min_tok_s:
            print(f"SMOKE FAIL: {out['tok_s']} tok/s < floor {args.min_tok_s}",
                  file=sys.stderr)
            ok = False
        tpf = out["frontend_tokens_per_frame"] or out["tokens_per_sse_event"] or 0
        if tpf <= 1.0:
            print(f"SMOKE FAIL: tokens-per-frame mean {tpf} <= 1 "
                  "(token path not batching)", file=sys.stderr)
            ok = False
        sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
