{{- define "dynamo.discoveryEndpoint" -}}
{{ .Release.Name }}-discovery:{{ .Values.discovery.port }}
{{- end -}}

{{- define "dynamo.workerEnv" -}}
- name: DYN_DISCOVERY_ENDPOINT
  value: {{ include "dynamo.discoveryEndpoint" . | quote }}
{{- end -}}
