"""KVBM tier-pipeline benchmark: mooncake-trace replay against the engine.

Measures what ISSUE 10 changed (docs/kvbm.md): batched per-step offload
gathers vs the seed's per-commit inline offload, device-executor time
stolen by KVBM, G1/G2/G3 hit rates on a prefix-heavy trace, and
onboard-hit vs recompute TTFT on repeated prefixes.

Method: a seeded mooncake-style trace (bench_e2e.synthesize_mooncake_trace
— radix-tree prefix structure + bursty session arrivals) is replayed
straight into a JaxEngine (no serving plane; this isolates the KV data
path) in two passes per arm:

  pass 1 (cold)  — tiers empty; measures steady-state serving + offload
  pass 2 (warm)  — the DEVICE prefix cache is cleared between passes, the
                   tiers are not: with KVBM the repeated prefixes onboard
                   from G2/G3, without it they recompute. Warm-pass TTFT
                   is the onboard-vs-recompute comparison.

Arms:
  off       — KVBM disabled (the recompute baseline)
  pipeline  — KVBM on, batched offload pipeline (DYN_KVBM_PIPELINE=1)
  inline    — KVBM on, seed-shaped per-commit inline offload
              (DYN_KVBM_PIPELINE=0); the before/after arm (skipped in
              --smoke to keep the CI gate fast)

Usage:
  python bench_kv_cache.py                 # full CPU report (3 arms)
  python bench_kv_cache.py --smoke         # CI gate (2 arms, floors)
  python bench_kv_cache.py --quantize int8 # hardware phase (bench_watchdog)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import List, Optional

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

from bench_e2e import load_mooncake_trace, synthesize_mooncake_trace  # noqa: E402


@dataclass
class ArmResult:
    name: str
    tokens: int = 0
    wall_s: float = 0.0
    ttft_cold_ms: List[float] = field(default_factory=list)
    ttft_warm_ms: List[float] = field(default_factory=list)
    stats: dict = field(default_factory=dict)

    @property
    def tok_s(self) -> float:
        return self.tokens / self.wall_s if self.wall_s > 0 else 0.0


def _trimmed_mean(xs: List[float]) -> float:
    """10%-trimmed mean: keeps the onboard/pull/recompute path cost
    visible (a p50 would land on a trivial G1-hit request) while
    shedding the GC/allocator spikes a busy host injects into a few
    samples per pass. Used for BOTH sides of the fabric gate's ratio —
    one definition, or the statistic silently diverges between arms."""
    xs = sorted(xs)
    k = max(len(xs) // 10, 1) if len(xs) > 4 else 0
    xs = xs[k: len(xs) - k] if k else xs
    return sum(xs) / max(len(xs), 1)


def _pct(xs: List[float], p: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(int(len(xs) * p), len(xs) - 1)]


def _make_engine(args, kvbm: bool, disk_dir: Optional[str]):
    from dynamo_tpu.engine import EngineConfig, JaxEngine

    cfg = EngineConfig(
        model=args.model,
        max_num_seqs=args.max_num_seqs,
        page_size=args.page_size,
        num_pages=args.num_pages,
        max_model_len=1024,
        prefill_buckets=(64, 128, 256),
        max_prefill_chunk=256,
        quantize=args.quantize,
        kvbm_host_blocks=args.host_blocks if kvbm else 0,
        kvbm_disk_blocks=args.disk_blocks if kvbm else 0,
        kvbm_disk_path=(
            disk_dir if kvbm and args.disk_blocks > 0 else None
        ),
    )
    return JaxEngine(cfg)


async def _replay(eng, trace, speedup: float, ttft_out: List[float]) -> int:
    """Paced replay of the trace; returns generated-token count and
    appends per-request TTFT (ms, request-relative) to ttft_out."""
    from dynamo_tpu.llm.protocols import PreprocessedRequest
    from dynamo_tpu.runtime.engine import Context

    total = 0
    t0 = time.perf_counter()

    async def one(req_i, row):
        nonlocal total
        delay = row.at / speedup - (time.perf_counter() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        start = time.perf_counter()
        req = PreprocessedRequest(
            token_ids=row.token_ids,
            stop_conditions={"max_tokens": row.osl, "ignore_eos": True},
            request_id=f"r{req_i}",
        ).to_dict()
        first = None
        async for item in eng.generate(req, Context()):
            data = item.get("data")
            if data and data.get("token_ids"):
                if first is None:
                    first = time.perf_counter()
                total += len(data["token_ids"])
        if first is not None:
            ttft_out.append((first - start) * 1000.0)

    await asyncio.gather(*[one(i, row) for i, row in enumerate(trace)])
    return total


async def _replay_serial(eng, trace, ttft_out: List[float]) -> int:
    """Closed-loop serial replay: one request at a time, no pacing — the
    per-request TTFT then measures the PATH cost (onboard / peer pull /
    recompute) without queueing noise, which is what the fabric gate
    compares. Paced replays measure the loaded regime; this measures the
    mechanism."""
    from dynamo_tpu.llm.protocols import PreprocessedRequest
    from dynamo_tpu.runtime.engine import Context

    total = 0
    for i, row in enumerate(trace):
        start = time.perf_counter()
        req = PreprocessedRequest(
            token_ids=row.token_ids,
            stop_conditions={"max_tokens": row.osl, "ignore_eos": True},
            request_id=f"s{i}",
        ).to_dict()
        first = None
        async for item in eng.generate(req, Context()):
            data = item.get("data")
            if data and data.get("token_ids"):
                if first is None:
                    first = time.perf_counter()
                total += len(data["token_ids"])
        if first is not None:
            ttft_out.append((first - start) * 1000.0)
    return total


async def _drain_offloads(eng):
    if eng.kvbm is None:
        return
    eng.kvbm.flush_step()
    for _ in range(1000):
        if eng.kvbm.pending_offloads() == 0:
            return
        await asyncio.sleep(0.005)


def run_arm(name: str, args, trace, kvbm: bool, pipelined: bool) -> ArmResult:
    prev = os.environ.get("DYN_KVBM_PIPELINE")
    os.environ["DYN_KVBM_PIPELINE"] = "1" if pipelined else "0"
    res = ArmResult(name=name)
    tmp = None
    try:
        disk_dir = None
        if kvbm and args.disk_blocks > 0:
            tmp = tempfile.TemporaryDirectory(prefix="bench_kv_g3_")
            disk_dir = tmp.name
        eng = _make_engine(args, kvbm, disk_dir)

        async def main():
            t0 = time.perf_counter()
            res.tokens += await _replay(eng, trace, args.speedup, res.ttft_cold_ms)
            await _drain_offloads(eng)
            # clear the DEVICE prefix cache only: pass 2 must choose
            # between tier onboarding (kvbm arms) and recompute (off arm)
            eng.allocator.clear_cache()
            res.tokens += await _replay(eng, trace, args.speedup, res.ttft_warm_ms)
            await _drain_offloads(eng)
            res.wall_s = time.perf_counter() - t0
            res.stats = eng.stats()
            await eng.close()

        asyncio.run(main())
    finally:
        if prev is None:
            os.environ.pop("DYN_KVBM_PIPELINE", None)
        else:
            os.environ["DYN_KVBM_PIPELINE"] = prev
        if tmp is not None:
            tmp.cleanup()
    return res


def run_peer_arm(name: str, args, trace):
    """Cluster-KV-fabric arm: engine A replays the trace cold (populating
    its G2 tier + announcing on the mesh), then the PAIRED measurement —
    an A-B-A design: A replays warm (device cache cleared — the local-G2
    reference), a FRESH engine B — same discovery plane, empty device
    cache AND empty tiers — replays warm onboarding every repeated
    prefix from A's tiers over the KV data plane (peer pull), then A
    replays warm AGAIN. The peer pass is compared against the MEAN of
    the two flanking local passes: successive replays in one process
    phase slow down roughly linearly on a small shared host, and the
    A-B-A mean cancels that drift exactly where a single sequential
    pair just measures it. Returns (peer ArmResult, local-reference
    warm TTFT p50 ms = mean of the two local passes)."""
    import copy

    prev = os.environ.get("DYN_KVBM_PIPELINE")
    os.environ["DYN_KVBM_PIPELINE"] = "1"
    res = ArmResult(name=name)
    ref = {"mean": 0.0}
    args = copy.copy(args)
    args.disk_blocks = 0  # G2-only: isolate the peer-pull vs local-G2 gap
    try:

        async def main():
            from dynamo_tpu.kvbm import KvbmDistributed
            from dynamo_tpu.llm.kv_transfer import KvDataPlaneServer
            from dynamo_tpu.runtime import (
                DiscoveryServer,
                DistributedRuntime,
                RuntimeConfig,
            )

            server = DiscoveryServer(port=0)
            _, port = await server.start()
            cfg = RuntimeConfig(discovery_endpoint=f"127.0.0.1:{port}")
            drts, engines, dists, planes = [], [], [], []
            for _ in range(2):
                drt = await DistributedRuntime.create(cfg)
                eng = _make_engine(args, True, None)
                dp = KvDataPlaneServer()
                await dp.start()
                await dp.register(drt)
                dist = KvbmDistributed(
                    drt, eng.kvbm, dp, "bench", "kvbm", drt.instance_id
                )
                await dist.start()
                drts.append(drt)
                engines.append(eng)
                dists.append(dist)
                planes.append(dp)
            eng_a, eng_b = engines
            try:
                # B is a FRESH engine: drive its dispatch variants once so
                # the measured warm pass doesn't pay jit tracing the local
                # side (which reuses its cold-pass engine) never sees
                await eng_b.warmup()
                t0 = time.perf_counter()
                res.tokens += await _replay(
                    eng_a, trace, args.speedup, res.ttft_cold_ms
                )
                await _drain_offloads(eng_a)
                # wait for A's announcements to mirror into B's owner map
                for _ in range(400):
                    if len(dists[1]._owners) >= 1:
                        break
                    await asyncio.sleep(0.01)

                async def measure_local():
                    eng_a.allocator.clear_cache()
                    ttfts = []
                    res.tokens += await _replay_serial(eng_a, trace, ttfts)
                    return _trimmed_mean(ttfts)

                # throwaway passes: one-time shape compiles fire on each
                # engine's FIRST pass over the trace; pay them off-camera
                # on both sides, then reset B (device cache + tiers) so
                # the measured pass pulls from A again
                await measure_local()
                eng_b.allocator.clear_cache()
                await _replay_serial(eng_b, trace, [])
                eng_b.allocator.clear_cache()
                eng_b.kvbm.manager.clear()

                local_1 = await measure_local()
                res.tokens += await _replay_serial(
                    eng_b, trace, res.ttft_warm_ms
                )
                local_2 = await measure_local()
                ref["mean"] = (local_1 + local_2) / 2.0
                # in-phase serial recompute reference: B with device
                # cache, tiers, and the peer arm all cleared — nothing
                # left to onboard from, every prefix recomputes
                eng_b.kvbm.peer_pull = False
                eng_b.allocator.clear_cache()
                eng_b.kvbm.manager.clear()
                dists[1]._owners.clear()
                rec = []
                res.tokens += await _replay_serial(eng_b, trace, rec)
                ref["recompute_mean"] = _trimmed_mean(rec)
                res.wall_s = time.perf_counter() - t0
                res.stats = eng_b.stats()
            finally:
                for eng in engines:
                    await eng.close()
                for d in dists:
                    await d.close()
                for p in planes:
                    await p.close()
                for drt in drts:
                    await drt.close()
                await server.stop()

        asyncio.run(main())
    finally:
        if prev is None:
            os.environ.pop("DYN_KVBM_PIPELINE", None)
        else:
            os.environ["DYN_KVBM_PIPELINE"] = prev
    return res, ref


def summarize(res: ArmResult) -> dict:
    st = res.stats
    steps = sum(
        v for k, v in st.items()
        if k.startswith("dispatch_") and k.endswith("_count")
        and any(t in k for t in ("prefill", "decode", "mixed"))
    )
    out = {
        "arm": res.name,
        "tok_s": round(res.tok_s, 1),
        "tokens": res.tokens,
        "wall_s": round(res.wall_s, 2),
        "ttft_cold_p50_ms": round(_pct(res.ttft_cold_ms, 0.50), 1),
        "ttft_warm_p50_ms": round(_pct(res.ttft_warm_ms, 0.50), 1),
        "ttft_warm_p95_ms": round(_pct(res.ttft_warm_ms, 0.95), 1),
        "engine_steps_approx": steps,
    }
    if st.get("kvbm_offload_commit_calls") is not None:
        gathers = st.get("kvbm_offload_gathers", 0)
        out.update({
            "offload_commit_calls": st["kvbm_offload_commit_calls"],
            "offload_gathers": gathers,
            "offload_gathers_per_commit": round(
                gathers / max(st["kvbm_offload_commit_calls"], 1), 3
            ),
            "kvbm_dev_ms_total": round(
                st.get("dispatch_kvbm_offload_s", 0.0) * 1000.0, 2
            ),
            "kvbm_dev_us_per_gather": round(
                st.get("dispatch_kvbm_offload_s", 0.0) * 1e6
                / max(st.get("dispatch_kvbm_offload_count", 0), 1), 1
            ),
            "offloaded_blocks": st.get("kvbm_offloaded_blocks", 0),
            "dropped_blocks": st.get("kvbm_offload_blocks_dropped", 0),
            "onboarded_blocks": st.get("kvbm_onboarded_blocks", 0),
            "onboard_recompute_fallbacks": st.get(
                "kvbm_onboard_recompute_fallbacks", 0
            ),
            "g1_hit_blocks": st.get("kvbm_g1_hit_blocks", 0),
            "g1_miss_blocks": st.get("kvbm_g1_miss_blocks", 0),
            "g2_hits": st.get("kvbm_host_hits", 0),
            "g3_hits": st.get("kvbm_disk_hits", 0),
            "g2_hit_rate_vs_g1_miss": round(
                st.get("kvbm_onboarded_blocks", 0)
                / max(st.get("kvbm_g1_miss_blocks", 0), 1), 3
            ),
            "onboard_mean_ms": round(
                st.get("kvbm_onboard_ms_sum", 0.0)
                / max(st.get("kvbm_onboard_count", 0), 1), 2
            ),
        })
        if st.get("kvbm_remote_onboards") is not None:
            out.update({
                "peer_onboards": st.get("kvbm_remote_onboards", 0),
                "peer_blocks_pulled": st.get("kvbm_remote_blocks_pulled", 0),
                "peer_bytes_pulled": st.get("kvbm_peer_bytes_pulled", 0),
                "peer_pull_failures": st.get("kvbm_peer_pull_failures", 0),
                "peer_pull_mean_ms": round(
                    st.get("kvbm_peer_pull_ms_sum", 0.0)
                    / max(st.get("kvbm_remote_onboards", 0), 1), 2
                ),
                "onboard_src_local": st.get("kvbm_onboard_src_local_blocks", 0),
                "onboard_src_peer": st.get("kvbm_onboard_src_peer_blocks", 0),
                "onboard_src_recompute": st.get(
                    "kvbm_onboard_src_recompute_blocks", 0
                ),
            })
    return out


def run_multi_worker(args, trace):
    """Cluster-KV-fabric report + gate. Each round runs a recompute
    reference (off arm) plus the PAIRED peer arm, which measures the
    cross-worker-peer and local-G2 warm passes back-to-back in one
    process phase (run_peer_arm docstring) — the gate statistic is the
    MEDIAN of the per-round peer/local ratios, which cancels the ambient
    load a shared CI host smears over sequential single arms. Recompute
    comparisons use best-of-rounds (the timeit statistic: ambient load
    only ever ADDS time)."""
    import copy

    args = copy.copy(args)
    args.disk_blocks = 0  # all arms G2-only, matching the peer arm
    rounds = 3
    warm_p50 = {"recompute": [], "local": [], "peer": []}
    ratios = []
    last = {}
    for r in range(rounds):
        peer, ref = run_peer_arm("peer", args, trace)
        peer_mean = _trimmed_mean(peer.ttft_warm_ms)
        warm_p50["peer"].append(peer_mean)
        warm_p50["local"].append(ref["mean"])
        warm_p50["recompute"].append(ref["recompute_mean"])
        ratios.append(peer_mean / max(ref["mean"], 1e-9))
        last["peer"] = peer
    best = {k: min(v) for k, v in warm_p50.items()}
    med = {k: sorted(v)[rounds // 2] for k, v in warm_p50.items()}
    ratio = sorted(ratios)[rounds // 2]
    peer_sum = summarize(last["peer"])
    report = {
        "mode": "multi-worker",
        "peer_vs_local_ratio_per_round": [round(x, 3) for x in ratios],
        "peer_vs_local_ratio_median": round(ratio, 3),
        "ttft_warm_mean_ms_best": {k: round(v, 1) for k, v in best.items()},
        "ttft_warm_mean_ms_median": {k: round(v, 1) for k, v in med.items()},
        "peer_vs_recompute_ratio": round(
            best["peer"] / max(best["recompute"], 1e-9), 3
        ),
        "local_vs_recompute_ratio": round(
            best["local"] / max(best["recompute"], 1e-9), 3
        ),
        "peer_arm": peer_sum,
    }
    print(json.dumps(report))
    failures = []
    if peer_sum.get("peer_blocks_pulled", 0) <= 0:
        failures.append("peer arm never pulled a block over the data plane")
    if ratio > args.max_peer_ttft_ratio:
        failures.append(
            f"peer warm TTFT {ratio:.3f}x local-G2 exceeds "
            f"{args.max_peer_ttft_ratio}x (median of {rounds} paired rounds)"
        )
    if failures:
        print("KV-FABRIC MULTI-WORKER FAILED:")
        for f in failures:
            print(f"  - {f}")
        sys.exit(1)
    print(
        f"KV-FABRIC MULTI-WORKER OK: peer/local-G2 ratio {ratio:.2f}x "
        f"(per-round {['%.2f' % x for x in ratios]}); best warm p50 "
        f"peer {best['peer']:.0f}ms, local {best['local']:.0f}ms, "
        f"recompute {best['recompute']:.0f}ms"
    )


async def _replay_serial_streams(eng, trace, prefix="q"):
    """Closed-loop serial replay collecting each request's full greedy
    stream AND per-token logprobs — the quality-guard inputs of the
    --kv-quant gate (token spot check + per-step logit MSE)."""
    from dynamo_tpu.llm.protocols import PreprocessedRequest
    from dynamo_tpu.runtime.engine import Context

    out = []
    for i, row in enumerate(trace):
        req = PreprocessedRequest(
            token_ids=row.token_ids,
            stop_conditions={"max_tokens": row.osl, "ignore_eos": True},
            sampling_options={"logprobs": True},
            request_id=f"{prefix}{i}",
        ).to_dict()
        toks, lps = [], []
        async for item in eng.generate(req, Context()):
            data = item.get("data")
            if data and data.get("token_ids"):
                toks.extend(data["token_ids"])
                lps.extend(data.get("log_probs") or [])
        out.append((toks, lps))
    return out


def _kvq_quality(fp_streams, q_streams):
    """Quality-guard statistics between the fp and quantized arms on the
    same greedy trace: token match rate over aligned steps (task-level
    spot check) and the per-step chosen-token logit MSE up to each
    request's first divergence (after a divergence the two arms walk
    different sequences, so later logits aren't comparable)."""
    agree = total = 0
    sq_sum = 0.0
    n_lp = 0
    per_step_sq = []
    for (ft, fl), (qt, ql) in zip(fp_streams, q_streams):
        n = min(len(ft), len(qt))
        total += n
        diverged = False
        for j in range(n):
            if ft[j] == qt[j]:
                agree += 1
            elif not diverged:
                diverged = True
            if not diverged and j < len(fl) and j < len(ql) \
                    and fl[j] is not None and ql[j] is not None:
                d = float(fl[j]) - float(ql[j])
                sq_sum += d * d
                per_step_sq.append(d * d)
                n_lp += 1
    return {
        "token_match_rate": round(agree / max(total, 1), 4),
        "logit_mse": round(sq_sum / max(n_lp, 1), 5),
        "logit_mse_p95": round(_pct(per_step_sq, 0.95), 5),
        "logit_samples": n_lp,
    }


def run_kv_quant(args, trace):
    """Quantized-KV density report + gate (--kv-quant int8|int4).

    Arms at a FIXED HBM page-count and FIXED G2 byte budget:
      fp    — kv_quant none, host_blocks = --host-blocks
      kvq   — kv_quant <mode>, host_blocks scaled so the tier holds the
              SAME BYTES (packed blocks are ~2x/4x smaller => ~2x/4x the
              blocks => higher hit rate on the same trace)

    Gates (the ISSUE 14 acceptance):
      * sessions-per-HBM-budget (measured pool allocation, incl. scales)
        >= --min-density-ratio x the fp arm
      * warm tier hit rate at fixed G2 bytes >= the fp arm's
      * quality guard: per-step logit MSE (chosen-token, pre-divergence)
        under --max-logit-mse AND token match rate over the greedy trace
        >= --min-token-match
      * none arm byte-identical: kv_quant="none" reproduces the
        DYN_KV_QUANT-unset streams token-for-token (quant off == seed)
    """
    from dynamo_tpu.models import llama
    from dynamo_tpu.ops.kv_quant import kv_page_bytes

    mode = args.kv_quant
    c = llama.LlamaConfig.tiny() if args.model == "tiny" else None
    from dynamo_tpu.engine.engine import _resolve_model

    c = c or _resolve_model(args.model)
    fp_page = 2 * c.num_layers * kv_page_bytes(
        args.page_size, c.num_kv_heads, c.head_dim, c.dtype, "none")
    q_page = 2 * c.num_layers * kv_page_bytes(
        args.page_size, c.num_kv_heads, c.head_dim, c.dtype, mode)
    host_bytes = args.host_blocks * fp_page
    q_host_blocks = max(host_bytes // q_page, 1)

    def arm(kv_quant, host_blocks, prefix):
        from dynamo_tpu.engine import EngineConfig, JaxEngine

        cfg = EngineConfig(
            model=args.model, max_num_seqs=args.max_num_seqs,
            page_size=args.page_size, num_pages=args.num_pages,
            max_model_len=1024, prefill_buckets=(64, 128, 256),
            max_prefill_chunk=256, quantize=args.quantize,
            kvbm_host_blocks=host_blocks, kv_quant=kv_quant,
        )
        eng = JaxEngine(cfg)
        res = {}

        async def main():
            streams_cold = await _replay_serial_streams(
                eng, trace, prefix + "c")
            await _drain_offloads(eng)
            eng.allocator.clear_cache()
            streams_warm = await _replay_serial_streams(
                eng, trace, prefix + "w")
            await _drain_offloads(eng)
            res["stats"] = eng.stats()
            res["cold"] = streams_cold
            res["warm"] = streams_warm
            await eng.close()

        asyncio.run(main())
        return res

    fp = arm("none", args.host_blocks, "f")
    kvq = arm(mode, int(q_host_blocks), "k")
    base = arm(None, args.host_blocks, "b")  # DYN_KV_QUANT-unset default

    # density: measured resident pool bytes at EQUAL page count -> how
    # many sessions a fixed HBM byte budget holds (pages/session from the
    # trace's mean prompt+output page footprint)
    pages_per_req = sum(
        (len(r.token_ids) + r.osl + args.page_size - 1) // args.page_size
        for r in trace
    ) / max(len(trace), 1)
    budget = 1 << 30  # a reference GiB of KV budget
    fp_bpp = fp["stats"]["kv_pool_bytes"] / (args.num_pages + 1)
    q_bpp = kvq["stats"]["kv_pool_bytes"] / (args.num_pages + 1)
    sessions = {
        "fp": (budget / fp_bpp) / pages_per_req,
        "kvq": (budget / q_bpp) / pages_per_req,
    }
    density_ratio = sessions["kvq"] / max(sessions["fp"], 1e-9)

    def hit_rate(st):
        return st.get("kvbm_onboarded_blocks", 0) / max(
            st.get("kvbm_g1_miss_blocks", 0), 1)

    fp_hit, q_hit = hit_rate(fp["stats"]), hit_rate(kvq["stats"])
    quality = _kvq_quality(fp["cold"], kvq["cold"])
    none_identical = [t for t, _ in fp["cold"]] == [t for t, _ in base["cold"]]

    report = {
        "mode": f"kv-quant-{mode}",
        "kv_bytes_per_page": {"fp": round(fp_bpp, 1), "kvq": round(q_bpp, 1)},
        "sessions_per_gib": {k: round(v, 1) for k, v in sessions.items()},
        "sessions_per_hbm_ratio": round(density_ratio, 3),
        "g2_budget_bytes": int(host_bytes),
        "g2_blocks": {"fp": args.host_blocks, "kvq": int(q_host_blocks)},
        "tier_hit_rate_warm": {"fp": round(fp_hit, 3), "kvq": round(q_hit, 3)},
        "quality": quality,
        "none_arm_byte_identical": none_identical,
    }
    print(json.dumps(report))
    failures = []
    if density_ratio < args.min_density_ratio:
        failures.append(
            f"sessions-per-HBM ratio {density_ratio:.2f} < "
            f"{args.min_density_ratio}")
    if q_hit < fp_hit:
        failures.append(
            f"tier hit rate DOWN at fixed G2 bytes: {q_hit:.3f} < {fp_hit:.3f}")
    if quality["logit_mse"] > args.max_logit_mse:
        failures.append(
            f"logit MSE {quality['logit_mse']} > {args.max_logit_mse} "
            "(quantization is buying wrong tokens)")
    if quality["token_match_rate"] < args.min_token_match:
        failures.append(
            f"token match rate {quality['token_match_rate']} < "
            f"{args.min_token_match}")
    if not none_identical:
        failures.append("kv_quant=none diverged from the unset default "
                        "(quant off must be the seed path, byte-identical)")
    if failures:
        print("KV-QUANT SMOKE FAILED:")
        for f in failures:
            print(f"  - {f}")
        sys.exit(1)
    print(
        f"KV-QUANT SMOKE OK ({mode}): {density_ratio:.2f}x sessions/HBM, "
        f"tier hit rate {fp_hit:.2f}->{q_hit:.2f} at fixed G2 bytes, "
        f"logit MSE {quality['logit_mse']}, token match "
        f"{quality['token_match_rate']}, none arm byte-identical"
    )


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", default="tiny")
    ap.add_argument("--quantize", default=None)
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--qps", type=float, default=8.0)
    ap.add_argument("--speedup", type=float, default=4.0,
                    help="trace time compression for CPU runs")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--num-pages", type=int, default=96)
    ap.add_argument("--max-num-seqs", type=int, default=4)
    ap.add_argument("--host-blocks", type=int, default=256)
    ap.add_argument("--disk-blocks", type=int, default=128)
    ap.add_argument("--osl", type=int, default=12)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--repeat", type=int, default=1,
                    help="run each arm N times, report the last (the "
                    "persistent XLA cache makes repeat runs compile-free, "
                    "so cross-arm timing comparisons become fair; CPU "
                    "first-run numbers are compile-dominated)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: 2 arms + hit-rate/throughput floors")
    ap.add_argument("--min-hit-rate", type=float, default=0.3,
                    help="--smoke floor on warm-pass tier hit rate")
    ap.add_argument("--min-tok-s-ratio", type=float, default=0.9,
                    help="--smoke floor on kvbm-on/kvbm-off tok/s")
    ap.add_argument("--multi-worker", action="store_true",
                    help="cluster KV fabric arm: two in-proc engines on "
                    "one discovery plane; cross-worker warm TTFT (peer "
                    "G2 pull) vs local-G2 vs recompute, medians of "
                    "interleaved arm triples; gates peer-hit count > 0 "
                    "and peer TTFT <= --max-peer-ttft-ratio x local-G2")
    ap.add_argument("--max-peer-ttft-ratio", type=float, default=1.3,
                    help="--multi-worker gate: peer warm-TTFT p50 ceiling "
                    "as a multiple of local-G2 warm-TTFT p50 (medians)")
    ap.add_argument("--kv-quant", choices=["int8", "int4"], default=None,
                    help="quantized-KV density arm + gate (run_kv_quant): "
                    "fp vs quantized engines at equal HBM pages and equal "
                    "G2 bytes — sessions-per-HBM-budget ratio, tier hit "
                    "rate, logit-MSE/token-match quality guard, and the "
                    "none-arm byte-identity check")
    ap.add_argument("--min-density-ratio", type=float, default=1.8,
                    help="--kv-quant floor on sessions-per-HBM-budget vs fp")
    ap.add_argument("--max-logit-mse", type=float, default=None,
                    help="--kv-quant ceiling on per-step chosen-token "
                    "logit MSE vs the fp arm (default: 0.02 int8, 0.5 "
                    "int4 — calibrated on the tiny CPU model)")
    ap.add_argument("--min-token-match", type=float, default=None,
                    help="--kv-quant floor on greedy token match rate vs "
                    "the fp arm (default: 0.9 int8, 0.7 int4 — the CPU "
                    "smoke's random-init tiny model is the WORST case: "
                    "its logits are near-uniform, so half-quant-step "
                    "noise flips argmax far more often than a trained "
                    "checkpoint's peaked logits would; the hardware "
                    "phase gates a real checkpoint tighter)")
    args = ap.parse_args()
    if args.max_logit_mse is None:
        args.max_logit_mse = {None: 0.02, "int8": 0.02, "int4": 0.5}[args.kv_quant]
    if args.min_token_match is None:
        args.min_token_match = {None: 0.9, "int8": 0.9, "int4": 0.7}[args.kv_quant]

    if args.smoke:
        args.requests = min(args.requests, 20)
        args.osl = min(args.osl, 8)

    # --multi-worker compares PATH costs (serial passes): deeper shared
    # chains and production-leaning pages make each onboard/pull move
    # enough bytes that the per-pull constant (serve round-trip)
    # amortizes the way real block sizes do — the default shallow trace
    # would measure loopback TCP setup, not the fabric
    if args.multi_worker:
        args.page_size = max(args.page_size, 32)
    depth, leaf_blocks = (12, 6) if args.multi_worker else (3, 2)
    rows = synthesize_mooncake_trace(
        args.requests, args.qps, args.page_size, seed=args.seed,
        n_roots=3, depth=depth, leaf_blocks=leaf_blocks, osl_mean=args.osl,
    )
    from dynamo_tpu.models import llama

    vocab = llama.LlamaConfig.tiny().vocab_size
    trace = load_mooncake_trace(
        rows, vocab=vocab, max_isl=512, max_osl=args.osl,
        block_size=args.page_size, seed=args.seed,
    )
    print(f"trace: {len(trace)} requests, "
          f"isl p50 {int(_pct([r.isl for r in trace], 0.5))}, "
          f"osl {args.osl}, prefix roots 3 x depth {depth}")

    if args.multi_worker:
        run_multi_worker(args, trace)
        return
    if args.kv_quant:
        if args.host_blocks == 256:
            # default the G2 byte budget to CAPACITY-CONSTRAINED on this
            # trace (the 256-block default holds the whole working set,
            # hiding the density win): at 24 fp blocks the fp arm
            # thrashes its LRU to a 0.0 warm hit rate while the quant
            # arm's 2x/4x blocks-per-byte holds the set at 0.5
            args.host_blocks = 24
        run_kv_quant(args, trace)
        return

    arms = [("off", False, True), ("pipeline", True, True)]
    if not args.smoke:
        arms.append(("inline", True, False))

    results = {}
    if args.smoke:
        # the tok/s floor compares two arms that cannot run at the same
        # instant — on a loaded CI host a single sequential pair races
        # ambient load (the exact flake the --sla-smoke retry fixed in
        # bench_serving_overhead). Interleave 3 pairs and compare MEDIANS.
        samples = {"off": [], "pipeline": []}
        last = {}
        for _ in range(3):
            for name, kvbm, pipelined in arms:
                res = run_arm(name, args, trace, kvbm, pipelined)
                samples[name].append(res.tok_s)
                last[name] = res
        for name in samples:
            results[name] = summarize(last[name])
            results[name]["tok_s_median"] = round(
                sorted(samples[name])[1], 1
            )
            print(json.dumps(results[name]))
    else:
        for name, kvbm, pipelined in arms:
            for _ in range(max(args.repeat, 1)):
                res = run_arm(name, args, trace, kvbm, pipelined)
            results[name] = summarize(res)
            print(json.dumps(results[name]))

    if args.smoke:
        off, pipe = results["off"], results["pipeline"]
        failures = []
        ratio = pipe["tok_s_median"] / max(off["tok_s_median"], 1e-9)
        if ratio < args.min_tok_s_ratio:
            failures.append(
                f"tok/s ratio {ratio:.3f} < {args.min_tok_s_ratio} "
                f"(kvbm must be near-free off the device executor)"
            )
        if pipe["g2_hit_rate_vs_g1_miss"] < args.min_hit_rate:
            failures.append(
                f"tier hit rate {pipe['g2_hit_rate_vs_g1_miss']} < "
                f"{args.min_hit_rate} on a prefix-heavy trace"
            )
        if pipe["offload_gathers"] > pipe["offload_commit_calls"]:
            failures.append("pipeline produced MORE gathers than commits")
        if pipe["onboarded_blocks"] <= 0:
            failures.append("warm pass never onboarded from the tiers")
        if failures:
            print("KV-CACHE SMOKE FAILED:")
            for f in failures:
                print(f"  - {f}")
            sys.exit(1)
        print(f"KV-CACHE SMOKE OK: tok/s ratio {ratio:.3f}, "
              f"hit rate {pipe['g2_hit_rate_vs_g1_miss']}, "
              f"{pipe['offload_gathers']} gathers / "
              f"{pipe['offload_commit_calls']} commits")
    else:
        inline, pipe = results.get("inline"), results["pipeline"]
        if inline:
            print(json.dumps({
                "comparison": "inline->pipeline",
                "kvbm_dev_ms_total": [
                    inline["kvbm_dev_ms_total"], pipe["kvbm_dev_ms_total"]
                ],
                "gathers": [inline["offload_gathers"], pipe["offload_gathers"]],
                "ttft_warm_p50_ms_off_vs_pipe": [
                    results["off"]["ttft_warm_p50_ms"], pipe["ttft_warm_p50_ms"]
                ],
            }))


if __name__ == "__main__":
    main()
