"""Headline benchmark: steady-state decode throughput of the JAX engine.

Runs on whatever `jax.devices()` provides (the real TPU chip under axon;
CPU with --smoke). Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "tok/s", "vs_baseline": N}

vs_baseline: the reference publishes no absolute end-to-end tables
(BASELINE.md); the closest per-accelerator number it documents is the SLA
profiler example decode rate of 51.22 tok/s/GPU at TP4 on H100-class
(docs/benchmarks/pre_deployment_profiling.md:56) => 204.9 tok/s per 4-GPU
worker. We report batched decode tok/s on ONE v5e chip divided by that
per-GPU figure so the ratio reads "v5e-chip decode throughput vs H100-GPU
decode throughput on the reference's own example".

Shapes follow the engine's production dispatch units (engine/engine.py):
  * prefill: ONE batched [B, isl] dispatch (all sequences together) with
    on-device first-token sampling; TTFT = a single-sequence dispatch plus
    the one host read that delivers the token.
  * decode: K-step fused blocks (lax.scan, sampling feeds the next step on
    device) — one host read per K*B tokens.

With --e2e the benchmark instead drives the FULL serving stack (HTTP
frontend + preprocessor + router + JAX worker) with a ShareGPT-style
trace at fixed QPS; see bench_e2e.py.
"""

import argparse
import json
import sys
import time

H100_DECODE_TOKS_PER_GPU = 51.22  # reference pre_deployment_profiling.md:56


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny model on CPU")
    ap.add_argument("--model", default=None)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--isl", type=int, default=128, help="input seq len")
    ap.add_argument("--osl", type=int, default=128, help="output seq len")
    ap.add_argument("--block", type=int, default=16, help="fused decode steps per dispatch")
    ap.add_argument("--steps", type=int, default=None, help="decode steps to time")
    ap.add_argument("--e2e", action="store_true", help="serve a trace through the full stack")
    args, extra = ap.parse_known_args()

    if args.e2e:
        from bench_e2e import main as e2e_main

        return e2e_main(extra + (["--smoke"] if args.smoke else []))

    if args.smoke:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        # axon sitecustomize imports jax at startup, freezing jax_platforms
        # before the env var applies — update the live config too
        if "jax" in sys.modules:
            import jax

            jax.config.update("jax_platforms", "cpu")
            # config.update is a silent no-op if a backend already
            # initialized; a "smoke" run must never hit the real TPU
            assert jax.devices()[0].platform == "cpu", (
                f"--smoke needs CPU but backend is {jax.devices()[0].platform}"
            )

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine.engine import _enable_compile_cache
    from dynamo_tpu.engine.kv_cache import alloc_kv_arrays
    from dynamo_tpu.engine.sampling import SamplingParams, sample
    from dynamo_tpu.models import llama

    _enable_compile_cache()
    model = args.model or ("tiny" if args.smoke else "llama3-3b")
    cfgs = {
        "tiny": llama.LlamaConfig.tiny,
        "llama3-3b": llama.LlamaConfig.llama3_2_3b,
        "llama3-8b": llama.LlamaConfig.llama3_8b,
    }
    cfg = cfgs[model]()

    B = args.batch
    PAGE = 64
    K = args.block
    max_len = args.isl + args.osl + K  # fused blocks may overshoot by < K
    pages_per_seq = (max_len + PAGE - 1) // PAGE
    num_pages = B * pages_per_seq + 1
    dev = jax.devices()[0]
    print(
        f"# bench: model={model} device={dev.platform} B={B} isl={args.isl} "
        f"osl={args.osl} block={K}",
        file=sys.stderr,
    )

    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    kv_k, kv_v = alloc_kv_arrays(
        cfg.num_layers, num_pages, PAGE, cfg.num_kv_heads, cfg.head_dim, cfg.dtype
    )

    # page tables: disjoint pages per slot (page 0 reserved scratch)
    pt = np.zeros((B, pages_per_seq), np.int32)
    for b in range(B):
        pt[b] = 1 + b * pages_per_seq + np.arange(pages_per_seq)
    pt = pt % num_pages
    page_tables = jnp.asarray(pt)

    # NOTE on timing: under the axon tunnel, block_until_ready() returns
    # before execution finishes — only a host value fetch actually syncs.
    # We therefore fetch a tiny scalar to fence each timed region.
    def fence(x):
        np.asarray(jax.device_get(x.ravel()[0]))

    # ---- batched prefill (one dispatch for the whole batch) ----
    def _prefill(params, kk, kv, toks, pos, tabs, cls, lis, samp, key):
        logits, kk, kv = llama.prefill_forward_batched(
            params, cfg, toks, pos, kk, kv, tabs, cls, lis
        )
        return sample(logits, samp, key), kk, kv

    prefill = jax.jit(_prefill, donate_argnums=(1, 2))

    rng = np.random.RandomState(0)
    all_toks = rng.randint(3, cfg.vocab_size - 1, size=(B, args.isl)).astype(np.int32)
    all_pos = np.tile(np.arange(args.isl, dtype=np.int32), (B, 1))
    ctx0 = jnp.zeros((B,), jnp.int32)
    last = jnp.full((B,), args.isl - 1, jnp.int32)
    samp = SamplingParams.full(B, temperature=0.0)
    samp1 = SamplingParams.full(1, temperature=0.0)
    key = jax.random.PRNGKey(7)

    # compile both variants before timing (first call pays XLA compile)
    first1, kv_k, kv_v = prefill(
        params, kv_k, kv_v, jnp.asarray(all_toks[:1]), jnp.asarray(all_pos[:1]),
        page_tables[:1], ctx0[:1], last[:1], samp1, key,
    )
    fence(first1)
    firstB, kv_k, kv_v = prefill(
        params, kv_k, kv_v, jnp.asarray(all_toks), jnp.asarray(all_pos),
        page_tables, ctx0, last, samp, key,
    )
    fence(firstB)

    # TTFT: one sequence arrives alone — dispatch + the host read of its token
    t0 = time.perf_counter()
    first1, kv_k, kv_v = prefill(
        params, kv_k, kv_v, jnp.asarray(all_toks[:1]), jnp.asarray(all_pos[:1]),
        page_tables[:1], ctx0[:1], last[:1], samp1, key,
    )
    tok0 = int(jax.device_get(first1)[0])
    t_first = time.perf_counter() - t0

    # prefill throughput: the full batch in one dispatch
    t0 = time.perf_counter()
    firstB, kv_k, kv_v = prefill(
        params, kv_k, kv_v, jnp.asarray(all_toks), jnp.asarray(all_pos),
        page_tables, ctx0, last, samp, key,
    )
    fence(firstB)
    t_prefill = time.perf_counter() - t0

    # ---- fused K-step decode blocks ----
    # the rng key is threaded THROUGH the jitted block (split on device,
    # advanced key returned): an eager fold_in/split between dispatches is
    # a hidden host round-trip (~9 ms/step through the axon tunnel)
    def _decode_block(params, kv_k, kv_v, tokens, positions, seq_lens, page_tables, samp, key):
        key, sub = jax.random.split(key)
        keys = jax.random.split(sub, K)

        def step(carry, k):
            tokens, positions, seq_lens, kv_k, kv_v = carry
            logits, kv_k, kv_v = llama.decode_forward(
                params, cfg, tokens, positions, kv_k, kv_v, page_tables, seq_lens
            )
            nxt = sample(logits, samp, k)
            return (nxt, positions + 1, seq_lens + 1, kv_k, kv_v), nxt

        (tokens, positions, seq_lens, kv_k, kv_v), toks = jax.lax.scan(
            step, (tokens, positions, seq_lens, kv_k, kv_v), keys
        )
        return toks, tokens, positions, seq_lens, kv_k, kv_v, key

    decode_block = jax.jit(_decode_block, donate_argnums=(1, 2, 8))

    tokens = firstB
    positions = jnp.full((B,), args.isl, jnp.int32)
    seq_lens = jnp.full((B,), args.isl + 1, jnp.int32)

    # warmup/compile
    toks, tokens, positions, seq_lens, kv_k, kv_v, key = decode_block(
        params, kv_k, kv_v, tokens, positions, seq_lens, page_tables, samp, key
    )
    fence(toks)

    n_steps = args.steps or (args.osl - 1)
    n_blocks = max(n_steps // K, 1)
    t0 = time.perf_counter()
    for i in range(n_blocks):
        toks, tokens, positions, seq_lens, kv_k, kv_v, key = decode_block(
            params, kv_k, kv_v, tokens, positions, seq_lens, page_tables, samp, key
        )
        # production fetch cadence: one host read per block (overlaps the
        # next block's compute in the engine; here serialized = lower bound)
        last_toks = toks
    fence(last_toks)
    dt = time.perf_counter() - t0
    n_done = n_blocks * K

    toks_per_sec = B * n_done / dt
    itl_ms = dt / n_done * 1000
    print(
        f"# decode: {toks_per_sec:.1f} tok/s (ITL {itl_ms:.2f} ms @ batch {B}); "
        f"prefill: {B * args.isl / t_prefill:.0f} tok/s, first-seq TTFT {t_first*1000:.1f} ms",
        file=sys.stderr,
    )
    result = {
        "metric": f"decode_throughput_{model}_bs{B}_isl{args.isl}",
        "value": round(toks_per_sec, 1),
        "unit": "tok/s",
        "vs_baseline": round(toks_per_sec / H100_DECODE_TOKS_PER_GPU, 2),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
