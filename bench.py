"""Headline benchmark: steady-state decode throughput of the JAX engine.

Runs on whatever `jax.devices()` provides (the real TPU chip under axon;
CPU with --smoke). Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "tok/s", "vs_baseline": N}

vs_baseline: the reference publishes no absolute end-to-end tables
(BASELINE.md); the closest per-accelerator number it documents is the SLA
profiler example decode rate of 51.22 tok/s/GPU at TP4 on H100-class
(docs/benchmarks/pre_deployment_profiling.md:56) => 204.9 tok/s per 4-GPU
worker. We report batched decode tok/s on ONE v5e chip divided by that
per-GPU figure so the ratio reads "v5e-chip decode throughput vs H100-GPU
decode throughput on the reference's own example".
"""

import argparse
import json
import sys
import time

H100_DECODE_TOKS_PER_GPU = 51.22  # reference pre_deployment_profiling.md:56


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny model on CPU")
    ap.add_argument("--model", default=None)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--isl", type=int, default=128, help="input seq len")
    ap.add_argument("--osl", type=int, default=128, help="output seq len")
    ap.add_argument("--steps", type=int, default=None, help="decode steps to time")
    args = ap.parse_args()

    if args.smoke:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        # axon sitecustomize imports jax at startup, freezing jax_platforms
        # before the env var applies — update the live config too
        if "jax" in sys.modules:
            import jax

            jax.config.update("jax_platforms", "cpu")
            # config.update is a silent no-op if a backend already
            # initialized; a "smoke" run must never hit the real TPU
            assert jax.devices()[0].platform == "cpu", (
                f"--smoke needs CPU but backend is {jax.devices()[0].platform}"
            )

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine.kv_cache import alloc_kv_arrays
    from dynamo_tpu.engine.sampling import SamplingParams, sample
    from dynamo_tpu.models import llama

    model = args.model or ("tiny" if args.smoke else "llama3-3b")
    cfgs = {
        "tiny": llama.LlamaConfig.tiny,
        "llama3-3b": llama.LlamaConfig.llama3_2_3b,
        "llama3-8b": llama.LlamaConfig.llama3_8b,
    }
    cfg = cfgs[model]()

    B = args.batch
    PAGE = 64
    max_len = args.isl + args.osl
    pages_per_seq = (max_len + PAGE - 1) // PAGE
    num_pages = B * pages_per_seq + 1
    dev = jax.devices()[0]
    print(f"# bench: model={model} device={dev.platform} B={B} isl={args.isl} osl={args.osl}", file=sys.stderr)

    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    kv_k, kv_v = alloc_kv_arrays(
        cfg.num_layers, num_pages, PAGE, cfg.num_kv_heads, cfg.head_dim, cfg.dtype
    )

    # page tables: disjoint pages per slot (page 0 reserved scratch)
    pt = np.zeros((B, pages_per_seq), np.int32)
    for b in range(B):
        pt[b] = 1 + b * pages_per_seq + np.arange(pages_per_seq)
    pt = pt % num_pages
    page_tables = jnp.asarray(pt)

    # ---- prefill all slots (measures TTFT-ish per-seq prefill rate) ----
    from dynamo_tpu.models.llama import prefill_forward

    prefill = jax.jit(
        lambda p, kk, kv, t, pos, tab, cl, li: prefill_forward(
            p, cfg, t, pos, kk, kv, tab, cl, li
        ),
        donate_argnums=(1, 2),
    )
    # NOTE on timing: under the axon tunnel, block_until_ready() returns
    # before execution finishes — only a host value fetch actually syncs.
    # We therefore fetch a tiny scalar to fence each timed region.
    def fence(x):
        np.asarray(jax.device_get(x.ravel()[0]))

    rng = np.random.RandomState(0)
    # compile prefill before timing (first call pays ~20-40s of XLA compile)
    _toks = jnp.zeros((args.isl,), jnp.int32)
    _pos = jnp.arange(args.isl, dtype=jnp.int32)
    logits, kv_k, kv_v = prefill(
        params, kv_k, kv_v, _toks, _pos, page_tables[0], jnp.asarray(0, jnp.int32),
        jnp.asarray(args.isl - 1, jnp.int32),
    )
    fence(logits)
    t_prefill0 = time.perf_counter()
    for b in range(B):
        toks = jnp.asarray(rng.randint(3, cfg.vocab_size - 1, size=args.isl), jnp.int32)
        pos = jnp.arange(args.isl, dtype=jnp.int32)
        logits, kv_k, kv_v = prefill(
            params, kv_k, kv_v, toks, pos, page_tables[b], jnp.asarray(0, jnp.int32),
            jnp.asarray(args.isl - 1, jnp.int32),
        )
        if b == 0:
            fence(logits)
            t_first = time.perf_counter() - t_prefill0
    fence(logits)
    t_prefill = time.perf_counter() - t_prefill0

    # ---- decode loop ----
    def _decode(params, kv_k, kv_v, tokens, positions, page_tables, seq_lens, samp, key):
        lg, kv_k, kv_v = llama.decode_forward(
            params, cfg, tokens, positions, kv_k, kv_v, page_tables, seq_lens
        )
        return sample(lg, samp, key), kv_k, kv_v

    decode_step = jax.jit(_decode, donate_argnums=(1, 2))

    tokens = jnp.zeros((B,), jnp.int32)
    positions = jnp.full((B,), args.isl, jnp.int32)
    seq_lens = jnp.full((B,), args.isl + 1, jnp.int32)
    samp = SamplingParams.full(B, temperature=0.0)
    key = jax.random.PRNGKey(7)

    # warmup/compile
    tokens, kv_k, kv_v = decode_step(
        params, kv_k, kv_v, tokens, positions, page_tables, seq_lens, samp, key
    )
    fence(tokens)

    n_steps = args.steps or (args.osl - 1)
    t0 = time.perf_counter()
    for i in range(n_steps):
        positions = positions + 1
        seq_lens = seq_lens + 1
        key = jax.random.fold_in(key, i)
        tokens, kv_k, kv_v = decode_step(
            params, kv_k, kv_v, tokens, positions, page_tables, seq_lens, samp, key
        )
    fence(tokens)
    dt = time.perf_counter() - t0

    toks_per_sec = B * n_steps / dt
    itl_ms = dt / n_steps * 1000
    print(
        f"# decode: {toks_per_sec:.1f} tok/s (ITL {itl_ms:.2f} ms @ batch {B}); "
        f"prefill: {B * args.isl / t_prefill:.0f} tok/s, first-seq TTFT {t_first*1000:.1f} ms",
        file=sys.stderr,
    )
    result = {
        "metric": f"decode_throughput_{model}_bs{B}_isl{args.isl}",
        "value": round(toks_per_sec, 1),
        "unit": "tok/s",
        "vs_baseline": round(toks_per_sec / H100_DECODE_TOKS_PER_GPU, 2),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
