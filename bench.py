"""Headline benchmark: the JAX engine raw step rate AND the full serving
stack (e2e), each in its own subprocess so they never share the device.

Default run (what the driver executes on TPU) prints TWO JSON lines:
  1. raw-step decode throughput (engine dispatch units, inline loop)
  2. e2e serving throughput through frontend+router+worker at fixed QPS
     (the north-star metric: output tok/s + p50 TTFT; see bench_e2e.py)
The LAST line is the headline: e2e when it succeeds, raw otherwise.

vs_baseline: the reference publishes no absolute end-to-end tables
(BASELINE.md); the closest per-accelerator number it documents is the SLA
profiler example decode rate of 51.22 tok/s/GPU at TP4 on H100-class —
for a 70B model (docs/benchmarks/pre_deployment_profiling.md:56). Since
our chip may run a different model, the ratio is PARAM-NORMALIZED:
(our tok/s x our params) / (51.22 x 70B), i.e. per-accelerator effective
decode bandwidth on equal terms (see baseline_ratio()).

Outage behavior: every non-smoke entry probes the backend in a killable
subprocess first (probe_backend); if the TPU is unreachable the bench
prints CPU fallback numbers plus a structured {"error": "tpu_unavailable"}
headline and exits 0 — a hung jax.devices() can no longer eat the round's
measurement budget.

Raw-step shapes follow the engine's production dispatch units
(engine/engine.py):
  * prefill: ONE batched [B, isl] dispatch (all sequences together) with
    on-device first-token sampling; TTFT = a single-sequence dispatch plus
    the one host read that delivers the token.
  * decode: K-step fused blocks (lax.scan, sampling feeds the next step on
    device) — one host read per K*B tokens.

Modes:
  --raw     only the raw-step bench (this file's measurement loop)
  --e2e     only the serving bench (bench_e2e.py; extra args pass through)
  (none)    both, as subprocesses
"""

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

H100_DECODE_TOKS_PER_GPU = 51.22  # reference pre_deployment_profiling.md:56

# The reference's 51.22 tok/s/GPU decodes a *70B* model at TP4
# (docs/benchmarks/pre_deployment_profiling.md:56). Comparing a different
# model's tok/s against it raw is apples-to-oranges, so vs_baseline is
# normalized by parameter count: decode is HBM-bandwidth-bound and bytes
# moved per token scale with params, so (tok/s x params) compares
# per-accelerator effective throughput on equal terms.
H100_REF_PARAMS_B = 70.0
MODEL_PARAMS_B = {
    "tiny": 0.001,
    "tiny-moe": 0.004,
    "llama3-3b": 3.2,
    "llama3-8b": 8.0,
    "llama3-70b": 70.0,
}


def baseline_ratio(toks_per_sec: float, model: str):
    """Param-normalized per-accelerator ratio vs the reference's H100 decode
    example; None when the model's size is unknown."""
    params_b = MODEL_PARAMS_B.get(model)
    if params_b is None:
        return None
    return round(
        (toks_per_sec * params_b) / (H100_DECODE_TOKS_PER_GPU * H100_REF_PARAMS_B), 2
    )


def probe_backend(deadline: float = 120.0):
    """Probe the accelerator in a killable subprocess with a hard deadline.

    `jax.devices()` hangs indefinitely when the TPU tunnel is down (round 3
    recorded an rc=124 driver timeout with zero output); probing in a
    subprocess turns an outage into a structured result. Returns
    (platform | None, error_message)."""
    code = "import jax; d = jax.devices(); print(d[0].platform, len(d))"
    t0 = time.perf_counter()
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=deadline,
        )
    except subprocess.TimeoutExpired:
        return None, f"backend probe timed out after {deadline:.0f}s"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return None, "backend probe failed: " + (tail[-1] if tail else f"rc={r.returncode}")
    plat = r.stdout.split()[0] if r.stdout.split() else "unknown"
    sys.stderr.write(
        f"# backend probe: {plat} in {time.perf_counter() - t0:.1f}s\n"
    )
    return plat, ""


def ensure_backend(metric: str):
    """Shared entry guard for every bench script: probe the accelerator
    unless a parent already did (DYN_BENCH_SKIP_PROBE). Returns None when
    the backend is usable; otherwise a structured result dict the caller
    should print as its only output before exiting 0."""
    if os.environ.get("DYN_BENCH_SKIP_PROBE") == "1":
        return None
    plat, err = probe_backend()
    if plat is None:
        return {
            "metric": metric, "value": 0.0, "unit": "tok/s",
            "vs_baseline": 0.0, "error": "tpu_unavailable", "detail": err,
        }
    os.environ["DYN_BENCH_SKIP_PROBE"] = "1"
    return None


def _emit_unavailable(detail: str):
    """TPU down: report whatever CPU numbers we can, then a structured
    tpu_unavailable headline. Exit 0 so the driver records the JSON."""
    sys.stderr.write(f"# TPU unavailable: {detail}\n")
    env = dict(os.environ, DYN_BENCH_SKIP_PROBE="1")
    try:
        r = subprocess.run(
            [sys.executable, __file__, "--raw", "--smoke"],
            capture_output=True, text=True, timeout=600, env=env,
        )
        for line in r.stdout.splitlines():
            if line.startswith("{"):
                d = json.loads(line)
                d["metric"] += "_cpu_fallback"
                d["note"] = "CPU smoke numbers; TPU was unreachable"
                print(json.dumps(d))
    except Exception as e:  # the fallback must never block the error line
        sys.stderr.write(f"# cpu fallback failed: {e}\n")
    print(json.dumps({
        "metric": "e2e_output_toks_agg",
        "value": 0.0,
        "unit": "tok/s",
        "vs_baseline": 0.0,
        "error": "tpu_unavailable",
        "detail": detail,
    }))
    sys.exit(0)


def _json_lines(cmd, label):
    """Run a bench subprocess; return (last stdout JSON line | None, rc)."""
    env = dict(os.environ, DYN_BENCH_SKIP_PROBE="1")
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=1800, env=env)
    except subprocess.TimeoutExpired as e:
        sys.stderr.write(f"# {label} bench timed out after {e.timeout}s\n")
        return None, 124
    sys.stderr.write(r.stderr)
    out = None
    for line in r.stdout.splitlines():
        if line.startswith("{"):
            out = line
    if r.returncode != 0:
        sys.stderr.write(f"# {label} bench exited rc={r.returncode}\n")
    return out, r.returncode


def _tag_error(line, rc):
    """Mark a JSON result line as coming from a failed subprocess."""
    try:
        d = json.loads(line)
    except (TypeError, ValueError):
        return line
    d["error"] = f"bench_exit_{rc}"
    return json.dumps(d)


def _combined(args, extra):
    """Run raw + engine + e2e as subprocesses (the BENCH_r04 triple the
    round-3 verdict prescribes). Lines print AS EACH PHASE COMPLETES, so
    a driver timeout mid-run still leaves the finished phases in the
    recorded tail; the LAST printed line is the recorded headline."""
    smoke = ["--smoke"] if args.smoke else []
    model = ["--model", args.model] if args.model else []
    quant = ["--quantize", args.quantize] if args.quantize else []
    raw_line, raw_rc = _json_lines(
        [sys.executable, __file__, "--raw", *smoke, *model,
         "--batch", str(args.batch), "--isl", str(args.isl),
         "--osl", str(args.osl), "--block", str(args.block),
         *(["--steps", str(args.steps)] if args.steps else []), *quant],
        "raw",
    )
    raw_ok = raw_line is not None and raw_rc == 0
    if raw_line:
        print(raw_line if raw_ok else _tag_error(raw_line, raw_rc), flush=True)
    eng_line, eng_rc = _json_lines(
        [sys.executable, str(Path(__file__).parent / "bench_engine.py"),
         *smoke, *model, "--batch", str(args.batch), "--isl", str(args.isl),
         "--osl", str(args.osl), "--block", str(args.block), *quant],
        "engine",
    )
    if eng_line:
        print(eng_line if eng_rc == 0 else _tag_error(eng_line, eng_rc),
              flush=True)
    e2e_line, e2e_rc = _json_lines(
        [sys.executable, str(Path(__file__).parent / "bench_e2e.py"),
         "--mode", "agg", *smoke, *model, *extra],
        "e2e",
    )
    # headline = LAST printed line; never let a failed subprocess's numbers
    # stand as the headline untagged, and propagate ANY phase failure in
    # the exit code
    eng_ok = eng_line is not None and eng_rc == 0
    e2e_ok = e2e_line is not None and e2e_rc == 0
    if e2e_ok:
        print(e2e_line)
        sys.exit(0 if (raw_ok and eng_ok) else 1)
    # headline e2e failed: print whatever was measured (tagged), exit 1.
    # Ordering keeps the best available UNTAGGED line LAST (the headline
    # slot) — tagged failures first, then engine, then raw (raw is the
    # most comparable single number across rounds).
    printed = False
    if e2e_line:  # e2e produced a line but exited nonzero (failed requests)
        print(_tag_error(e2e_line, e2e_rc))
        printed = True
    if eng_line:
        print(eng_line if eng_ok else _tag_error(eng_line, eng_rc))
        printed = True
    if raw_line:
        print(raw_line if raw_ok else _tag_error(raw_line, raw_rc))
        printed = True
    if not printed:
        print(json.dumps({
            "metric": "e2e_output_toks_agg", "value": 0.0, "unit": "tok/s",
            "vs_baseline": 0.0, "error": "bench_failed",
            "detail": f"raw rc={raw_rc} engine rc={eng_rc} e2e rc={e2e_rc}, "
                      "no JSON produced",
        }))
    sys.exit(1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny model on CPU")
    ap.add_argument("--model", default=None)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--isl", type=int, default=128, help="input seq len")
    ap.add_argument("--osl", type=int, default=128, help="output seq len")
    ap.add_argument("--block", type=int, default=16, help="fused decode steps per dispatch")
    ap.add_argument("--steps", type=int, default=None, help="decode steps to time")
    ap.add_argument("--quantize", choices=["int8"], default=None,
                    help="int8 weight-only quantization (models/quant.py)")
    ap.add_argument("--raw", action="store_true", help="only the raw-step bench")
    ap.add_argument("--e2e", action="store_true", help="serve a trace through the full stack")
    ap.add_argument("--engine", action="store_true",
                    help="drive JaxEngine.generate (scheduler + fetch pipeline included)")
    args, extra = ap.parse_known_args()

    # Any non-smoke path touches the real device: probe it first with a hard
    # deadline so a dead tunnel yields a structured result, never a hang.
    # Children spawned by _combined inherit DYN_BENCH_SKIP_PROBE.
    if not args.smoke:
        unavailable = ensure_backend("e2e_output_toks_agg")
        if unavailable is not None:
            _emit_unavailable(unavailable["detail"])

    if args.e2e:
        from bench_e2e import main as e2e_main

        return e2e_main(extra + (["--smoke"] if args.smoke else []))

    if args.engine:
        from bench_engine import main as engine_main

        return engine_main(
            extra
            + (["--smoke"] if args.smoke else [])
            + (["--quantize", args.quantize] if args.quantize else [])
        )

    if not args.raw:
        return _combined(args, extra)

    if args.smoke:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        # axon sitecustomize imports jax at startup, freezing jax_platforms
        # before the env var applies — update the live config too
        if "jax" in sys.modules:
            import jax

            jax.config.update("jax_platforms", "cpu")
            # config.update is a silent no-op if a backend already
            # initialized; a "smoke" run must never hit the real TPU
            assert jax.devices()[0].platform == "cpu", (
                f"--smoke needs CPU but backend is {jax.devices()[0].platform}"
            )

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine.engine import _enable_compile_cache
    from dynamo_tpu.engine.kv_cache import alloc_kv_arrays
    from dynamo_tpu.engine.sampling import SamplingParams, sample
    from dynamo_tpu.models import llama

    _enable_compile_cache()
    model = args.model or ("tiny" if args.smoke else "llama3-3b")
    cfgs = {
        "tiny": llama.LlamaConfig.tiny,
        "llama3-3b": llama.LlamaConfig.llama3_2_3b,
        "llama3-8b": llama.LlamaConfig.llama3_8b,
    }
    cfg = cfgs[model]()

    B = args.batch
    PAGE = 64
    K = args.block
    max_len = args.isl + args.osl + K  # fused blocks may overshoot by < K
    pages_per_seq = (max_len + PAGE - 1) // PAGE
    num_pages = B * pages_per_seq + 1
    dev = jax.devices()[0]
    print(
        f"# bench: model={model} device={dev.platform} B={B} isl={args.isl} "
        f"osl={args.osl} block={K}",
        file=sys.stderr,
    )

    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    if args.quantize == "int8":
        from dynamo_tpu.models.quant import quantize_tree

        params = quantize_tree(params, consume=True)
    kv_k, kv_v = alloc_kv_arrays(
        cfg.num_layers, num_pages, PAGE, cfg.num_kv_heads, cfg.head_dim, cfg.dtype
    )

    # page tables: disjoint pages per slot (page 0 reserved scratch)
    pt = np.zeros((B, pages_per_seq), np.int32)
    for b in range(B):
        pt[b] = 1 + b * pages_per_seq + np.arange(pages_per_seq)
    pt = pt % num_pages
    page_tables = jnp.asarray(pt)

    # NOTE on timing: under the axon tunnel, block_until_ready() returns
    # before execution finishes — only a host value fetch actually syncs.
    # We therefore fetch a tiny scalar to fence each timed region.
    def fence(x):
        np.asarray(jax.device_get(x.ravel()[0]))

    # ---- batched prefill (one dispatch for the whole batch) ----
    def _prefill(params, kk, kv, toks, pos, tabs, cls, lis, samp, key):
        logits, kk, kv = llama.prefill_forward_batched(
            params, cfg, toks, pos, kk, kv, tabs, cls, lis
        )
        return sample(logits, samp, key), kk, kv

    prefill = jax.jit(_prefill, donate_argnums=(1, 2))

    rng = np.random.RandomState(0)
    all_toks = rng.randint(3, cfg.vocab_size - 1, size=(B, args.isl)).astype(np.int32)
    all_pos = np.tile(np.arange(args.isl, dtype=np.int32), (B, 1))
    ctx0 = jnp.zeros((B,), jnp.int32)
    last = jnp.full((B,), args.isl - 1, jnp.int32)
    samp = SamplingParams.full(B, temperature=0.0)
    samp1 = SamplingParams.full(1, temperature=0.0)
    key = jax.random.PRNGKey(7)

    # compile both variants before timing (first call pays XLA compile)
    first1, kv_k, kv_v = prefill(
        params, kv_k, kv_v, jnp.asarray(all_toks[:1]), jnp.asarray(all_pos[:1]),
        page_tables[:1], ctx0[:1], last[:1], samp1, key,
    )
    fence(first1)
    firstB, kv_k, kv_v = prefill(
        params, kv_k, kv_v, jnp.asarray(all_toks), jnp.asarray(all_pos),
        page_tables, ctx0, last, samp, key,
    )
    fence(firstB)

    # TTFT: one sequence arrives alone — dispatch + the host read of its token
    t0 = time.perf_counter()
    first1, kv_k, kv_v = prefill(
        params, kv_k, kv_v, jnp.asarray(all_toks[:1]), jnp.asarray(all_pos[:1]),
        page_tables[:1], ctx0[:1], last[:1], samp1, key,
    )
    tok0 = int(jax.device_get(first1)[0])
    t_first = time.perf_counter() - t0

    # prefill throughput: the full batch in one dispatch
    t0 = time.perf_counter()
    firstB, kv_k, kv_v = prefill(
        params, kv_k, kv_v, jnp.asarray(all_toks), jnp.asarray(all_pos),
        page_tables, ctx0, last, samp, key,
    )
    fence(firstB)
    t_prefill = time.perf_counter() - t0

    # ---- fused K-step decode blocks ----
    # the rng key is threaded THROUGH the jitted block (split on device,
    # advanced key returned): an eager fold_in/split between dispatches is
    # a hidden host round-trip (~9 ms/step through the axon tunnel)
    def _decode_block(params, kv_k, kv_v, tokens, positions, seq_lens, page_tables, samp, key):
        key, sub = jax.random.split(key)
        keys = jax.random.split(sub, K)

        def step(carry, k):
            tokens, positions, seq_lens, kv_k, kv_v = carry
            logits, kv_k, kv_v = llama.decode_forward(
                params, cfg, tokens, positions, kv_k, kv_v, page_tables, seq_lens
            )
            nxt = sample(logits, samp, k)
            return (nxt, positions + 1, seq_lens + 1, kv_k, kv_v), nxt

        (tokens, positions, seq_lens, kv_k, kv_v), toks = jax.lax.scan(
            step, (tokens, positions, seq_lens, kv_k, kv_v), keys
        )
        return toks, tokens, positions, seq_lens, kv_k, kv_v, key

    decode_block = jax.jit(_decode_block, donate_argnums=(1, 2, 8))

    tokens = firstB
    positions = jnp.full((B,), args.isl, jnp.int32)
    seq_lens = jnp.full((B,), args.isl + 1, jnp.int32)

    # warmup/compile
    toks, tokens, positions, seq_lens, kv_k, kv_v, key = decode_block(
        params, kv_k, kv_v, tokens, positions, seq_lens, page_tables, samp, key
    )
    fence(toks)

    n_steps = args.steps or (args.osl - 1)
    n_blocks = max(n_steps // K, 1)
    t0 = time.perf_counter()
    for i in range(n_blocks):
        toks, tokens, positions, seq_lens, kv_k, kv_v, key = decode_block(
            params, kv_k, kv_v, tokens, positions, seq_lens, page_tables, samp, key
        )
        # production fetch cadence: one host read per block (overlaps the
        # next block's compute in the engine; here serialized = lower bound)
        last_toks = toks
    fence(last_toks)
    dt = time.perf_counter() - t0
    n_done = n_blocks * K

    toks_per_sec = B * n_done / dt
    itl_ms = dt / n_done * 1000
    print(
        f"# decode: {toks_per_sec:.1f} tok/s (ITL {itl_ms:.2f} ms @ batch {B}); "
        f"prefill: {B * args.isl / t_prefill:.0f} tok/s, first-seq TTFT {t_first*1000:.1f} ms",
        file=sys.stderr,
    )
    from bench_eff import efficiency_fields

    n_params = sum(
        int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params)
    )
    result = {
        "metric": f"decode_throughput_{model}_bs{B}_isl{args.isl}"
        + ("_int8" if args.quantize else ""),
        "value": round(toks_per_sec, 1),
        "unit": "tok/s",
        "vs_baseline": baseline_ratio(toks_per_sec, model),
        **(efficiency_fields(
            model, toks_per_sec, B, args.isl + n_done / 2, args.quantize,
            n_params=float(n_params),
            dims=(cfg.num_layers, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim),
        ) if dev.platform == "tpu" else {}),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
