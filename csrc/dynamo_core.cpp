// dynamo-tpu native core: chained block hashing + KV radix index.
//
// The host-side hot path of the KV router (reference lib/llm/src/tokens.rs
// compute_hash_v2 :36 and kv_router/indexer.rs RadixTree :224 /
// find_matches :276 — Rust there, C++ here). Exposed as a C ABI consumed
// via ctypes from dynamo_tpu/native/__init__.py; semantics must match the
// pure-Python fallback (llm/tokens.py, llm/kv_router/indexer.py) exactly —
// parity-tested in tests/test_native_core.py.
//
// Hash scheme: xxh3_64(le_bytes(u32 tokens), seed=parent_hash); parent of
// the first block is the salt hash. Chained hashes make every block hash a
// unique prefix id, so the "radix tree" is a flat hash map with a
// continuity walk at match time (same collapse the Python version does).

#define XXH_INLINE_ALL
#include "xxhash.h"

#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <unordered_map>
#include <unordered_set>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- hashing

uint64_t dyn_block_hash(const uint32_t* tokens, uint64_t n, uint64_t parent) {
  // tokens are already little-endian u32 in memory on every target we run on
  return XXH3_64bits_withSeed(tokens, n * sizeof(uint32_t), parent);
}

// out must hold n / block_size entries; returns the number written
uint64_t dyn_seq_hashes(const uint32_t* tokens, uint64_t n,
                        uint64_t block_size, uint64_t salt, uint64_t* out) {
  uint64_t parent = salt;
  uint64_t written = 0;
  for (uint64_t start = 0; start + block_size <= n; start += block_size) {
    parent = XXH3_64bits_withSeed(tokens + start, block_size * sizeof(uint32_t),
                                  parent);
    out[written++] = parent;
  }
  return written;
}

// ------------------------------------------------------------------ index

struct DynIndex {
  // hash -> holder workers. Chained hashes are effectively unique per
  // prefix, so holder sets are tiny (replicas of the same content).
  std::unordered_map<uint64_t, std::vector<int64_t>> blocks;
  std::unordered_map<int64_t, std::unordered_set<uint64_t>> worker_blocks;
};

void* dyn_index_new() { return new DynIndex(); }

void dyn_index_free(void* p) { delete static_cast<DynIndex*>(p); }

void dyn_index_apply_stored(void* p, int64_t worker, const uint64_t* hashes,
                            uint64_t n) {
  auto* idx = static_cast<DynIndex*>(p);
  auto& wb = idx->worker_blocks[worker];
  for (uint64_t i = 0; i < n; ++i) {
    uint64_t h = hashes[i];
    auto& holders = idx->blocks[h];
    bool present = false;
    for (int64_t w : holders)
      if (w == worker) { present = true; break; }
    if (!present) holders.push_back(worker);
    wb.insert(h);
  }
}

void dyn_index_apply_removed(void* p, int64_t worker, const uint64_t* hashes,
                             uint64_t n) {
  auto* idx = static_cast<DynIndex*>(p);
  auto wb = idx->worker_blocks.find(worker);
  for (uint64_t i = 0; i < n; ++i) {
    uint64_t h = hashes[i];
    auto it = idx->blocks.find(h);
    if (it != idx->blocks.end()) {
      auto& holders = it->second;
      for (size_t j = 0; j < holders.size(); ++j) {
        if (holders[j] == worker) {
          holders[j] = holders.back();
          holders.pop_back();
          break;
        }
      }
      if (holders.empty()) idx->blocks.erase(it);
    }
    if (wb != idx->worker_blocks.end()) wb->second.erase(h);
  }
}

void dyn_index_remove_worker(void* p, int64_t worker) {
  auto* idx = static_cast<DynIndex*>(p);
  auto wb = idx->worker_blocks.find(worker);
  if (wb == idx->worker_blocks.end()) return;
  for (uint64_t h : wb->second) {
    auto it = idx->blocks.find(h);
    if (it == idx->blocks.end()) continue;
    auto& holders = it->second;
    for (size_t j = 0; j < holders.size(); ++j) {
      if (holders[j] == worker) {
        holders[j] = holders.back();
        holders.pop_back();
        break;
      }
    }
    if (holders.empty()) idx->blocks.erase(it);
  }
  idx->worker_blocks.erase(wb);
}

uint64_t dyn_index_num_blocks(void* p) {
  return static_cast<DynIndex*>(p)->blocks.size();
}

uint64_t dyn_index_worker_block_count(void* p, int64_t worker) {
  auto* idx = static_cast<DynIndex*>(p);
  auto it = idx->worker_blocks.find(worker);
  return it == idx->worker_blocks.end() ? 0 : it->second.size();
}

// Match walk (reference find_matches indexer.rs:276): a worker scores d+1
// iff it holds blocks 0..d contiguously; workers that drop out early keep
// the score of the depth they last survived (matches the Python/Rust
// OverlapScores map). Output: parallel arrays of worker ids and scores
// (capacity max_workers) plus per-depth survivor counts (capacity n,
// written count to *freq_n). Returns the number of scored workers.
uint64_t dyn_index_find_matches(void* p, const uint64_t* hashes,
                                uint64_t n, int early_exit,
                                int64_t* out_workers, uint64_t* out_scores,
                                uint64_t max_workers, uint64_t* out_freqs,
                                uint64_t* freq_n) {
  auto* idx = static_cast<DynIndex*>(p);
  std::unordered_map<int64_t, uint64_t> scores;
  std::vector<int64_t> active;
  bool first = true;
  uint64_t freqs = 0;
  for (uint64_t depth = 0; depth < n; ++depth) {
    auto it = idx->blocks.find(hashes[depth]);
    if (it == idx->blocks.end() || it->second.empty()) break;
    if (first) {
      active = it->second;
      first = false;
    } else {
      const auto& holders = it->second;
      std::vector<int64_t> next;
      next.reserve(active.size());
      for (int64_t w : active)
        for (int64_t h : holders)
          if (w == h) { next.push_back(w); break; }
      active.swap(next);
    }
    if (active.empty()) break;
    out_freqs[freqs++] = active.size();
    for (int64_t w : active) scores[w] = depth + 1;
    if (early_exit && active.size() == 1) break;
  }
  *freq_n = freqs;
  uint64_t i = 0;
  for (const auto& kv : scores) {
    if (i >= max_workers) break;
    out_workers[i] = kv.first;
    out_scores[i] = kv.second;
    ++i;
  }
  return i;
}

// Snapshot support: write (worker, hash) pairs. First call with
// out=nullptr to get the count.
uint64_t dyn_index_dump(void* p, int64_t* out_workers, uint64_t* out_hashes,
                        uint64_t cap) {
  auto* idx = static_cast<DynIndex*>(p);
  uint64_t total = 0;
  for (const auto& kv : idx->worker_blocks) total += kv.second.size();
  if (out_workers == nullptr || out_hashes == nullptr) return total;
  uint64_t i = 0;
  for (const auto& kv : idx->worker_blocks) {
    for (uint64_t h : kv.second) {
      if (i >= cap) return i;
      out_workers[i] = kv.first;
      out_hashes[i] = h;
      ++i;
    }
  }
  return i;
}

// ------------------------------------------------------- C event ABI
//
// Role of the reference's C bindings (lib/bindings/c/src/lib.rs:100,115,281
// dynamo_llm_init / dynamo_llm_shutdown / kv event publish): native engine
// runtimes publish KV block stored/removed events from C/C++ threads
// without touching Python. Events land in a mutex-guarded queue; the
// Python side drains it (dynamo_tpu/native, NativeKvEventQueue) and
// forwards to the discovery event topic via KvEventPublisher.

namespace {

struct DynKvEvent {
  int64_t worker;
  int32_t type;  // 0 = stored, 1 = removed, 2 = cleared
  std::vector<uint64_t> hashes;
};

struct DynEventQueue {
  std::mutex mu;
  std::deque<DynKvEvent> events;
  uint64_t dropped = 0;
  uint64_t capacity;
  explicit DynEventQueue(uint64_t cap) : capacity(cap) {}
};

}  // namespace

void* dyn_llm_init(uint64_t queue_capacity) {
  return new DynEventQueue(queue_capacity ? queue_capacity : 65536);
}

void dyn_llm_shutdown(void* p) { delete static_cast<DynEventQueue*>(p); }

static void dyn_push(void* p, int64_t worker, int32_t type,
                     const uint64_t* hashes, uint64_t n) {
  auto* q = static_cast<DynEventQueue*>(p);
  std::lock_guard<std::mutex> lock(q->mu);
  if (q->events.size() >= q->capacity) {
    // keep the newest events: stale stored/removed info is the least harmful
    // thing to lose (the router self-corrects on later events)
    q->events.pop_front();
    q->dropped++;
  }
  q->events.push_back({worker, type, std::vector<uint64_t>(hashes, hashes + n)});
}

void dyn_kv_publish_stored(void* p, int64_t worker, const uint64_t* hashes,
                           uint64_t n) {
  dyn_push(p, worker, 0, hashes, n);
}

void dyn_kv_publish_removed(void* p, int64_t worker, const uint64_t* hashes,
                            uint64_t n) {
  dyn_push(p, worker, 1, hashes, n);
}

void dyn_kv_publish_cleared(void* p, int64_t worker) {
  dyn_push(p, worker, 2, nullptr, 0);
}

// Pop one event. Returns the number of hashes written (<= cap), or -1 if the
// queue is empty, or -2 if the event's hashes exceed cap (event stays queued;
// call again with a bigger buffer; required size in *out_n_hashes).
int64_t dyn_kv_event_pop(void* p, int64_t* out_worker, int32_t* out_type,
                         uint64_t* out_hashes, uint64_t cap,
                         uint64_t* out_n_hashes) {
  auto* q = static_cast<DynEventQueue*>(p);
  std::lock_guard<std::mutex> lock(q->mu);
  if (q->events.empty()) return -1;
  DynKvEvent& ev = q->events.front();
  *out_n_hashes = ev.hashes.size();
  if (ev.hashes.size() > cap) return -2;
  *out_worker = ev.worker;
  *out_type = ev.type;
  std::memcpy(out_hashes, ev.hashes.data(), ev.hashes.size() * sizeof(uint64_t));
  int64_t n = static_cast<int64_t>(ev.hashes.size());
  q->events.pop_front();
  return n;
}

uint64_t dyn_kv_events_dropped(void* p) {
  auto* q = static_cast<DynEventQueue*>(p);
  std::lock_guard<std::mutex> lock(q->mu);
  return q->dropped;
}

uint64_t dyn_kv_events_pending(void* p) {
  auto* q = static_cast<DynEventQueue*>(p);
  std::lock_guard<std::mutex> lock(q->mu);
  return q->events.size();
}

}  // extern "C"
