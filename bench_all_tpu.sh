#!/bin/bash
# The round-4 TPU measurement ladder (BENCH_NOTES_r04.md "queued for
# TPU"). Run when the axon tunnel is back:  bash bench_all_tpu.sh
# Appends every JSON line to bench_tpu_results.jsonl as phases complete,
# so a mid-ladder outage keeps everything already measured.
set -u
cd "$(dirname "$0")"
OUT=bench_tpu_results.jsonl
# notes are JSON records, never bare comments — the results file must
# stay valid JSONL (round-4 advisor low #4)
log() {
  python - "$*" <<'PYEOF' | tee -a $OUT
import json, sys, time
print(json.dumps({"note": sys.argv[1],
                  "ts": time.strftime("%H:%M:%S", time.gmtime())}))
PYEOF
}

run() {  # run <timeout_s> <label> <cmd...>
  local t=$1 label=$2; shift 2
  log "$label: $*"
  timeout "$t" "$@" 2> >(tail -5 >&2) | grep "^{" | tee -a $OUT
  # rc of the BENCHMARK, not the grep|tee tail (round-4 advisor low #4)
  local rc=${PIPESTATUS[0]}
  log "$label done rc=$rc"
}

log "ladder start"
# 1. headline triple (raw + engine + e2e agg); first run pays compiles
run 3600 triple python bench.py
# 2. ttft breakdown (net of tunnel floor)
run 1200 ttft python bench_ttft.py
# 3. KV-write strategy sweep at production pool sizes
run 5400 sweep python bench_sweep.py --quick --out sweep_tpu.json
# 4. int8 decode ceiling (raw + engine)
run 1800 int8_raw python bench.py --raw --quantize int8
run 1800 int8_engine python bench.py --engine --quantize int8
# 5. e2e disagg + kv router benefit. Two workers share the ONE
# tunnel-attached chip: int8 weights (2 x ~3.4 GB) + fixed 384-page pools
# fit 16 GiB HBM where bf16 (2 x 6.4 GB) would not.
run 3600 disagg python bench_e2e.py --mode disagg --quantize int8
run 5400 kv_benefit python bench_e2e.py --mode kv --prefix-ratio 0.5 --router-compare --quantize int8
# 6. real-trace router benefit (mooncake-style bursty radix trace)
run 5400 kv_trace python bench_e2e.py --mode kv --trace synth --trace-speedup 4 --router-compare --quantize int8
# 7. speculative decoding ITL on a repetition-heavy trace
run 1800 spec python bench_engine.py --quantize int8 --spec ngram
log "ladder complete"
