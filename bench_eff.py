"""MBU/MFU self-reporting for every bench result row (round-4 verdict
next #5): each JSON line carries its own efficiency vs the chip's
roofline, so hardware numbers are directly judgeable without
reverse-engineering from notes.

Model of a batched decode step (the served regime):
  bytes/step  = weight_bytes + B * kv_read_bytes(ctx)   (weights stream
                once per step for the whole batch; each lane reads its own
                KV history)
  flops/token = 2 * n_params + 4 * L * n_heads * head_dim * ctx
                (matmul mult-adds, plus QK^T + AV attention FLOPs)

  MBU = bytes/step * steps_per_s / HBM_BW      steps_per_s = tok_s / B
  MFU = flops/token * tok_s / PEAK_FLOPS

Chip roofline defaults are TPU v5e (the bench target: 16 GiB HBM at
~819 GB/s, 197 bf16 TFLOP/s — jax-ml.github.io/scaling-book part 'TPUs');
override via DYN_TPU_HBM_BW / DYN_TPU_PEAK_FLOPS for other chips. int8
weight-only quantization halves weight bytes; compute still runs in
bf16 (dequant into the accumulator), so the FLOPS roofline is unchanged.

Reference analogue: docs/benchmarks/pre_deployment_profiling.md:54-56
reports per-GPU decode efficiency the same way.
"""

from __future__ import annotations

import os

V5E_HBM_BW = 819e9  # bytes/s
V5E_PEAK_FLOPS = 197e12  # bf16

# (n_params, layers, hidden, n_heads, n_kv_heads, head_dim)
DIMS = {
    "llama3-3b": (3.21e9, 28, 3072, 24, 8, 128),
    "llama3-8b": (8.03e9, 32, 4096, 32, 8, 128),
    "llama3-70b": (70.6e9, 80, 8192, 64, 8, 128),
}


def efficiency_fields(model: str, toks_per_sec: float, batch: int,
                      ctx_mean: float, quantize: str | None = None,
                      n_params: float | None = None,
                      dims: tuple | None = None) -> dict:
    """{"mbu": ..., "mfu": ...} for a decode-rate measurement, or {} when
    the model's dims are unknown (tiny CPU-test models have no meaningful
    roofline). `dims` (layers, n_heads, n_kv_heads, head_dim) + `n_params`
    override the static table when the caller holds the live config."""
    if dims is not None and n_params is not None:
        layers, n_heads, n_kv, hd = dims
    elif model in DIMS:
        n_params, layers, _hidden, n_heads, n_kv, hd = DIMS[model]
    else:
        return {}
    if toks_per_sec <= 0 or batch <= 0:
        return {}
    bw = float(os.environ.get("DYN_TPU_HBM_BW", V5E_HBM_BW))
    peak = float(os.environ.get("DYN_TPU_PEAK_FLOPS", V5E_PEAK_FLOPS))
    wbytes = n_params * (1 if quantize == "int8" else 2)
    kv_read = 2 * layers * n_kv * hd * 2 * ctx_mean  # bf16 K+V history
    bytes_per_step = wbytes + batch * kv_read
    steps_per_s = toks_per_sec / batch
    flops_per_tok = 2 * n_params + 4 * layers * n_heads * hd * ctx_mean
    return {
        "mbu": round(bytes_per_step * steps_per_s / bw, 3),
        "mfu": round(flops_per_tok * toks_per_sec / peak, 4),
    }
