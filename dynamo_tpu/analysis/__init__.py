"""dynolint: AST-based invariant checker for the serving stack.

The serving stack's correctness contracts are machine-checkable, and the
round-5 history shows why they must be: API parameters accepted by the
OpenAI frontend and silently ignored by the engine (the sampling-penalties
bug) survived multiple reviews. Each contract is a `Rule` over the parsed
AST of the package; `tests/test_static_analysis.py` runs the pack as a
tier-1 test so every PR inherits enforcement.

Two packs: the per-file `core` rules (rules/) and the interprocedural
`shard` pack (shard/ — mesh-axis registry, Pallas grid consistency,
collective symmetry; resolution through call chains, defaults, and
functools.partial).

Run locally:

    python -m dynamo_tpu.analysis                # text report, exit 1 on hits
    python -m dynamo_tpu.analysis --format=json  # machine-readable
    python -m dynamo_tpu.analysis --rules shard  # one pack
    python -m dynamo_tpu.analysis --changed-only # git-scoped report
    python -m dynamo_tpu.analysis --emit-env-docs docs/configuration.md

Suppress a finding on its line (reason required by convention):

    x = thing()  # dynolint: disable=async-blocking -- startup path, loop not running

See docs/static_analysis.md for the rule pack and how to add a rule.
"""

from .core import Project, Rule, SourceFile, Violation, format_json, format_text, run
from .rules import ALL_RULES, default_rules

__all__ = [
    "ALL_RULES",
    "Project",
    "Rule",
    "SourceFile",
    "Violation",
    "default_rules",
    "format_json",
    "format_text",
    "run",
]
