"""Compile-contract registry extraction (AST-parsed, never imported).

Two tables anchor the comp pack, both read straight out of the AST so
the checker runs on hosts without jax importable (the ENV_REGISTRY /
KNOWN_FAULT_POINTS / GUARDED_STATE / METRICS contract):

  * `engine/compile_registry.py:COMPILE_SURFACES` — one entry per
    staged surface (module, kind, donate, static, axes, warmup,
    dispatch aliases, help);
  * `engine/bucketing.py:BUCKETING_HELPERS` — the bounded shape
    sources comp-shape-bucketing resolves dispatch-operand dimensions
    against.

Every value must stay a pure literal (`ast.literal_eval`-able) and
every key a string literal; ** merges and duplicate keys are malformed.
"""

from __future__ import annotations

import ast
from typing import Dict, Optional, Tuple

from ..core import Project, str_const

COMPILE_MODULE = "dynamo_tpu/engine/compile_registry.py"
BUCKETING_MODULE = "dynamo_tpu/engine/bucketing.py"

VALID_KINDS = {"jit", "pjit", "shard_map", "pallas_call"}

#: package dirs the comp rules scan for staged callsites
SCOPES = ("engine/", "ops/", "models/", "llm/", "planner/")


def _load_literal_table(
    project: Project, module: str, var: str
) -> Tuple[Optional[Dict[str, dict]], Optional[Dict[str, int]], Optional[str]]:
    """Shared loader: parse `var` (a pure-literal dict keyed by string
    literals) out of `module`. Returns (entries, key_lines, error)."""
    src = project.get(module)
    if src is None:
        return None, None, f"{module} not found: the {var} registry is gone"
    table: Optional[ast.Dict] = None
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name) and tgt.id == var and isinstance(
                node.value, ast.Dict
            ):
                table = node.value
    if table is None:
        return None, None, (
            f"{module} defines no {var} dict literal — the comp rules "
            "need the compile contract as their source of truth"
        )
    entries: Dict[str, dict] = {}
    lines: Dict[str, int] = {}
    for k, v in zip(table.keys, table.values):
        if k is None:
            return None, None, (
                f"{module}: {var} must not use ** merges — every entry "
                "must be spelled at its own line"
            )
        name = str_const(k)
        if name is None:
            return None, None, (
                f"{module}: {var} key {ast.dump(k)} is not a string "
                "literal"
            )
        try:
            spec = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            return None, None, (
                f"{module}: {var}['{name}'] value is not a pure "
                "literal — the registry must stay literal_eval-able"
            )
        if not isinstance(spec, dict):
            return None, None, f"{module}: {var}['{name}'] must be a dict"
        if name in entries:
            return None, None, f"{module}: {var} registers '{name}' twice"
        entries[name] = spec
        lines[name] = k.lineno
    return entries, lines, None


def load_compile_surfaces(
    project: Project,
) -> Tuple[Optional[Dict[str, dict]], Optional[Dict[str, int]], Optional[str]]:
    """Parse COMPILE_SURFACES out of engine/compile_registry.py.

    Returns (entries, lines, error): entries maps surface key -> spec
    dict; lines maps surface key -> registry line for anchoring
    stale-entry and warmup-gap findings; error is a human message when
    the registry is missing or malformed.
    """
    entries, lines, err = _load_literal_table(
        project, COMPILE_MODULE, "COMPILE_SURFACES"
    )
    if err is not None:
        return None, None, err
    for name, spec in entries.items():
        kind = spec.get("kind")
        if kind not in VALID_KINDS:
            return None, None, (
                f"{COMPILE_MODULE}: COMPILE_SURFACES['{name}'] kind "
                f"{kind!r} is not one of {sorted(VALID_KINDS)}"
            )
        module = spec.get("module")
        if not isinstance(module, str) or not module.endswith(".py"):
            return None, None, (
                f"{COMPILE_MODULE}: COMPILE_SURFACES['{name}'] module "
                f"{module!r} is not a .py path"
            )
        donate = spec.get("donate", ())
        if not isinstance(donate, tuple) or not all(
            isinstance(i, int) for i in donate
        ):
            return None, None, (
                f"{COMPILE_MODULE}: COMPILE_SURFACES['{name}'] donate "
                f"{donate!r} must be a tuple of argument positions"
            )
        static = spec.get("static", ())
        if not isinstance(static, tuple) or not all(
            isinstance(s, (int, str)) for s in static
        ):
            return None, None, (
                f"{COMPILE_MODULE}: COMPILE_SURFACES['{name}'] static "
                f"{static!r} must be a tuple of names or positions"
            )
        if not isinstance(spec.get("warmup"), bool):
            return None, None, (
                f"{COMPILE_MODULE}: COMPILE_SURFACES['{name}'] must "
                "declare warmup: True/False explicitly"
            )
        dispatch = spec.get("dispatch", ())
        if not isinstance(dispatch, tuple) or not all(
            isinstance(d, str) for d in dispatch
        ):
            return None, None, (
                f"{COMPILE_MODULE}: COMPILE_SURFACES['{name}'] dispatch "
                f"{dispatch!r} must be a tuple of caller-side names"
            )
    return entries, lines, None


def load_bucketing_helpers(
    project: Project,
) -> Tuple[Optional[Dict[str, dict]], Optional[Dict[str, int]], Optional[str]]:
    """Parse BUCKETING_HELPERS out of engine/bucketing.py. Same shape as
    load_compile_surfaces; keys are bare helper names (callsites match
    with leading underscores stripped)."""
    entries, lines, err = _load_literal_table(
        project, BUCKETING_MODULE, "BUCKETING_HELPERS"
    )
    if err is not None:
        return None, None, err
    for name in entries:
        if name.startswith("_"):
            return None, None, (
                f"{BUCKETING_MODULE}: BUCKETING_HELPERS key '{name}' must "
                "be the bare helper name (callsites strip leading "
                "underscores when matching)"
            )
    return entries, lines, None


def accepted_names(key: str, spec: dict) -> set:
    """Caller-side and def-side names that resolve to a surface entry:
    the key itself, `_<key>` (the engine's bound-attribute convention),
    and any declared dispatch aliases."""
    return {key, "_" + key} | set(spec.get("dispatch", ()))
