"""Rule: comp-shape-bucketing — dispatch-operand shapes come from buckets.

XLA compiles one program per distinct operand shape. The engine's
steady-state guarantee — warmup precompiles everything, serving never
compiles — therefore rests on every dispatch-operand dimension being
drawn from a finite, config-bounded set. One request-derived integer
leaking into an `np.zeros` shape at a dispatch site turns serving into
a recompile storm: 20-40s per new program through the remote-compile
tunnel, step loop frozen, discovery leases lapsing.

The rule taints host-side shape constructors (`np/jnp` `zeros`/`full`/
`ones`/`empty`, `np.pad` widths, `.reshape` args) inside DISPATCH
functions — functions that hand work to a serving surface (call
`_run_on_device` or a warmup-obligated surface from COMPILE_SURFACES) —
and requires every dimension to resolve to a bounded source:

  * int literals and config attributes (any dotted path through a
    `*config*` segment), and attributes/subscripts of bounded values
    (`plan.bucket`, `cfg.prefill_buckets[-1]`);
  * calls to helpers registered in bucketing.BUCKETING_HELPERS
    (matched with leading underscores stripped: `_next_pow2`,
    `self.scheduler.plan_prefill`);
  * `.shape` of an existing operand (already-materialized = already
    bounded by its own constructor);
  * closed arithmetic: `min()` with ANY bounded arm (a clamp), `max()`/
    `+`/`-`/`*` with ALL arms bounded, `//` with a bounded left arm,
    `%` with EITHER side bounded, conditional expressions with both
    branches bounded;
  * locals whose every (textually prior) assignment is bounded, and
    `self.<attr>` whose every assignment in the file is bounded
    (`self._mixed_row_bucket = _next_pow2(...)`).

`len(...)`, request/slot fields, and anything unresolvable are
unbounded and fire at the constructor line. Offline surfaces
(warmup: False, e.g. the planner profiler) compile per call by design
and do not make their callers dispatch functions.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import Project, Rule, SourceFile, Violation, dotted_name
from ..shard.callgraph import _walk_with_chain
from .registry import (
    BUCKETING_MODULE,
    COMPILE_MODULE,
    SCOPES,
    accepted_names,
    load_bucketing_helpers,
    load_compile_surfaces,
)

# host numpy only: dispatch operands are minted host-side with np.*;
# jnp constructors inside traced code take trace-time shapes (a bad dim
# there fails at trace, it does not silently mint compile variants)
_CTOR_BASES = {"np", "numpy"}
_CTOR_NAMES = {"zeros", "full", "ones", "empty"}
_PARTIAL_NAMES = {"partial", "functools.partial"}

#: recursion ceiling — dispatch shape math is shallow; anything deeper
#: is already unreadable enough to deserve a bucketing helper
_MAX_DEPTH = 24


def _tail(name: str) -> str:
    return name.rsplit(".", 1)[-1]


class _Bounds:
    """Boundedness oracle for one file, memoized across self-attributes."""

    def __init__(self, src: SourceFile, helpers: Set[str]):
        self.src = src
        self.helpers = helpers
        #: self.<attr> -> every value assigned to it anywhere in the file
        self.self_attrs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    for el in tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]:
                        if (
                            isinstance(el, ast.Attribute)
                            and isinstance(el.value, ast.Name)
                            and el.value.id == "self"
                        ):
                            self.self_attrs.setdefault(el.attr, []).append(
                                node.value
                            )
        self._attr_memo: Dict[str, Optional[bool]] = {}

    # ------------------------------------------------------------------ #

    def _local_defs(
        self, func: ast.AST, name: str
    ) -> List[Tuple[int, ast.AST]]:
        """(line, value) pairs assigned to `name` directly in func's
        scope — Assign, AnnAssign, AugAssign (the value being added)."""
        out: List[Tuple[int, ast.AST]] = []
        stack = list(ast.iter_child_nodes(func))
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    els = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                    for el in els:
                        if isinstance(el, ast.Name) and el.id == name:
                            # tuple-unpack from a call: bounded only when
                            # the call is a registered helper
                            out.append((node.lineno, node.value))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if isinstance(node.target, ast.Name) and node.target.id == name:
                    out.append((node.lineno, node.value))
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name) and node.target.id == name:
                    out.append((node.lineno, node.value))
            stack.extend(ast.iter_child_nodes(node))
        out.sort(key=lambda p: p[0])
        return out

    def bounded(
        self, node: ast.AST, chain: Tuple[ast.AST, ...], at_line: int,
        depth: int = 0,
    ) -> bool:
        if depth > _MAX_DEPTH:
            return False
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Name):
            if node.id == "self":
                return False
            for func in reversed(chain):
                # strictly-prior assignments only: a name on its own
                # assignment line (`T_pad = ... T_pad ...`) refers to the
                # previous binding, not itself
                defs = [
                    (ln, v)
                    for ln, v in self._local_defs(func, node.id)
                    if ln < at_line
                ]
                if defs:
                    return all(
                        self.bounded(v, chain, ln, depth + 1)
                        for ln, v in defs
                    )
            return False
        if isinstance(node, ast.Attribute):
            if node.attr == "shape":
                return True
            dotted = dotted_name(node)
            if dotted and any("config" in seg for seg in dotted.split(".")):
                return True
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                return self._self_attr_bounded(node.attr, depth)
            return self.bounded(node.value, chain, at_line, depth + 1)
        if isinstance(node, ast.Subscript):
            return self.bounded(node.value, chain, at_line, depth + 1)
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            tail = _tail(fname) if fname else ""
            if tail.lstrip("_") in self.helpers:
                return True
            if tail == "min":
                return any(
                    self.bounded(a, chain, at_line, depth + 1)
                    for a in node.args
                )
            if tail in ("max", "abs", "int", "round"):
                return bool(node.args) and all(
                    self.bounded(a, chain, at_line, depth + 1)
                    for a in node.args
                )
            return False
        if isinstance(node, ast.BinOp):
            left = self.bounded(node.left, chain, at_line, depth + 1)
            if isinstance(node.op, (ast.FloorDiv, ast.Div, ast.RShift)):
                # floor/shift division shrinks a positive int: the left
                # bound carries
                return left
            right = self.bounded(node.right, chain, at_line, depth + 1)
            if isinstance(node.op, ast.Mod):
                # a % b <= min(a, b-1): either side's bound carries
                return left or right
            return left and right
        if isinstance(node, ast.UnaryOp):
            return self.bounded(node.operand, chain, at_line, depth + 1)
        if isinstance(node, ast.IfExp):
            return self.bounded(
                node.body, chain, at_line, depth + 1
            ) and self.bounded(node.orelse, chain, at_line, depth + 1)
        if isinstance(node, (ast.Tuple, ast.List)):
            return all(
                self.bounded(e, chain, at_line, depth + 1) for e in node.elts
            )
        return False

    def _self_attr_bounded(self, attr: str, depth: int) -> bool:
        memo = self._attr_memo.get(attr, "absent")
        if memo is None:
            # in-progress: a cycle through bounded constructors stays
            # bounded (coinductive), and the outer frame settles the value
            return True
        if memo != "absent":
            return memo
        values = self.self_attrs.get(attr)
        if not values:
            self._attr_memo[attr] = False
            return False
        self._attr_memo[attr] = None
        result = all(
            self.bounded(v, (), getattr(v, "lineno", 0), depth + 1)
            for v in values
        )
        self._attr_memo[attr] = result
        return result


def _call_tails(func: ast.AST) -> Set[str]:
    """Simple names this def calls (own scope and nested), including
    functions deferred through `partial(fn, ...)`."""
    tails: Set[str] = set()
    for node in ast.walk(func):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted_name(node.func)
        if not fname:
            continue
        tails.add(_tail(fname))
        if _tail(fname) in _PARTIAL_NAMES and node.args:
            inner = dotted_name(node.args[0])
            if inner:
                tails.add(_tail(inner))
    return tails


def _shape_args(call: ast.Call) -> List[ast.AST]:
    """The shape-carrying expressions of a constructor/pad/reshape call,
    or [] when this call does not mint operand shapes."""
    fname = dotted_name(call.func)
    if not fname:
        return []
    parts = fname.split(".")
    tail = parts[-1]
    base_is_np = len(parts) >= 2 and parts[-2] in _CTOR_BASES
    if tail in _CTOR_NAMES and base_is_np:
        out = list(call.args[:1])
        out += [kw.value for kw in call.keywords if kw.arg == "shape"]
        return out
    if tail == "pad" and base_is_np:
        return list(call.args[1:2])
    # .reshape is deliberately NOT checked: the method cannot be typed to
    # its receiver, and the tree's reshapes are device-side (traced) —
    # a bad dim there fails at trace time instead of minting variants
    return []


class CompShapeBucketingRule(Rule):
    name = "comp-shape-bucketing"
    description = (
        "operand-shape dimensions at dispatch sites must resolve to a "
        "registered bucketing helper, a config bound, or closed "
        "arithmetic over those — an unbounded (request-derived) shape "
        "source is a steady-state recompile storm"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        surfaces, _, err = load_compile_surfaces(project)
        if err is not None:
            yield Violation(self.name, COMPILE_MODULE, 1, err)
            return
        helpers, _, err = load_bucketing_helpers(project)
        if err is not None:
            yield Violation(self.name, BUCKETING_MODULE, 1, err)
            return
        #: caller-side names that make a function a dispatch function
        triggers = {"_run_on_device"}
        for key, spec in surfaces.items():
            if spec.get("warmup"):
                triggers |= accepted_names(key, spec)
                triggers.add(key)
        helper_names = set(helpers)
        for src in project.in_scope(SCOPES):
            if src.rel in (COMPILE_MODULE, BUCKETING_MODULE):
                continue
            bounds = _Bounds(src, helper_names)
            dispatch_cache: Dict[int, bool] = {}

            def is_dispatch(func: ast.AST) -> bool:
                hit = dispatch_cache.get(id(func))
                if hit is None:
                    hit = bool(_call_tails(func) & triggers)
                    dispatch_cache[id(func)] = hit
                return hit

            for node, chain in _walk_with_chain(src.tree):
                if not isinstance(node, ast.Call) or not chain:
                    continue
                if not any(is_dispatch(f) for f in chain):
                    continue
                for shape in _shape_args(node):
                    dims = (
                        shape.elts
                        if isinstance(shape, (ast.Tuple, ast.List))
                        else [shape]
                    )
                    for dim in dims:
                        if bounds.bounded(dim, chain, node.lineno):
                            continue
                        try:
                            spelled = ast.unparse(dim)
                        except Exception:  # pragma: no cover
                            spelled = "<dim>"
                        yield Violation(
                            self.name, src.rel, node.lineno,
                            f"dispatch-operand dimension '{spelled}' does "
                            "not resolve to a registered bucketing helper "
                            f"({BUCKETING_MODULE}:BUCKETING_HELPERS) or a "
                            "config bound — a request-derived dimension "
                            "here compiles a new XLA program per distinct "
                            "value (steady-state recompile storm); route "
                            "it through next_pow2/bucket_for + a config "
                            "cap",
                        )
