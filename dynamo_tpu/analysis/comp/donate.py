"""Rule: comp-donation-safety — a donated operand is dead after the call.

`donate_argnums` tells XLA it may alias a donated input's buffer into
the outputs — the engine donates the KV pool into every decode/prefill
program so a multi-GB carry updates in place instead of doubling HBM.
The price: after the call returns, the caller's reference points at a
buffer XLA may have overwritten. On CPU jax usually copies and the bug
hides; on TPU a post-call read is silent wrong data — the worst failure
mode serving has.

The engine's safe idiom reassigns every donated carry in the SAME
statement that makes the call (the carry-patch idiom):

    first, self.kv_k, self.kv_v, self._rng = self._prefill_batch(
        self.params, self.kv_k, self.kv_v, ..., self._rng, ...)

The rule finds every call to a donating surface (COMPILE_SURFACES
entries with a non-empty donate tuple, matched by dispatch name within
the surface's own module) and, for each donated positional operand that
names a readable path (local or `self.` attribute):

  * same-statement reassignment of that path → safe;
  * otherwise the first later use of the path in the calling function
    decides: a store → safe (rebound before read), a read →
    use-after-donate, fired at the reading line.

Calls that forward `*args` are skipped (positions unknowable), as are
operands that are expressions rather than named paths (temporaries
nobody can read again). The match is textual-path, same-function — the
race-pack's await-atomicity style: under-approximate, zero-noise.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from ..core import Project, Rule, Violation, dotted_name
from ..shard.callgraph import _walk_with_chain
from .registry import COMPILE_MODULE, accepted_names, load_compile_surfaces


def _target_paths(stmt: ast.AST) -> set:
    """Dotted paths (re)bound by an assignment statement's targets."""
    out = set()
    if isinstance(stmt, ast.Assign):
        for tgt in stmt.targets:
            els = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) else [tgt]
            for el in els:
                if isinstance(el, ast.Starred):
                    el = el.value
                path = dotted_name(el)
                if path:
                    out.add(path)
    return out


def _uses_after(
    func: ast.AST, path: str, after_line: int
) -> Tuple[Optional[int], Optional[int]]:
    """(first read line, first store line) of `path` strictly after
    `after_line` in func's own scope (nested defs excluded: their
    execution time is unknowable, so they neither accuse nor excuse)."""
    first_read: Optional[int] = None
    first_store: Optional[int] = None
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        if isinstance(node, ast.Assign) and node.lineno > after_line:
            if path in _target_paths(node):
                if first_store is None or node.lineno < first_store:
                    first_store = node.lineno
            # the RHS may also read the path — the generic walk below
            # sees it (Load context nodes inside node.value)
        if isinstance(node, (ast.Name, ast.Attribute)):
            if (
                isinstance(getattr(node, "ctx", None), ast.Load)
                and node.lineno > after_line
                and dotted_name(node) == path
            ):
                if first_read is None or node.lineno < first_read:
                    first_read = node.lineno
        stack.extend(ast.iter_child_nodes(node))
    return first_read, first_store


class CompDonationSafetyRule(Rule):
    name = "comp-donation-safety"
    description = (
        "an operand donated by position to a staged surface must not be "
        "read in the caller after the call returns — reassign the carry "
        "in the call statement (use-after-donate is silent wrong data "
        "on TPU)"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        surfaces, _, err = load_compile_surfaces(project)
        if err is not None:
            yield Violation(self.name, COMPILE_MODULE, 1, err)
            return
        donating = {
            key: spec for key, spec in surfaces.items()
            if spec.get("donate")
        }
        by_module = {}
        for key, spec in donating.items():
            names = accepted_names(key, spec)
            by_module.setdefault(spec["module"], []).append((key, names))
        for rel, entries in by_module.items():
            src = project.get(rel)
            if src is None:
                continue
            # statement owning each expression node, for same-statement
            # carry detection
            stmt_of = {}
            for node in ast.walk(src.tree):
                if isinstance(node, ast.stmt):
                    # ast.walk is breadth-first, so deeper statements are
                    # visited later: plain assignment leaves each
                    # expression mapped to its INNERMOST enclosing stmt
                    # (the Assign, not the surrounding ClassDef)
                    for sub in ast.walk(node):
                        stmt_of[id(sub)] = node
            for node, chain in _walk_with_chain(src.tree):
                if not isinstance(node, ast.Call) or not chain:
                    continue
                fname = dotted_name(node.func)
                if not fname:
                    continue
                tail = fname.rsplit(".", 1)[-1]
                hit = None
                for k, names in entries:
                    if tail in names or tail.lstrip("_") == k:
                        hit = k
                        break
                if hit is None:
                    continue
                key, spec = hit, donating[hit]
                func = chain[-1]
                if func.name in accepted_names(key, spec):
                    # the staged def itself (self-recursion inside the
                    # surface) is device code, not a host caller
                    continue
                if any(isinstance(a, ast.Starred) for a in node.args):
                    continue
                stmt = stmt_of.get(id(node))
                rebound = _target_paths(stmt) if stmt is not None else set()
                end_line = (
                    getattr(stmt, "end_lineno", None) or node.lineno
                    if stmt is not None else node.lineno
                )
                for pos in spec["donate"]:
                    if pos >= len(node.args):
                        continue
                    path = dotted_name(node.args[pos])
                    if not path or path in rebound:
                        continue
                    read, store = _uses_after(func, path, end_line)
                    if read is not None and (
                        store is None or read <= store
                    ):
                        yield Violation(
                            self.name, src.rel, read,
                            f"'{path}' was donated to '{key}' (operand "
                            f"{pos}, donate_argnums="
                            f"{tuple(spec['donate'])}) at line "
                            f"{node.lineno} and is read here without "
                            "being rebound — after donation the buffer "
                            "may be aliased into the outputs and this "
                            "read is silent wrong data on TPU; rebind "
                            "the carry in the call statement (the "
                            "engine's carry-patch idiom) or pass a copy",
                        )
