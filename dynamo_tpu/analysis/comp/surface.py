"""Rule: comp-surface-registry — every staged surface is in the contract.

The compile contract is only worth enforcing if it is complete: a jit
closure added without a COMPILE_SURFACES entry is a surface the other
three comp rules (bucketing, donation, warmup) silently do not see, and
a registry entry whose surface was renamed or deleted is documentation
lying about the binary. Both directions fire:

  * a jit/pjit/shard_map/pallas_call staging point in the scoped dirs
    that resolves into no registry entry — at the callsite;
  * a registry entry no staging point matches — at its registry line;
  * a matched callsite whose spelled donate_argnums / static_argnames
    disagree with the registry's declared signature — at the callsite
    (the registry is the reviewed contract; the code drifted).

pallas_call staged inside a registered jit wrapper resolves into the
wrapper's entry (one surface, two staging layers), and signature diffs
are only checked where the signature is spelled (jit sites with literal
keywords).
"""

from __future__ import annotations

from typing import Iterator

from ..core import Project, Rule, Violation
from .registry import COMPILE_MODULE, load_compile_surfaces
from .scan import find_staged_sites, match_entry


class CompSurfaceRegistryRule(Rule):
    name = "comp-surface-registry"
    description = (
        "every jit/pjit/shard_map/pallas_call staging point resolves into "
        "engine/compile_registry.py:COMPILE_SURFACES with the declared "
        "donation/static signature; stale entries fire at their registry "
        "line"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        surfaces, lines, err = load_compile_surfaces(project)
        if err is not None:
            yield Violation(self.name, COMPILE_MODULE, 1, err)
            return
        matched = set()
        for site in find_staged_sites(project):
            key = match_entry(site, surfaces)
            if key is None:
                what = (
                    f"'{site.name}'" if site.name
                    else "(could not resolve a surface name — stage it as "
                    "a named def or a named binding)"
                )
                yield Violation(
                    self.name, site.src.rel, site.line,
                    f"staged surface {what} ({site.kind}) is not in "
                    f"COMPILE_SURFACES — every compile surface must "
                    f"declare its variant axes, donation signature, and "
                    f"warmup obligation in {COMPILE_MODULE}",
                )
                continue
            matched.add(key)
            spec = surfaces[key]
            if site.kind in ("jit", "pjit"):
                if site.donate is not None:
                    declared = tuple(sorted(spec.get("donate", ())))
                    spelled = tuple(sorted(site.donate))
                    if spelled != declared:
                        yield Violation(
                            self.name, site.src.rel, site.line,
                            f"'{key}' spells donate_argnums={spelled} but "
                            f"COMPILE_SURFACES['{key}'] declares "
                            f"{declared} — donation is part of the "
                            "reviewed compile contract (memory aliasing "
                            "AND use-after-donate surface); update the "
                            "registry in the same change",
                        )
                if site.static is not None:
                    declared = tuple(sorted(spec.get("static", ()), key=str))
                    spelled = tuple(sorted(site.static, key=str))
                    if spelled != declared:
                        yield Violation(
                            self.name, site.src.rel, site.line,
                            f"'{key}' spells static args {spelled} but "
                            f"COMPILE_SURFACES['{key}'] declares "
                            f"{declared}",
                        )
            elif site.kind != spec.get("kind"):
                # a pallas_call inside a registered jit wrapper is the
                # same surface; any other kind drift is a real rewrite
                if not (
                    site.kind == "pallas_call"
                    and spec.get("kind") in ("jit", "pjit")
                ):
                    yield Violation(
                        self.name, site.src.rel, site.line,
                        f"'{key}' is staged via {site.kind} but "
                        f"COMPILE_SURFACES['{key}'] declares kind "
                        f"'{spec.get('kind')}'",
                    )
        for key in surfaces:
            if key not in matched:
                yield Violation(
                    self.name, COMPILE_MODULE, lines[key],
                    f"COMPILE_SURFACES['{key}'] matches no staged "
                    f"callsite in {surfaces[key].get('module')} — stale "
                    "entry (surface renamed or deleted); registry and "
                    "code must move together",
                )
