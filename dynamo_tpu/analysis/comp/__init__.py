"""dynocomp: the compile-contract pack.

Four rules anchored to `engine/compile_registry.py:COMPILE_SURFACES`
and `engine/bucketing.py:BUCKETING_HELPERS` (both AST-parsed, never
imported): comp-surface-registry (every staged callsite resolves into
the registry with its declared donation/static signature, stale entries
fire), comp-shape-bucketing (dispatch-operand shape dimensions resolve
to registered bucketing helpers or config bounds), comp-donation-safety
(no caller reads a donated operand after the call returns), and
comp-warmup-coverage (every warmup-obligated surface stays reachable
from JaxEngine.warmup). See docs/static_analysis.md and
docs/compilation.md.
"""

from .bucket import CompShapeBucketingRule
from .donate import CompDonationSafetyRule
from .registry import (
    BUCKETING_MODULE,
    COMPILE_MODULE,
    load_bucketing_helpers,
    load_compile_surfaces,
)
from .surface import CompSurfaceRegistryRule
from .warmup import CompWarmupCoverageRule

COMP_RULES = (
    CompSurfaceRegistryRule,
    CompShapeBucketingRule,
    CompDonationSafetyRule,
    CompWarmupCoverageRule,
)

__all__ = [
    "BUCKETING_MODULE",
    "COMPILE_MODULE",
    "COMP_RULES",
    "CompDonationSafetyRule",
    "CompShapeBucketingRule",
    "CompSurfaceRegistryRule",
    "CompWarmupCoverageRule",
    "load_bucketing_helpers",
    "load_compile_surfaces",
]
