"""Rule: comp-warmup-coverage — serving surfaces are warmup-reachable.

`JaxEngine.warmup` drives the real `generate` path over every dispatch
variant before the worker registers with the control plane, because a
first-request compile is 20-40s through the remote-compile tunnel —
long enough to lapse discovery leases and break in-flight streams. A
surface that serves traffic but is NOT reachable from warmup's call
graph compiles on a live request: a cold-compile TTFT spike that SLOs
see and replay benches don't (warmup hides it locally).

Every COMPILE_SURFACES entry marked `warmup: True` must therefore stay
reachable from `JaxEngine.warmup` through the simple-name call graph
(shard/callgraph machinery: attribute calls by tail name, `partial`
as a deferred call, dispatch aliases from the registry hopped to their
staged defs). An unreachable warmup-obligated surface fires at its
registry line; surfaces serving no live traffic (KV-transfer RPC
targets, the offline profiler) declare `warmup: False` and are exempt —
flipping a flag to False is a reviewable statement that cold compiles
are acceptable for that surface.

Name-level reachability over-approximates (same-named defs conflate),
which is the safe direction: a surface this rule flags is unreachable
under EVERY resolution of the names.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from ..core import Project, Rule, Violation, dotted_name
from ..shard.callgraph import FunctionIndex
from .registry import (
    COMPILE_MODULE,
    accepted_names,
    load_compile_surfaces,
)
from .scan import find_staged_sites, match_entry

_ENGINE_MODULE = "dynamo_tpu/engine/engine.py"
_PARTIAL_NAMES = {"partial", "functools.partial"}


def _called_tails(func: ast.AST) -> Set[str]:
    """Names this def may invoke: call tails, plus function references
    handed onward as call arguments — `_run_on_device(self._dev_block)`
    and `partial(self._dev_block_lora, idx)` both count (the engine
    passes its device closures by reference everywhere)."""
    tails: Set[str] = set()
    for node in ast.walk(func):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted_name(node.func)
        if not fname:
            continue
        tails.add(fname.rsplit(".", 1)[-1])
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Call) and dotted_name(
                arg.func
            ) in _PARTIAL_NAMES and arg.args:
                arg = arg.args[0]
            ref = dotted_name(arg)
            if ref:
                tails.add(ref.rsplit(".", 1)[-1])
    return tails


class CompWarmupCoverageRule(Rule):
    name = "comp-warmup-coverage"
    description = (
        "every COMPILE_SURFACES entry marked warmup: True must be "
        "reachable from JaxEngine.warmup's call graph — a serving "
        "surface missing from warmup is a cold-compile TTFT spike on a "
        "live fleet"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        surfaces, lines, err = load_compile_surfaces(project)
        if err is not None:
            yield Violation(self.name, COMPILE_MODULE, 1, err)
            return
        index = FunctionIndex(project)
        seeds = [
            info for info in index.functions.get("warmup", ())
            if info.src.rel == _ENGINE_MODULE
        ]
        if not seeds:
            yield Violation(
                self.name, COMPILE_MODULE, 1,
                f"no `warmup` def in {_ENGINE_MODULE} — the compile drive "
                "JaxEngine.warmup is gone, so every warmup-obligated "
                "surface is a cold compile",
            )
            return
        # alias -> staged def names, so `self._spec_block_fn(...)` hops
        # into the `spec_block` def
        alias_defs = {}
        for key, spec in surfaces.items():
            for name in accepted_names(key, spec):
                alias_defs.setdefault(name, set()).add(key)
                alias_defs.setdefault(name, set()).update(
                    spec.get("dispatch", ())
                )
        visited: Set[str] = set()
        called: Set[str] = set()
        queue: List = list(seeds)
        queued: Set[int] = {id(info.node) for info in seeds}
        while queue:
            info = queue.pop()
            visited.add(info.node.name)
            for tail in _called_tails(info.node):
                called.add(tail)
                hops = {tail, tail.lstrip("_")}
                hops |= alias_defs.get(tail, set())
                for hop in hops:
                    for cand in index.functions.get(hop, ()):
                        if id(cand.node) not in queued:
                            queued.add(id(cand.node))
                            queue.append(cand)
        reached_names = visited | called | {t.lstrip("_") for t in called}
        # a surface whose staging point sits inside a visited def (the
        # ops kernels inside their jit wrappers, shard_map inside
        # ring_attention) is reached through that def
        site_reached: Set[str] = set()
        for site in find_staged_sites(project):
            key = match_entry(site, surfaces)
            if key is None:
                continue
            names = set(site.enclosing)
            if site.name:
                names.add(site.name)
            if names & visited:
                site_reached.add(key)
        for key, spec in surfaces.items():
            if not spec.get("warmup"):
                continue
            if accepted_names(key, spec) & reached_names:
                continue
            if key in site_reached:
                continue
            yield Violation(
                self.name, COMPILE_MODULE, lines[key],
                f"COMPILE_SURFACES['{key}'] is marked warmup: True but "
                "is not reachable from JaxEngine.warmup's call graph — "
                "its first compile will happen on a live request (20-40s "
                "cold-compile TTFT spike); drive it from warmup, or "
                "declare warmup: False if it genuinely serves no live "
                "traffic",
            )
