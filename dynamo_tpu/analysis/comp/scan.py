"""Staged-callsite discovery for the comp pack.

Finds every jit/pjit/shard_map/pallas_call staging point in the scoped
package dirs and resolves each to the NAME a maintainer (and the
COMPILE_SURFACES registry) knows it by:

  * a jit-decorated def (`@jax.jit`, `@partial(jax.jit, ...)`) — the
    def's own name;
  * a jit call assigned to a binding (`self._fwd = jax.jit(...)`,
    `decode_step = jax.jit(_decode, ...)`) — the assignment target's
    tail name;
  * a shard_map staging call — the simple name of the function being
    mapped, resolved through `functools.partial`;
  * a bare `pl.pallas_call(...)` — the enclosing def's name (the ops
    kernels stage pallas_call inside their jit wrapper, so the site
    resolves into the wrapper's registry entry).

Each site also carries the donate/static signature spelled at the
callsite so comp-surface-registry can diff it against the registry's
declared contract.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Tuple

from ..core import Project, SourceFile, dotted_name
from ..shard.callgraph import _walk_with_chain
from .registry import SCOPES

_JIT_NAMES = {"jit", "jax.jit", "pjit", "jax.pjit"}
_SHARD_MAP_NAMES = {
    "shard_map", "jax.shard_map", "jax.experimental.shard_map.shard_map",
}
_PALLAS_NAMES = {"pallas_call", "pl.pallas_call"}
_PARTIAL_NAMES = {"partial", "functools.partial"}


@dataclasses.dataclass
class StagedSite:
    """One staging point: where, what kind, and the declared contract."""

    src: SourceFile
    line: int
    kind: str  # "jit" | "pjit" | "shard_map" | "pallas_call"
    name: Optional[str]  # resolved surface-side name; None = unresolvable
    enclosing: Tuple[str, ...]  # enclosing def names, outermost first
    donate: Optional[tuple]  # donate_argnums literal; None = not literal
    static: Optional[tuple]  # static_argnames/nums literal
    has_donate_kw: bool = False
    has_static_kw: bool = False


def _literal_tuple(node: Optional[ast.AST]) -> Optional[tuple]:
    """A donate/static keyword value as a tuple, or None when it is not
    a pure literal (the registry diff is skipped, not guessed)."""
    if node is None:
        return None
    try:
        val = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None
    if isinstance(val, (int, str)):
        return (val,)
    if isinstance(val, (tuple, list)):
        return tuple(val)
    return None


def _staging_signature(call: ast.Call) -> Tuple[Optional[tuple], Optional[tuple], bool, bool]:
    """(donate, static, has_donate_kw, has_static_kw) from a jit call's
    keywords."""
    donate = static = None
    has_d = has_s = False
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            has_d = True
            donate = _literal_tuple(kw.value)
        elif kw.arg in ("static_argnums", "static_argnames"):
            has_s = True
            static = _literal_tuple(kw.value)
    return donate, static, has_d, has_s


def _jit_call_of(dec: ast.AST) -> Optional[ast.Call]:
    """The jit Call carrying the signature keywords for a decorator:
    `@jax.jit` → None (bare, no keywords), `@jax.jit(...)` → that call,
    `@partial(jax.jit, donate_argnums=...)` → the partial call (its
    keywords ARE jit's keywords)."""
    if isinstance(dec, ast.Call):
        inner = dotted_name(dec.func)
        if inner in _JIT_NAMES:
            return dec
        if inner in _PARTIAL_NAMES and dec.args and (
            dotted_name(dec.args[0]) in _JIT_NAMES
        ):
            return dec
    return None


def _is_jit_decorator(dec: ast.AST) -> bool:
    if dotted_name(dec) in _JIT_NAMES:
        return True
    return _jit_call_of(dec) is not None


def _kind_of(name: str) -> str:
    return "pjit" if name.rsplit(".", 1)[-1] == "pjit" else "jit"


def _first_arg_name(call: ast.Call) -> Optional[str]:
    """Simple name of the function a shard_map stages, through partial."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Call) and dotted_name(arg.func) in _PARTIAL_NAMES:
        if not arg.args:
            return None
        arg = arg.args[0]
    name = dotted_name(arg)
    return name.rsplit(".", 1)[-1] if name else None


def find_staged_sites(project: Project) -> List[StagedSite]:
    """Every staging point in the scoped dirs, identity-resolved."""
    sites: List[StagedSite] = []
    for src in project.in_scope(SCOPES):
        # jit calls consumed as decorators are reported through their def;
        # collect them so the call walk below skips the same node
        decorator_ids = set()
        assign_of: Dict[int, ast.Assign] = {}  # id(value-subtree node) -> stmt
        for node, chain in _walk_with_chain(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    decorator_ids.add(id(dec))
                    if not _is_jit_decorator(dec):
                        continue
                    jit_call = _jit_call_of(dec)
                    if jit_call is None:
                        donate, static, has_d, has_s = None, None, False, False
                    else:
                        donate, static, has_d, has_s = _staging_signature(
                            jit_call
                        )
                    dec_name = dotted_name(dec) or dotted_name(
                        getattr(dec, "func", dec)
                    )
                    if isinstance(dec, ast.Call) and dotted_name(
                        dec.func
                    ) in _PARTIAL_NAMES:
                        dec_name = dotted_name(dec.args[0])
                    sites.append(StagedSite(
                        src=src, line=node.lineno, kind=_kind_of(dec_name),
                        name=node.name,
                        enclosing=tuple(f.name for f in chain),
                        donate=donate if has_d else (),
                        static=static if has_s else (),
                        has_donate_kw=has_d, has_static_kw=has_s,
                    ))
            elif isinstance(node, ast.Assign):
                for sub in ast.walk(node.value):
                    assign_of[id(sub)] = node
        for node, chain in _walk_with_chain(src.tree):
            if not isinstance(node, ast.Call) or id(node) in decorator_ids:
                continue
            fname = dotted_name(node.func)
            if not fname:
                continue
            if fname in _JIT_NAMES:
                donate, static, has_d, has_s = _staging_signature(node)
                stmt = assign_of.get(id(node))
                name = None
                if stmt is not None and len(stmt.targets) == 1:
                    tgt = dotted_name(stmt.targets[0])
                    if tgt:
                        name = tgt.rsplit(".", 1)[-1]
                sites.append(StagedSite(
                    src=src, line=node.lineno, kind=_kind_of(fname),
                    name=name, enclosing=tuple(f.name for f in chain),
                    donate=donate if has_d else (),
                    static=static if has_s else (),
                    has_donate_kw=has_d, has_static_kw=has_s,
                ))
            elif fname in _SHARD_MAP_NAMES:
                sites.append(StagedSite(
                    src=src, line=node.lineno, kind="shard_map",
                    name=_first_arg_name(node),
                    enclosing=tuple(f.name for f in chain),
                    donate=(), static=(),
                ))
            elif fname in _PALLAS_NAMES:
                encl = tuple(f.name for f in chain)
                sites.append(StagedSite(
                    src=src, line=node.lineno, kind="pallas_call",
                    name=encl[-1] if encl else None,
                    enclosing=encl, donate=(), static=(),
                ))
    return sites


def match_entry(
    site: StagedSite, surfaces: Dict[str, dict]
) -> Optional[str]:
    """The registry key a site resolves to, or None.

    A site matches an entry when the modules agree and the site's name
    (or its enclosing def, for pallas_call staged inside a registered
    jit wrapper) is one of the entry's accepted names — the key, the
    `_<key>` attribute spelling, or a declared dispatch alias.
    """
    from .registry import accepted_names

    if site.name is None:
        return None
    for key, spec in surfaces.items():
        if spec.get("module") != site.src.rel:
            continue
        names = accepted_names(key, spec)
        if site.name in names or site.name.lstrip("_") == key:
            return key
    return None
