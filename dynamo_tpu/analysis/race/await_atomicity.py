"""Rule: race-await-atomicity — check-then-act must not tear across await.

The asyncio serving plane's classic silent failure: a coroutine TESTS a
piece of shared state, suspends at an `await` (any other task may run
and mutate that state), then ACTS on the stale answer:

    if slot.free:                  # test
        await allocate_pages()     # suspension — another task takes slot
        slot.free = False          # act on a stale check: double-booked

Within one async function, for each attribute path (`self.<attr>`,
`slot.<attr>`, ...), the rule looks for the event sequence

    TEST-READ  ->  await  ->  WRITE      (same path, same spelling)

with no re-validation between the LAST suspension and the write.  Two
idioms make the sequence safe and keep the rule quiet:

  * holding a lock across the region — test and write share an
    enclosing `async with`/`with` block;
  * re-checking after the suspension — a fresh test of the same path
    between the last await and the write (the engine's
    `if slot.done or self.slots[i] is not slot: return` pattern).

TEST-READS are reads in genuinely conditional positions: `if`/ternary/
`assert` tests, and the source/conditions of a filtering comprehension
(`[l for l in self._leases.values() if l.expired]` is a check whose
answer goes stale at the next await).  `while` tests are exempt as
anchors — `while not pred: await wake()` re-tests after every wake,
which is the condition-variable idiom — but they do count as
re-validation.  Writes are assignments, subscript stores, deletes, and
container-mutator calls; an awaited same-class method that mutates
`self.<attr>` is folded in as a write at the call site (one level), and
loops wrap: a write early in a loop body races the awaits of the
previous iteration.

Attributes registered in runtime/sync.py GUARDED_STATE are exempt here —
their discipline (lock/owner confinement) is race-guarded-state's job,
and confinement makes the tear impossible by construction.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import Project, Rule, SourceFile, Violation, dotted_name
from .common import (
    MUTATOR_METHODS,
    SNAPSHOT_CALLS,
    enclosing_classes,
    full_path,
)
from .registry import guarded_keys

# event kinds
_READ, _RECHECK_ONLY, _AWAIT, _WRITE = "read", "recheck", "await", "write"


@dataclasses.dataclass
class _Event:
    kind: str
    path: Optional[str]  # None for awaits
    line: int
    withs: Tuple[int, ...]  # ids of enclosing With/AsyncWith nodes
    loops: Tuple[int, ...]  # ids of enclosing loop nodes
    regions: Tuple[int, ...]  # ids of enclosing TERMINAL branches (a body
    # ending in return/raise never flows to the code after it — its
    # events are invisible to the fall-through path)

    def on_path_to(self, other: "_Event") -> bool:
        return set(self.regions) <= set(other.regions)


@dataclasses.dataclass
class _CalleeSummary:
    """What one level of `self.<meth>()` contributes: attr paths the
    method writes on `self` (and whether an await precedes the write),
    plus the self-attrs it READS — a callee that re-reads what it writes
    observes fresh state and is self-validating."""

    writes: Dict[str, bool]  # attr -> callee awaits before first write
    reads: "Set[str]"
    has_await: bool
    is_async: bool


def _summarize_callee(fn: ast.AST) -> _CalleeSummary:
    first_await: Optional[int] = None
    writes: Dict[str, int] = {}
    loads: List[ast.Attribute] = []
    write_receivers: Set[int] = set()  # Attribute node ids that ARE the write
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Await):
            if first_await is None or node.lineno < first_await:
                first_await = node.lineno
        if isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load) \
                and dotted_name(node.value) == "self":
            loads.append(node)
        tgt: Optional[ast.AST] = None
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Delete)):
            tgts = node.targets if isinstance(node, (ast.Assign, ast.Delete)) \
                else [node.target]
            for t in tgts:
                if isinstance(t, ast.Subscript):
                    t = t.value
                    write_receivers.add(id(t))
                if isinstance(t, ast.Attribute) and dotted_name(t.value) == "self":
                    writes.setdefault(t.attr, t.lineno)
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in MUTATOR_METHODS
        ):
            tgt = node.func.value
            if isinstance(tgt, ast.Attribute) and dotted_name(tgt.value) == "self":
                writes.setdefault(tgt.attr, tgt.lineno)
                write_receivers.add(id(tgt))
        stack.extend(ast.iter_child_nodes(node))
    # a mutator's own receiver observes existence, not freshness — only
    # an INDEPENDENT load of the attr counts as re-reading it
    reads = {n.attr for n in loads if id(n) not in write_receivers}
    return _CalleeSummary(
        writes={
            attr: first_await is not None and first_await < line
            for attr, line in writes.items()
        },
        reads=reads,
        has_await=first_await is not None,
        is_async=isinstance(fn, ast.AsyncFunctionDef),
    )


def _ends_terminal(body: List[ast.stmt]) -> bool:
    """A branch body whose last statement is return/raise never reaches
    the code after its enclosing if/try — continue/break are deliberately
    NOT terminal (they re-enter the loop, whose next iteration does reach
    that code)."""
    return bool(body) and isinstance(body[-1], (ast.Return, ast.Raise))


def _test_read_paths(expr: ast.AST) -> Iterator[Tuple[str, int]]:
    """Paths read inside a conditional expression, the snapshot calls
    stripped (testing `len(list(self.slots))` still reads self.slots)."""
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
            p = full_path(node)
            if p:
                yield p, node.lineno
                # also surface the receiver chain: a test of
                # `self.slots[i].free` goes stale when self.slots mutates
                inner = full_path(node.value)
                if inner:
                    yield inner, node.lineno
        stack.extend(ast.iter_child_nodes(node))


class _FunctionScanner:
    """Linearize one async function body into an ordered event stream."""

    def __init__(self, callees: Dict[str, _CalleeSummary]):
        self.callees = callees
        self.events: List[_Event] = []
        self._withs: List[int] = []
        self._loops: List[int] = []
        self._regions: List[int] = []

    # -- emit helpers -------------------------------------------------- #

    def _emit(self, kind: str, path: Optional[str], line: int):
        self.events.append(
            _Event(
                kind, path, line,
                tuple(self._withs), tuple(self._loops), tuple(self._regions),
            )
        )

    def _emit_test_reads(self, expr: ast.AST, recheck_only: bool = False):
        kind = _RECHECK_ONLY if recheck_only else _READ
        for path, line in _test_read_paths(expr):
            self._emit(kind, path, line)

    # -- traversal ----------------------------------------------------- #

    def scan(self, fn: ast.AST):
        self._stmts(fn.body)

    def _stmts(self, body: List[ast.stmt]):
        for stmt in body:
            self._stmt(stmt)

    def _branch(self, body: List[ast.stmt]):
        """An if/except branch: when it ends in return/raise its events
        never flow to the statements after the compound — scope them to a
        diverted region the judge filters by."""
        if not body:
            return
        if _ends_terminal(body):
            self._regions.append(id(body[0]))
            self._stmts(body)
            self._regions.pop()
        else:
            self._stmts(body)

    def _stmt(self, stmt: ast.stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(stmt, ast.If):
            self._emit_test_reads(stmt.test)
            self._expr(stmt.test)
            self._branch(stmt.body)
            self._branch(stmt.orelse)
            return
        if isinstance(stmt, ast.Assert):
            # asserts are developer invariants, not acted-on checks: they
            # revalidate but never anchor a check-then-act finding
            self._emit_test_reads(stmt.test, recheck_only=True)
            self._expr(stmt.test)
            return
        if isinstance(stmt, ast.While):
            # while-tests re-run after every in-loop await: they are the
            # SAFE retest idiom, so they revalidate but never anchor
            self._emit_test_reads(stmt.test, recheck_only=True)
            self._expr(stmt.test)
            self._loops.append(id(stmt))
            self._stmts(stmt.body)
            self._loops.pop()
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter)
            self._loops.append(id(stmt))
            self._stmts(stmt.body)
            self._loops.pop()
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr(item.context_expr)
            self._withs.append(id(stmt))
            self._stmts(stmt.body)
            self._withs.pop()
            return
        if isinstance(stmt, ast.Try):
            self._stmts(stmt.body)
            for handler in stmt.handlers:
                self._branch(handler.body)
            self._stmts(stmt.orelse)
            self._stmts(stmt.finalbody)
            return
        # simple statement.  For assignments, the RHS evaluates (and may
        # suspend) BEFORE the store lands — event order must match, or a
        # `self.x = await compute()` under an `if self.x:` hides its tear
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if getattr(stmt, "value", None) is not None:
                self._expr(stmt.value)
            for path, line in self._stmt_writes(stmt):
                self._emit(_WRITE, path, line)
            return
        if isinstance(stmt, ast.Delete):
            for path, line in self._stmt_writes(stmt):
                self._emit(_WRITE, path, line)
            return
        self._expr(stmt)

    def _stmt_writes(self, stmt: ast.stmt) -> Iterator[Tuple[str, int]]:
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Delete)):
            tgts = stmt.targets if isinstance(stmt, (ast.Assign, ast.Delete)) \
                else [stmt.target]
            stack: List[ast.AST] = list(tgts)
            while stack:
                t = stack.pop()
                if isinstance(t, (ast.Tuple, ast.List)):
                    stack.extend(t.elts)
                    continue
                if isinstance(t, ast.Starred):
                    stack.append(t.value)
                    continue
                if isinstance(t, ast.Subscript):
                    p = full_path(t.value)
                    if p:
                        yield p, t.lineno
                    continue
                p = full_path(t)
                if p:
                    yield p, t.lineno

    def _expr(self, node: ast.AST):
        """Walk an expression/statement in source order for awaits,
        mutator calls, comprehension filters, and ternary tests."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.IfExp):
            self._emit_test_reads(node.test)
            self._expr(node.test)
            self._expr(node.body)
            self._expr(node.orelse)
            return
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
            for gen in node.generators:
                if gen.ifs:
                    # a filtering comprehension over shared state is a
                    # check whose answer goes stale at the next await
                    self._emit_test_reads(gen.iter)
                    for cond in gen.ifs:
                        self._emit_test_reads(cond)
                self._expr(gen.iter)
                for cond in gen.ifs:
                    self._expr(cond)
            if hasattr(node, "elt"):
                self._expr(node.elt)
            else:
                self._expr(node.key)
                self._expr(node.value)
            return
        if isinstance(node, ast.Await):
            self._await(node)
            return
        if isinstance(node, ast.Call):
            for child in ast.iter_child_nodes(node):
                self._expr(child)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in MUTATOR_METHODS
            ):
                p = full_path(node.func.value)
                if p:
                    self._emit(_WRITE, p, node.lineno)
            # one level of sync same-class helper: self._meth() writing
            # self.<attr> acts at this site (a bare call to an ASYNC
            # method only builds a coroutine — nothing runs here).  A
            # callee that re-reads what it writes observes fresh state —
            # fold the read in as revalidation.
            summary = self._self_call_summary(node)
            if summary is not None and not summary.is_async:
                for attr in sorted(summary.writes):
                    if attr in summary.reads:
                        self._emit(_RECHECK_ONLY, f"self.{attr}", node.lineno)
                    self._emit(_WRITE, f"self.{attr}", node.lineno)
            return
        for child in ast.iter_child_nodes(node):
            self._expr(child)

    def _await(self, node: ast.Await):
        inner = node.value
        summary = (
            self._self_call_summary(inner) if isinstance(inner, ast.Call) else None
        )
        if summary is None or not summary.is_async:
            # walk inside for nested awaits/mutators in arguments; an
            # unresolvable awaitable is assumed to suspend
            self._expr(inner)
            self._emit(_AWAIT, None, node.lineno)
            return
        # awaited same-class coroutine: its arguments evaluate first,
        # then the folded-in writes order against the suspension the way
        # the callee body does.  A callee with no await of its own runs
        # inline without yielding — writes, but no suspension.
        for arg in inner.args:
            self._expr(arg)
        for kw in inner.keywords:
            self._expr(kw.value)
        before = [a for a, awaited_first in summary.writes.items() if not awaited_first]
        after = [a for a, awaited_first in summary.writes.items() if awaited_first]
        for attr in sorted(before):
            if attr in summary.reads:
                self._emit(_RECHECK_ONLY, f"self.{attr}", node.lineno)
            self._emit(_WRITE, f"self.{attr}", node.lineno)
        if summary.has_await:
            self._emit(_AWAIT, None, node.lineno)
        for attr in sorted(after):
            if attr in summary.reads:
                self._emit(_RECHECK_ONLY, f"self.{attr}", node.lineno)
            self._emit(_WRITE, f"self.{attr}", node.lineno)

    def _self_call_summary(self, call: ast.Call) -> Optional[_CalleeSummary]:
        if not isinstance(call.func, ast.Attribute):
            return None
        if dotted_name(call.func.value) != "self":
            return None
        return self.callees.get(call.func.attr)


class RaceAwaitAtomicityRule(Rule):
    name = "race-await-atomicity"
    description = (
        "a conditional read of shared state followed across an await by a "
        "write to the same state, with no spanning lock and no re-check "
        "after the suspension (check-then-act torn by the event loop)"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        exempt = guarded_keys(project)
        for src in project.files:
            yield from self._check_file(src, exempt)

    def _check_file(self, src: SourceFile, exempt: Set[str]) -> Iterator[Violation]:
        classes = enclosing_classes(src.tree)
        # per-class one-level callee summaries for self-method folding
        summaries: Dict[str, Dict[str, _CalleeSummary]] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls = classes.get(id(node), "")
                if cls:
                    summaries.setdefault(cls, {})[node.name] = _summarize_callee(node)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            cls = classes.get(id(node), "")
            callees = dict(summaries.get(cls, {}))
            callees.pop(node.name, None)  # no self-recursion folding
            scanner = _FunctionScanner(callees)
            scanner.scan(node)
            yield from self._judge(src, cls, node, scanner.events, exempt)

    def _judge(
        self,
        src: SourceFile,
        cls: str,
        fn: ast.AST,
        events: List[_Event],
        exempt: Set[str],
    ) -> Iterator[Violation]:
        seen: Set[Tuple[str, int, int]] = set()
        for wi, w in enumerate(events):
            if w.kind != _WRITE:
                continue
            path = w.path
            if path is None:
                continue
            if path.startswith("self.") and cls \
                    and f"{cls}.{path[5:]}" in exempt:
                continue
            hit = self._linear(events, wi) or self._wrapped(events, wi)
            if hit is None:
                continue
            read, awaited = hit
            # a lock spanning test and act makes the region atomic
            if set(read.withs) & set(w.withs):
                continue
            key = (path, read.line, w.line)
            if key in seen:
                continue
            seen.add(key)
            yield Violation(
                rule=self.name,
                path=src.rel,
                line=read.line,
                message=(
                    f"check-then-act on `{path}` torn by await: tested here, "
                    f"suspended at line {awaited.line} (any other task may "
                    f"mutate it), then written at line {w.line} with no "
                    "re-check and no spanning lock — hold a lock across the "
                    "region, re-validate after the await, or register the "
                    "attribute's confinement in runtime/sync.py GUARDED_STATE"
                ),
            )

    @staticmethod
    def _linear(
        events: List[_Event], wi: int
    ) -> Optional[Tuple[_Event, _Event]]:
        w = events[wi]
        path = w.path
        last_await: Optional[int] = None
        for i in range(wi - 1, -1, -1):
            ev = events[i]
            if not ev.on_path_to(w):
                continue  # a terminal branch never flows to this write
            if ev.kind == _AWAIT:
                last_await = i
                break
            if ev.kind in (_READ, _RECHECK_ONLY) and ev.path == path:
                return None  # revalidated after every suspension before the act
        if last_await is None:
            return None
        for i in range(last_await - 1, -1, -1):
            ev = events[i]
            if not ev.on_path_to(w):
                continue
            if ev.kind == _READ and ev.path == path:
                return ev, events[last_await]
        return None

    @staticmethod
    def _wrapped(
        events: List[_Event], wi: int
    ) -> Optional[Tuple[_Event, _Event]]:
        """Loop wrap-around: a write inside a loop follows the PREVIOUS
        iteration's awaits.  Fires when the loop body suspends, the test
        lives before the loop, and nothing inside the loop re-tests."""
        w = events[wi]
        if not w.loops:
            return None
        loop = w.loops[-1]
        loop_awaits = [
            e for e in events
            if e.kind == _AWAIT and loop in e.loops and e.on_path_to(w)
        ]
        if not loop_awaits:
            return None
        for e in events:
            if e.kind in (_READ, _RECHECK_ONLY) and e.path == w.path \
                    and loop in e.loops and e.on_path_to(w):
                return None  # re-tested every iteration
        first_in_loop = next(
            (i for i, e in enumerate(events) if loop in e.loops), len(events)
        )
        for i in range(first_in_loop - 1, -1, -1):
            e = events[i]
            if e.kind == _READ and e.path == w.path and e.on_path_to(w):
                # the stale test precedes the loop
                return e, loop_awaits[0]
        return None
