"""Rule: race-guarded-state — registered shared state keeps its guard.

`runtime/sync.py:GUARDED_STATE` is the single-spelling table of every
attribute whose concurrency discipline is a project contract (see that
module's docstring for the guard grammar).  This rule holds both ends:

  * `lock:<attr>` entries: every access (read or write) of the
    attribute inside the owning class — `__init__` exempt — must sit
    lexically under `with self.<attr>` / `async with self.<attr>` on
    the named lock (a local alias assigned from the lock attribute
    counts);
  * `single-task:<owner>` / `thread:<owner>` entries: every MUTATION
    site of the attribute inside the owning class must be `<owner>` or
    a function in the project-wide call closure of `<owner>` (reads are
    event-loop-atomic for tasks, and snapshot-required for threads —
    documented in runtime/sync.py);
  * stale/unresolvable entries fire AT THE REGISTRY LINE: a class,
    attribute, guard lock, or owner function that no longer exists must
    leave the table (and the generated docs/concurrency.md) with it.

Under-approximation: enforcement is scoped to the owning class's own
methods (nested closures are checked as their own scopes — a lock held
where a closure is DEFINED is not held where it runs); an external
accessor reaching through another object's attribute chain is invisible
to this rule and belongs to code review.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import Project, Rule, SourceFile, Violation, dotted_name, str_const
from .common import (
    MUTATOR_METHODS,
    call_closure,
    project_function_defs,
)

SYNC_MODULE = "dynamo_tpu/runtime/sync.py"

_GUARD_KINDS = ("lock", "single-task", "thread")


@dataclasses.dataclass(frozen=True)
class GuardEntry:
    cls: str
    attr: str
    kind: str  # "lock" | "single-task" | "thread"
    target: str  # lock attr or owner function name
    line: int  # registry line, for stale-entry anchoring

    @property
    def key(self) -> str:
        return f"{self.cls}.{self.attr}"


def load_guarded_state(
    project: Project,
) -> Tuple[Optional[List[GuardEntry]], Optional[str]]:
    """Parse GUARDED_STATE out of runtime/sync.py (AST only, never
    imported).  Returns (entries, error) — error is a human message when
    the registry is missing or malformed, reported as a violation like
    KNOWN_AXES/FRAME_TAGS/KNOWN_FAULT_POINTS."""
    src = project.get(SYNC_MODULE)
    if src is None:
        return None, f"{SYNC_MODULE} not found: the guarded-state registry is gone"
    table: Optional[ast.Dict] = None
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name) and tgt.id == "GUARDED_STATE" \
                    and isinstance(node.value, ast.Dict):
                table = node.value
    if table is None:
        return None, (
            f"{SYNC_MODULE} defines no GUARDED_STATE dict literal — the race "
            "rules need the guard registry as their source of truth"
        )
    entries: List[GuardEntry] = []
    for k, v in zip(table.keys, table.values):
        key = str_const(k) if k is not None else None
        spec = str_const(v)
        if key is None or spec is None:
            return None, (
                f"{SYNC_MODULE}: GUARDED_STATE keys and guard specs must be "
                "string literals"
            )
        if key.count(".") != 1:
            return None, (
                f"{SYNC_MODULE}: GUARDED_STATE key '{key}' is not "
                "'Class.attr'"
            )
        kind, sep, target = spec.partition(":")
        if not sep or kind not in _GUARD_KINDS or not target:
            return None, (
                f"{SYNC_MODULE}: GUARDED_STATE['{key}'] guard '{spec}' is not "
                f"'<kind>:<target>' with kind in {_GUARD_KINDS}"
            )
        cls, attr = key.split(".")
        entries.append(GuardEntry(cls, attr, kind, target, k.lineno))
    return entries, None


def guarded_keys(project: Project) -> Set[str]:
    """'Class.attr' keys of the registry; empty on load failure (the rule
    itself reports the failure — siblings just see no exemptions)."""
    entries, err = load_guarded_state(project)
    if err is not None or entries is None:
        return set()
    return {e.key for e in entries}


def _class_defs(project: Project) -> Dict[str, List[Tuple[SourceFile, ast.ClassDef]]]:
    out: Dict[str, List[Tuple[SourceFile, ast.ClassDef]]] = {}
    for src in project.files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                out.setdefault(node.name, []).append((src, node))
    return out


def _with_guards(with_node: ast.AST, lock_attr: str) -> bool:
    """True when a With/AsyncWith acquires `<recv>.<lock_attr>` (or a
    bare name equal to the lock attr — a local alias)."""
    for item in with_node.items:
        d = dotted_name(item.context_expr)
        if d and (d.endswith(f".{lock_attr}") or d == lock_attr):
            return True
    return False


def _class_scopes(cls: ast.ClassDef):
    """Every function scope in a class, nested closures included, each
    yielded once as (scope, is_class_init).  Nested defs are separate
    scopes: a lock held where a closure is DEFINED is not held where it
    runs (the closure body must take it again)."""
    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack = [meth]
        while stack:
            fn = stack.pop()
            yield fn, (fn is meth and meth.name == "__init__")
            for node in ast.walk(fn):
                if node is not fn and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    stack.append(node)


def _scope_walk(func: ast.AST):
    """(node, with_stack) inside one function scope only — no descent
    into nested defs/lambdas."""
    stack = [(func, ())]
    while stack:
        node, withs = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            child_withs = withs
            if isinstance(child, (ast.With, ast.AsyncWith)):
                child_withs = withs + (child,)
            yield child, child_withs
            stack.append((child, child_withs))


def _self_attr_nodes(func: ast.AST, attr: str):
    """(attribute-or-subscript node, with_stack, is_mutation) for every
    `self.<attr>` access in one scope.  A Subscript store/del on the
    attribute, and container-mutator calls, count as mutations."""
    for node, withs in _scope_walk(func):
        if isinstance(node, ast.Attribute) and node.attr == attr \
                and dotted_name(node.value) == "self":
            yield node, withs, isinstance(node.ctx, (ast.Store, ast.Del))
        elif (
            isinstance(node, ast.Subscript)
            and isinstance(node.ctx, (ast.Store, ast.Del))
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == attr
            and dotted_name(node.value.value) == "self"
        ):
            yield node, withs, True
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in MUTATOR_METHODS
            and isinstance(node.func.value, ast.Attribute)
            and node.func.value.attr == attr
            and dotted_name(node.func.value.value) == "self"
        ):
            yield node, withs, True


class RaceGuardedStateRule(Rule):
    name = "race-guarded-state"
    description = (
        "every access of an attribute registered in runtime/sync.py "
        "GUARDED_STATE happens under its declared guard (lock held / "
        "owner task-or-thread confinement), and stale registry entries fire"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        entries, err = load_guarded_state(project)
        if err is not None:
            yield Violation(rule=self.name, path=SYNC_MODULE, line=1, message=err)
            return
        classes = _class_defs(project)
        functions = project_function_defs(project)
        closures: Dict[str, Set[str]] = {}
        for entry in entries:
            defs = classes.get(entry.cls)
            if not defs:
                yield Violation(
                    rule=self.name, path=SYNC_MODULE, line=entry.line,
                    message=(
                        f"GUARDED_STATE entry '{entry.key}': class "
                        f"'{entry.cls}' no longer exists in the package — "
                        "remove the entry or fix the spelling"
                    ),
                )
                continue
            if entry.kind == "lock":
                yield from self._check_lock(entry, defs)
            else:
                if entry.target not in functions:
                    yield Violation(
                        rule=self.name, path=SYNC_MODULE, line=entry.line,
                        message=(
                            f"GUARDED_STATE entry '{entry.key}': owner "
                            f"function '{entry.target}' no longer exists — "
                            "the confinement claim is unverifiable; update "
                            "or remove the entry"
                        ),
                    )
                    continue
                if entry.target not in closures:
                    closures[entry.target] = call_closure(functions, entry.target)
                yield from self._check_confined(entry, defs, closures[entry.target])

    # ----------------------------------------------------------------- #

    def _check_lock(
        self, entry: GuardEntry, defs: List[Tuple[SourceFile, ast.ClassDef]]
    ) -> Iterator[Violation]:
        touched = False
        for src, cls in defs:
            has_lock = any(
                isinstance(n, ast.Attribute) and n.attr == entry.target
                and isinstance(n.ctx, ast.Store)
                for n in ast.walk(cls)
            )
            if not has_lock:
                yield Violation(
                    rule=self.name, path=SYNC_MODULE, line=entry.line,
                    message=(
                        f"GUARDED_STATE entry '{entry.key}': guard lock "
                        f"'{entry.target}' is never assigned in class "
                        f"'{entry.cls}' ({src.rel}) — the entry is "
                        "unresolvable; fix the lock name or the guard spec"
                    ),
                )
                continue
            for scope, is_init in _class_scopes(cls):
                for node, withs, _mut in _self_attr_nodes(scope, entry.attr):
                    touched = True
                    if is_init:
                        continue  # construction precedes sharing
                    if any(_with_guards(w, entry.target) for w in withs):
                        continue
                    yield Violation(
                        rule=self.name, path=src.rel, line=node.lineno,
                        message=(
                            f"access of {entry.key} outside `with "
                            f"self.{entry.target}` — GUARDED_STATE declares "
                            f"this attribute lock-guarded ({SYNC_MODULE}); "
                            "take the lock, or change/remove the registry "
                            "entry, or waive with a reason"
                        ),
                    )
        if not touched:
            yield Violation(
                rule=self.name, path=SYNC_MODULE, line=entry.line,
                message=(
                    f"GUARDED_STATE entry '{entry.key}' matches no access of "
                    f"self.{entry.attr} in class '{entry.cls}' — stale "
                    "registry weight; remove it"
                ),
            )

    def _check_confined(
        self,
        entry: GuardEntry,
        defs: List[Tuple[SourceFile, ast.ClassDef]],
        closure: Set[str],
    ) -> Iterator[Violation]:
        noun = "task" if entry.kind == "single-task" else "thread"
        touched = False
        for src, cls in defs:
            for scope, is_init in _class_scopes(cls):
                for node, _withs, mut in _self_attr_nodes(scope, entry.attr):
                    touched = True
                    if not mut or is_init:
                        continue
                    if scope.name in closure:
                        continue
                    yield Violation(
                        rule=self.name, path=src.rel, line=node.lineno,
                        message=(
                            f"mutation of {entry.key} outside its owner "
                            f"{noun} — GUARDED_STATE confines this attribute "
                            f"to '{entry.target}' (and its callees, "
                            f"{SYNC_MODULE}); route the mutation through the "
                            "owner, change/remove the registry entry, or "
                            "waive with a reason"
                        ),
                    )
        if not touched:
            yield Violation(
                rule=self.name, path=SYNC_MODULE, line=entry.line,
                message=(
                    f"GUARDED_STATE entry '{entry.key}' matches no access of "
                    f"self.{entry.attr} in class '{entry.cls}' — stale "
                    "registry weight; remove it"
                ),
            )
