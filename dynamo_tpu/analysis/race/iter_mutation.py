"""Rule: race-iter-mutation — don't await while iterating shared state.

`for x in self.<container>:` followed by an `await` in the loop body is
a suspension in the middle of a live iterator.  Any other task that
runs during the suspension and mutates the container either corrupts
the iteration (`RuntimeError: dictionary changed size`) or — worse on
lists — silently skips/duplicates elements.  Discovery instance caches,
`RequestPlaneClient._conns`, and engine slot dicts are all iterated on
notification paths exactly like this.

The rule fires on a sync `for` whose iterable reads a `self.<attr>`
container (bare, or through `.values()/.items()/.keys()`) when:

  * the loop body (same coroutine — nested defs excluded) contains an
    `await`, and
  * some OTHER function in the project mutates an attribute of that
    name (assignment / container-mutator call, matched by attribute
    name project-wide — same evidence contract as flow-task-lifecycle:
    collisions can only add a mutator, and a container nobody else
    mutates is loop-private), and
  * the iterable is not an atomic snapshot (`list(...)`, `tuple(...)`,
    `sorted(...)`, `.copy()`), and the loop is not under a spanning
    `with`/`async with` guard.

`async for` is exempt: the protocol objects it iterates (queues,
subscriptions, watches) are the sanctioned cross-task handoff, not a
shared container.  Fix by snapshotting (`list(self.<attr>.values())`),
or by holding the container's lock across the loop.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import Project, Rule, SourceFile, Violation, call_name, dotted_name
from .common import (
    MUTATOR_METHODS,
    SNAPSHOT_CALLS,
    contains_await,
    walk_same_scope,
)

_VIEW_METHODS = {"values", "items", "keys"}


def _iter_attr(expr: ast.AST) -> Optional[Tuple[str, bool]]:
    """(attr name, snapshotted) when a for-loop iterable reads a
    `self.<attr>` container; None otherwise."""
    snapshotted = False
    e = expr
    while isinstance(e, ast.Call):
        name = call_name(e)
        if name in SNAPSHOT_CALLS:
            snapshotted = True
            if not e.args:
                return None
            e = e.args[0]
            continue
        if isinstance(e.func, ast.Attribute) and e.func.attr in _VIEW_METHODS:
            e = e.func.value
            continue
        if isinstance(e.func, ast.Attribute) and e.func.attr == "copy":
            snapshotted = True
            e = e.func.value
            continue
        if isinstance(e.func, ast.Attribute) and e.func.attr == "get":
            # dict.get(topic, []) fetches ONE value; iterating it is only
            # safe if snapshotted — keep chasing the receiver
            e = e.func.value
            continue
        return None
    if isinstance(e, ast.Attribute) and dotted_name(e.value) == "self":
        return e.attr, snapshotted
    return None


def _project_mutators(project: Project) -> Dict[str, List[Tuple[str, int, str]]]:
    """attr name -> [(rel, line, function)] of mutation sites anywhere in
    the package (any receiver — name-based evidence)."""
    out: Dict[str, List[Tuple[str, int, str]]] = {}

    def add(attr: str, src: SourceFile, line: int, fn: str):
        out.setdefault(attr, []).append((src.rel, line, fn))

    for src in project.files:
        # map nodes to their enclosing function name cheaply
        stack: List[Tuple[ast.AST, str]] = [(src.tree, "<module>")]
        while stack:
            node, fname = stack.pop()
            for child in ast.iter_child_nodes(node):
                cname = fname
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    cname = child.name
                if isinstance(child, (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Delete)):
                    tgts = child.targets if isinstance(child, (ast.Assign, ast.Delete)) \
                        else [child.target]
                    for t in tgts:
                        tt = t.value if isinstance(t, ast.Subscript) else t
                        if isinstance(tt, ast.Attribute):
                            add(tt.attr, src, child.lineno, fname)
                elif (
                    isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr in MUTATOR_METHODS
                    and isinstance(child.func.value, ast.Attribute)
                ):
                    add(child.func.value.attr, src, child.lineno, fname)
                stack.append((child, cname))
    return out


class RaceIterMutationRule(Rule):
    name = "race-iter-mutation"
    description = (
        "no await inside a sync for-loop iterating a shared self.<attr> "
        "container that another function mutates, unless the iterable is "
        "a snapshot or the loop holds a spanning lock"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        mutators = _project_mutators(project)
        for src in project.files:
            yield from self._check_file(src, mutators)

    def _check_file(
        self, src: SourceFile, mutators: Dict[str, List[Tuple[str, int, str]]]
    ) -> Iterator[Violation]:
        for fn in ast.walk(src.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            yield from self._check_fn(src, fn, mutators)

    def _check_fn(
        self,
        src: SourceFile,
        fn: ast.AsyncFunctionDef,
        mutators: Dict[str, List[Tuple[str, int, str]]],
    ) -> Iterator[Violation]:
        # (for-node, under-with) in this coroutine's own body
        stack: List[Tuple[ast.AST, bool]] = [(fn, False)]
        loops: List[Tuple[ast.For, bool]] = []
        while stack:
            node, guarded = stack.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                child_guarded = guarded or isinstance(
                    child, (ast.With, ast.AsyncWith)
                )
                if isinstance(child, ast.For):
                    loops.append((child, guarded))
                stack.append((child, child_guarded))
        for loop, guarded in loops:
            got = _iter_attr(loop.iter)
            if got is None:
                continue
            attr, snapshotted = got
            if snapshotted or guarded:
                continue
            if not any(contains_await(s) for s in loop.body):
                continue
            enclosing = self._enclosing_fn_name(fn)
            foreign = [
                m for m in mutators.get(attr, [])
                if m[2] != enclosing
            ]
            if not foreign:
                continue
            where = ", ".join(
                f"{rel}:{line} ({fname})" for rel, line, fname in foreign[:3]
            )
            yield Violation(
                rule=self.name,
                path=src.rel,
                line=loop.lineno,
                message=(
                    f"awaiting inside a loop over `self.{attr}` — the "
                    "suspension lets any task mutate the container "
                    f"mid-iteration (mutators: {where}); iterate a snapshot "
                    f"(`list(self.{attr})`) or hold its guard across the loop"
                ),
            )

    @staticmethod
    def _enclosing_fn_name(fn: ast.AST) -> str:
        return getattr(fn, "name", "<module>")
