"""Rule: race-lock-order — lock acquisition stays ordered and primitive-pure.

Three hazards, over every `with`/`async with` acquisition of a lock the
package constructs (`asyncio.Lock/Semaphore/Condition`,
`threading.Lock/RLock/Condition`, attribute-held or local):

  * ORDER INVERSION — lock B acquired while holding A on one callgraph
    path, and A acquired while holding B on another.  Two tasks running
    the two paths deadlock.  The acquisition graph is interprocedural:
    holding A and calling `f()` charges A against every lock f (or its
    callees, bounded depth) acquires.
  * THREADING LOCK HELD ACROSS AWAIT — `with self._lock:` (a
    threading primitive) whose body suspends at an `await` parks the
    lock on a suspended task; any OTHER thread (and any other task that
    needs the lock via an executor hop) blocks the whole event loop
    when it tries to take it.
  * PRIMITIVE CONFUSION — a sync `with` on an asyncio lock (raises at
    runtime on 3.10+, silently does nothing useful before), or an
    `async with` on a threading lock (blocks the loop), e.g. touching
    an asyncio.Lock from the kvbm device-exec thread.

Lock identity: `self.<attr>` resolves against the enclosing class;
`<obj>.<attr>` resolves when exactly one class constructs a lock under
that attribute name (unique-attr matching — ambiguous names are
skipped); bare locals assigned a lock constructor resolve within their
function.  Resolution is under-approximate: an unresolvable context
expression participates in no edge.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import Project, Rule, SourceFile, Violation, call_name, dotted_name
from .common import enclosing_classes, walk_same_scope

_ASYNC_LOCKS = {
    "asyncio.Lock", "asyncio.Semaphore", "asyncio.BoundedSemaphore",
    "asyncio.Condition",
}
_THREAD_LOCKS = {
    "threading.Lock", "threading.RLock", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Condition",
}
#: conflation-bounded: call matching is by simple name, so deep chains
#: compound collisions (a `.drain()` on a StreamWriter is not the
#: server's drain()).  Two hops catch the real holder->helper->lock
#: shapes without manufacturing cross-subsystem edges.
_MAX_CALL_DEPTH = 2


def _lock_kind(value: ast.AST) -> Optional[str]:
    """'async' | 'thread' when the expression constructs a lock —
    directly, or as the default of a dict `.setdefault(key,
    asyncio.Lock())`-style call argument."""
    if not isinstance(value, ast.Call):
        return None
    name = call_name(value)
    if name in _ASYNC_LOCKS or name.split(".")[-1] in {
        n.split(".")[-1] for n in _ASYNC_LOCKS
    } and name.startswith("asyncio"):
        return "async"
    if name in _THREAD_LOCKS or (
        name.startswith("threading")
        and name.split(".")[-1] in {n.split(".")[-1] for n in _THREAD_LOCKS}
    ):
        return "thread"
    for arg in list(value.args) + [kw.value for kw in value.keywords]:
        k = _lock_kind(arg)
        if k:
            return k
    return None


@dataclasses.dataclass(frozen=True)
class _Acquisition:
    lock: str  # canonical lock id, e.g. "DiscoveryClient._lock"
    kind: str  # "async" | "thread" | "unknown"
    is_async_with: bool
    src_rel: str
    line: int
    with_node_id: int


class _LockIndex:
    """Project-wide lock declarations: class-attr locks (with kind) and
    the attr-name -> classes map for unique-attr resolution."""

    def __init__(self, project: Project):
        self.class_attr_kind: Dict[Tuple[str, str], str] = {}
        self.attr_classes: Dict[str, Set[str]] = {}
        for src in project.files:
            classes = enclosing_classes(src.tree)
            for node in ast.walk(src.tree):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                value = node.value
                if value is None:
                    continue
                kind = _lock_kind(value)
                if kind is None:
                    continue
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for tgt in targets:
                    if isinstance(tgt, ast.Attribute) \
                            and dotted_name(tgt.value) == "self":
                        cls = self._owner_class(src, tgt)
                        if cls:
                            self.class_attr_kind[(cls, tgt.attr)] = kind
                            self.attr_classes.setdefault(tgt.attr, set()).add(cls)

    @staticmethod
    def _owner_class(src: SourceFile, node: ast.AST) -> Optional[str]:
        # line-range containment: the innermost class whose span holds the node
        best: Optional[Tuple[int, str]] = None
        for cand in ast.walk(src.tree):
            if isinstance(cand, ast.ClassDef):
                end = getattr(cand, "end_lineno", cand.lineno)
                if cand.lineno <= node.lineno <= end:
                    if best is None or cand.lineno > best[0]:
                        best = (cand.lineno, cand.name)
        return best[1] if best else None

    def resolve(
        self,
        src: SourceFile,
        cls: str,
        func: Optional[ast.AST],
        expr: ast.AST,
    ) -> Optional[Tuple[str, str]]:
        """(lock id, kind) for a with-item context expression."""
        d = dotted_name(expr)
        if not d:
            return None
        if d.startswith("self.") and d.count(".") == 1:
            # `self` IS the enclosing class — never fall back to another
            # class that happens to share the attribute name
            attr = d.split(".")[1]
            kind = self.class_attr_kind.get((cls, attr))
            if kind:
                return f"{cls}.{attr}", kind
            return None
        if "." in d:
            return self._by_unique_attr(d.rsplit(".", 1)[1])
        # bare local: a lock constructed (or fetched from a lock dict) in
        # this function
        if func is not None:
            for node in walk_same_scope(func):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name) and tgt.id == d:
                            kind = _lock_kind(node.value)
                            if kind:
                                return (
                                    f"{src.rel}:{getattr(func, 'name', '?')}:{d}",
                                    kind,
                                )
        return None

    def _by_unique_attr(self, attr: str) -> Optional[Tuple[str, str]]:
        classes = self.attr_classes.get(attr)
        if classes and len(classes) == 1:
            cls = next(iter(classes))
            return f"{cls}.{attr}", self.class_attr_kind[(cls, attr)]
        return None


class RaceLockOrderRule(Rule):
    name = "race-lock-order"
    description = (
        "lock pairs are acquired in one global order on every callgraph "
        "path (inversion = deadlock candidate); threading locks are never "
        "held across an await; async/thread lock primitives are not "
        "confused across contexts"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        index = _LockIndex(project)
        # function name -> defs (with src), for interprocedural charging
        fn_defs: Dict[str, List[Tuple[SourceFile, ast.AST, str]]] = {}
        for src in project.files:
            classes = enclosing_classes(src.tree)
            for node in ast.walk(src.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn_defs.setdefault(node.name, []).append(
                        (src, node, classes.get(id(node), ""))
                    )
        # per-function direct acquisitions (with held-set context) and
        # calls made while holding each lock
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        first_acq: Dict[str, Tuple[str, int]] = {}

        def record_edge(a: str, b: str, src_rel: str, line: int, via: str):
            edges.setdefault((a, b), (src_rel, line, via))

        # direct scan + primitive-purity checks
        mixed: List[Violation] = []
        fn_summary: Dict[int, Tuple[List[_Acquisition], Dict[str, Set[str]]]] = {}
        for name, defs in fn_defs.items():
            for src, fn, cls in defs:
                acqs, calls_under = self._scan_function(
                    src, fn, cls, index, mixed
                )
                fn_summary[id(fn)] = (acqs, calls_under)
                for a in acqs:
                    first_acq.setdefault(a.lock, (a.src_rel, a.line))
        yield from mixed

        # nested (intra-function) edges + interprocedural edges
        for name, defs in fn_defs.items():
            for src, fn, cls in defs:
                acqs, calls_under = fn_summary[id(fn)]
                # intra-function nesting
                for i, outer in enumerate(acqs):
                    for inner in acqs:
                        if inner is outer:
                            continue
                        if self._nested_inside(src, fn, outer, inner):
                            record_edge(
                                outer.lock, inner.lock,
                                inner.src_rel, inner.line,
                                f"nested in {name}()",
                            )
                # calls made while holding a lock: charge transitively.
                # A sync holder can only execute sync callees (calling an
                # async def just builds a coroutine) — the asymmetry stops
                # name conflation from bridging sync thread-lock code into
                # the asyncio plane and back.
                holder_async = isinstance(fn, ast.AsyncFunctionDef)
                for lock, callees in calls_under.items():
                    if lock == "":
                        continue
                    seen: Set[str] = set()
                    frontier = [(c, holder_async) for c in callees]
                    depth = 0
                    while frontier and depth < _MAX_CALL_DEPTH:
                        nxt: List[Tuple[str, bool]] = []
                        for callee, may_async in frontier:
                            if callee in seen:
                                continue
                            seen.add(callee)
                            for csrc, cfn, _ccls in fn_defs.get(callee, ()):
                                cfn_async = isinstance(cfn, ast.AsyncFunctionDef)
                                if cfn_async and not may_async:
                                    continue
                                cacqs, ccalls = fn_summary[id(cfn)]
                                for a in cacqs:
                                    if a.lock != lock:
                                        record_edge(
                                            lock, a.lock, a.src_rel, a.line,
                                            f"{name}() holds it and calls "
                                            f"{callee}()",
                                        )
                                for sub in ccalls.get("", ()):  # calls anywhere
                                    nxt.append((sub, cfn_async))
                        frontier = nxt
                        depth += 1

        reported: Set[Tuple[str, str]] = set()
        for (a, b), (rel, line, via) in sorted(edges.items()):
            if (b, a) not in edges or (b, a) in reported or a == b:
                continue
            reported.add((a, b))
            rel2, line2, via2 = edges[(b, a)]
            yield Violation(
                rule=self.name,
                path=rel,
                line=line,
                message=(
                    f"lock-order inversion: `{b}` acquired under `{a}` here "
                    f"({via}), but `{a}` is acquired under `{b}` at "
                    f"{rel2}:{line2} ({via2}) — two tasks on these paths "
                    "deadlock; pick one global order"
                ),
            )

    # ----------------------------------------------------------------- #

    def _scan_function(
        self,
        src: SourceFile,
        fn: ast.AST,
        cls: str,
        index: _LockIndex,
        mixed: List[Violation],
    ) -> Tuple[List[_Acquisition], Dict[str, Set[str]]]:
        """Direct acquisitions in one function scope, the simple names of
        calls made while holding each (key "" = calls made anywhere in
        the function), and primitive-purity findings appended to
        `mixed`."""
        acqs: List[_Acquisition] = []
        calls_under: Dict[str, Set[str]] = {"": set()}
        is_async_fn = isinstance(fn, ast.AsyncFunctionDef)

        def visit(node: ast.AST, held: Tuple[str, ...]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                child_held = held
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    is_aw = isinstance(child, ast.AsyncWith)
                    acquired: List[str] = []
                    for item in child.items:
                        resolved = index.resolve(src, cls, fn, item.context_expr)
                        if resolved is None:
                            continue
                        lock, kind = resolved
                        acqs.append(_Acquisition(
                            lock, kind, is_aw, src.rel,
                            item.context_expr.lineno, id(child),
                        ))
                        calls_under.setdefault(lock, set())
                        if kind == "thread" and is_aw:
                            mixed.append(Violation(
                                rule=self.name, path=src.rel,
                                line=item.context_expr.lineno,
                                message=(
                                    f"`async with` on threading lock "
                                    f"`{lock}` — threading locks have no "
                                    "async protocol and would block the "
                                    "event loop; use asyncio.Lock, or a "
                                    "sync `with` on a non-loop thread"
                                ),
                            ))
                        elif kind == "async" and not is_aw:
                            where = (
                                "an async function" if is_async_fn
                                else "sync/thread context (asyncio locks "
                                "are event-loop-only — the kvbm "
                                "device-exec thread must use a "
                                "threading.Lock)"
                            )
                            mixed.append(Violation(
                                rule=self.name, path=src.rel,
                                line=item.context_expr.lineno,
                                message=(
                                    f"sync `with` on asyncio lock `{lock}` "
                                    f"in {where} — acquisition never "
                                    "suspends and raises on 3.10+; use "
                                    "`async with` on the loop, or switch "
                                    "primitives"
                                ),
                            ))
                        elif kind == "thread" and not is_aw and is_async_fn:
                            # held across await?
                            for sub in walk_same_scope(child):
                                if isinstance(sub, ast.Await):
                                    mixed.append(Violation(
                                        rule=self.name, path=src.rel,
                                        line=sub.lineno,
                                        message=(
                                            f"threading lock `{lock}` held "
                                            "across an await (acquired at "
                                            f"line {item.context_expr.lineno})"
                                            " — the suspended task parks the "
                                            "lock and any thread (or "
                                            "executor-hopping task) that "
                                            "wants it wedges the process; "
                                            "release before suspending or "
                                            "use asyncio.Lock"
                                        ),
                                    ))
                                    break
                        acquired.append(lock)
                    if acquired:
                        child_held = held + tuple(acquired)
                elif isinstance(child, ast.Call):
                    name = call_name(child)
                    if name:
                        simple = name.split(".")[-1]
                        calls_under[""].add(simple)
                        for lock in held:
                            calls_under.setdefault(lock, set()).add(simple)
                visit(child, child_held)

        visit(fn, ())
        return acqs, calls_under

    @staticmethod
    def _nested_inside(
        src: SourceFile, fn: ast.AST, outer: _Acquisition, inner: _Acquisition
    ) -> bool:
        """True when `inner`'s with-node sits inside `outer`'s with-node."""
        outer_node = inner_node = None
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                if id(node) == outer.with_node_id:
                    outer_node = node
                if id(node) == inner.with_node_id:
                    inner_node = node
        if outer_node is None or inner_node is None or outer_node is inner_node:
            return False
        return any(
            n is inner_node
            for n in ast.walk(outer_node)
        )
