"""dynorace: the interprocedural race / atomicity / lock-order pack.

Fourth rules pack on the analysis core.  Where dynoflow covers task
lifecycle and protocol drift, this pack covers the failure mode asyncio
makes easiest to write and hardest to see: shared state whose
check-then-act sequence is silently torn by an `await`, guarded state
accessed off its guard, lock pairs acquired in inconsistent orders, and
containers mutated under a suspended iterator.  Every open ROADMAP item
(SLA scheduler, radix prefix index, autoscaling soak) adds more
concurrently-mutated slot tables / scoring maps / block maps on top of
exactly this plane — the pack is the convention they land into.

The guard registry lives in `runtime/sync.py:GUARDED_STATE` (same
single-spelling pattern as ENV_REGISTRY / FRAME_TAGS /
KNOWN_FAULT_POINTS) and renders into docs/concurrency.md via
`--emit-sync-docs`.  See docs/static_analysis.md ("The race pack").
"""

from .await_atomicity import RaceAwaitAtomicityRule
from .iter_mutation import RaceIterMutationRule
from .lock_order import RaceLockOrderRule
from .registry import (
    GuardEntry,
    RaceGuardedStateRule,
    guarded_keys,
    load_guarded_state,
)

RACE_RULES = (
    RaceAwaitAtomicityRule,
    RaceGuardedStateRule,
    RaceLockOrderRule,
    RaceIterMutationRule,
)

__all__ = [
    "GuardEntry",
    "RACE_RULES",
    "RaceAwaitAtomicityRule",
    "RaceGuardedStateRule",
    "RaceIterMutationRule",
    "RaceLockOrderRule",
    "guarded_keys",
    "load_guarded_state",
]
