"""Shared AST machinery for the race pack.

All four race rules reason about the same three primitives:

  * attribute PATHS — `self.slots`, `slot.free`, `conn.streams` — the
    dotted receiver+attribute spelling of a piece of shared state as it
    appears inside one function.  Matching is textual within a function
    (the same spelling names the same object on every line of a method),
    which is exactly the granularity an `async with`/re-check fix
    operates at;
  * WRITES — assignments, augmented assignments, deletes, subscript
    stores, and calls to container mutators (`.append/.pop/.update/...`)
    on a path;
  * suspension points — `await` expressions, where the event loop can
    interleave any other task.

Nested `def`/`lambda` bodies are other coroutines/functions and are
never scanned as part of the enclosing one (same contract as
flow-cancellation-safety).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from ..core import Project, dotted_name

#: container-mutation method names treated as writes to their receiver.
#: Queue.put_nowait/get_nowait are deliberately absent: asyncio queues are
#: the sanctioned cross-task handoff primitive.
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "insert",
    "pop", "popleft", "popitem", "remove", "discard",
    "add", "clear", "update", "setdefault",
}

#: callables that take an atomic snapshot of a container (iterating the
#: result cannot race a concurrent mutation of the source)
SNAPSHOT_CALLS = {"list", "tuple", "sorted", "set", "dict", "frozenset"}


def attr_path(node: ast.AST) -> Optional[Tuple[str, str]]:
    """(receiver, attr) for an attribute access with a resolvable dotted
    receiver: `self.slots` -> ("self", "slots"), `self.kvbm._pending` ->
    ("self.kvbm", "_pending").  None for computed receivers."""
    if not isinstance(node, ast.Attribute):
        return None
    base = dotted_name(node.value)
    if not base:
        return None
    return base, node.attr


def full_path(node: ast.AST) -> Optional[str]:
    p = attr_path(node)
    return f"{p[0]}.{p[1]}" if p else None


def write_targets(stmt: ast.AST) -> Iterator[Tuple[str, int]]:
    """(path, line) of every attribute path a statement writes: direct
    assignment, subscript/attribute store on the path, del, aug-assign."""
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.Delete):
        targets = list(stmt.targets)
    for tgt in targets:
        # unpack tuple targets: `a.x, b.y = ...`
        stack = [tgt]
        while stack:
            t = stack.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
                continue
            if isinstance(t, ast.Starred):
                stack.append(t.value)
                continue
            if isinstance(t, ast.Subscript):
                # store INTO a container held at the path: self.slots[i]=x
                p = full_path(t.value)
                if p:
                    yield p, t.lineno
                continue
            p = full_path(t)
            if p:
                yield p, t.lineno


def mutator_calls(expr: ast.AST) -> Iterator[Tuple[str, int]]:
    """(path, line) for container-mutator calls on a path anywhere inside
    `expr` (not descending into nested defs)."""
    for node in walk_same_scope(expr):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in MUTATOR_METHODS
        ):
            p = full_path(node.func.value)
            if p:
                yield p, node.lineno


def walk_same_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Walk without descending into nested def/lambda bodies."""
    stack: List[ast.AST] = [node]
    while stack:
        n = stack.pop()
        if n is not node and isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def contains_await(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Await) for n in walk_same_scope(node))


def read_paths(expr: ast.AST) -> Iterator[Tuple[str, int]]:
    """(path, line) of attribute paths READ inside an expression."""
    for node in walk_same_scope(expr):
        if isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
            p = full_path(node)
            if p:
                yield p, node.lineno


def enclosing_classes(tree: ast.AST) -> Dict[int, str]:
    """id(function def node) -> name of its immediately enclosing class."""
    out: Dict[int, str] = {}
    stack: List[Tuple[ast.AST, Optional[str]]] = [(tree, None)]
    while stack:
        node, cls = stack.pop()
        for child in ast.iter_child_nodes(node):
            child_cls = cls
            if isinstance(child, ast.ClassDef):
                child_cls = child.name
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[id(child)] = cls or ""
                child_cls = None  # methods of nested classes only
            stack.append((child, child_cls))
    return out


def function_calls(func: ast.AST) -> Set[str]:
    """Simple names of every call target inside a function (descending
    into nested defs: a spawned/nested callee is still this function's
    code path for ownership-closure purposes)."""
    out: Set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name:
                out.add(name.split(".")[-1])
    return out


def call_closure(project_functions: Dict[str, List[ast.AST]], root: str,
                 max_depth: int = 12) -> Set[str]:
    """Simple-name BFS closure of functions reachable from `root` through
    project-wide call sites.  Under-approximation runs the permissive
    way: name conflation can only ALLOW more sites, never flag a
    legitimate one."""
    seen: Set[str] = {root}
    frontier = [root]
    depth = 0
    while frontier and depth < max_depth:
        nxt: List[str] = []
        for name in frontier:
            for fn in project_functions.get(name, ()):
                for callee in function_calls(fn):
                    if callee not in seen and callee in project_functions:
                        seen.add(callee)
                        nxt.append(callee)
        frontier = nxt
        depth += 1
    return seen


def project_function_defs(project: Project) -> Dict[str, List[ast.AST]]:
    """Simple name -> every def in the package (nested included)."""
    out: Dict[str, List[ast.AST]] = {}
    for src in project.files:
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.setdefault(node.name, []).append(node)
    return out
