"""dynolint framework: source model, suppression parsing, rule runner.

Design notes:
  * Rules are PROJECT-level, not file-level — the flagship rule
    (silent-drop) cross-references the request schema parsed in one layer
    against consumption sites two layers down, so the unit of analysis is
    the whole package tree.
  * Everything is stdlib `ast`; no third-party parser. The checker must
    run in CI and in the tier-1 test suite with zero extra deps.
  * Suppressions are line-scoped comments, mirroring the tools people
    already know: `# dynolint: disable=<rule>[,<rule>...] [-- reason]`.
    A directive on a pure-comment line applies to the next code line, so
    long expressions can carry their waiver above them. File-scoped:
    `# dynolint: disable-file=<rule>`.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

_DIRECTIVE = re.compile(
    # rule list only: a `-- reason` tail must never be parsed as more
    # rules (a comma inside the reason would silently widen the waiver)
    r"#\s*dynolint:\s*(disable|disable-file)="
    r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
)


@dataclasses.dataclass(frozen=True)
class Violation:
    """One rule finding, addressed by repo-relative path + 1-based line."""

    rule: str
    path: str
    line: int
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """A parsed module plus its suppression directives."""

    def __init__(self, root: Path, path: Path):
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=str(path))
        self._line_disables: Dict[int, Set[str]] = {}
        self._file_disables: Set[str] = set()
        self._parse_directives()

    def _parse_directives(self):
        # directives live in COMMENT tokens only — a directive QUOTED in a
        # docstring or string literal (e.g. docs describing the syntax)
        # must never take effect, so raw-line regex scanning is out
        try:
            comments = [
                tok
                for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline
                )
                if tok.type == tokenize.COMMENT
            ]
        except tokenize.TokenError:  # pragma: no cover - tree already parsed
            comments = []
        for tok in comments:
            m = _DIRECTIVE.search(tok.string)
            if not m:
                continue
            i = tok.start[0]
            kind = m.group(1)
            rules = {
                r.strip() for r in m.group(2).split(",") if r.strip()
            }
            if kind == "disable-file":
                self._file_disables |= rules
            else:
                self._line_disables.setdefault(i, set()).update(rules)
                if self.lines[i - 1].lstrip().startswith("#"):
                    # pure-comment line: the waiver covers the next CODE
                    # line — skip over blanks and further comment lines
                    j = i + 1
                    while j <= len(self.lines) and (
                        not self.lines[j - 1].strip()
                        or self.lines[j - 1].lstrip().startswith("#")
                    ):
                        j += 1
                    self._line_disables.setdefault(j, set()).update(rules)
        self._spread_over_statements()

    # compound statements own their bodies; a waiver inside a body must
    # NOT creep up to the header line (Match/TryStar guarded: 3.10/3.11)
    _COMPOUND = tuple(
        getattr(ast, n)
        for n in (
            "FunctionDef", "AsyncFunctionDef", "ClassDef",
            "For", "AsyncFor", "While", "If",
            "With", "AsyncWith", "Try", "TryStar", "Match",
        )
        if hasattr(ast, n)
    )

    def _spread_over_statements(self):
        """A waiver on ANY line of a multi-line simple statement covers the
        whole statement — black puts trailing comments on the closing
        paren, while violations anchor at the offending call's line."""
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.stmt) or isinstance(node, self._COMPOUND):
                continue
            end = getattr(node, "end_lineno", None) or node.lineno
            if end <= node.lineno:
                continue
            span = range(node.lineno, end + 1)
            rules = set()
            for ln in span:
                rules |= self._line_disables.get(ln, set())
            if rules:
                for ln in span:
                    self._line_disables.setdefault(ln, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self._file_disables:
            return True
        return rule in self._line_disables.get(line, set())


class Project:
    """The file set a lint run sees: every .py under `root`/`package`."""

    def __init__(self, root: Path, files: Sequence[SourceFile]):
        self.root = Path(root)
        self.files = list(files)
        self._by_rel = {f.rel: f for f in self.files}

    @classmethod
    def load(cls, root: Path, package: str = "dynamo_tpu") -> "Project":
        root = Path(root)
        base = root / package
        files = []
        errors = []
        for path in sorted(base.rglob("*.py")):
            if "analysis" in path.relative_to(base).parts[:1]:
                # the linter does not lint itself: its fixture strings and
                # pattern tables are full of the exact shapes it flags
                continue
            try:
                files.append(SourceFile(root, path))
            except SyntaxError as e:  # pragma: no cover - tree should parse
                errors.append(f"{path}: {e}")
        if errors:
            raise SyntaxError("unparseable files: " + "; ".join(errors))
        return cls(root, files)

    def get(self, rel: str) -> Optional[SourceFile]:
        return self._by_rel.get(rel)

    def in_scope(self, scopes: Sequence[str]) -> Iterator[SourceFile]:
        """Files whose package-relative path starts with any scope prefix.
        Scopes are relative to the package dir (e.g. "runtime/", "llm/")."""
        for f in self.files:
            rel = f.rel.split("/", 1)[1] if "/" in f.rel else f.rel
            if any(rel.startswith(s) for s in scopes):
                yield f


class Rule:
    """Base rule. Subclasses set `name`/`description` and yield Violations
    from `check`; the runner applies suppressions afterwards so rules never
    need to think about them."""

    name: str = "base"
    description: str = ""

    def check(self, project: Project) -> Iterator[Violation]:  # pragma: no cover
        raise NotImplementedError


def run(project: Project, rules: Iterable[Rule]) -> List[Violation]:
    out: List[Violation] = []
    for rule in rules:
        for v in rule.check(project):
            src = project.get(v.path)
            if src is not None and src.suppressed(v.rule, v.line):
                continue
            out.append(v)
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


def format_text(violations: Sequence[Violation]) -> str:
    if not violations:
        return "dynolint: clean"
    lines = [str(v) for v in violations]
    lines.append(f"dynolint: {len(violations)} violation(s)")
    return "\n".join(lines)


def format_json(violations: Sequence[Violation]) -> str:
    return json.dumps(
        {
            "violations": [v.to_dict() for v in violations],
            "count": len(violations),
        },
        indent=2,
    )


def format_sarif(
    violations: Sequence[Violation], rules: Sequence[Rule]
) -> str:
    """SARIF 2.1.0 — the interchange format CI annotation uploads speak.

    One run, one driver ("dynolint"), one reportingDescriptor per
    registered rule (so PR annotations link a finding to its contract
    description), one result per violation with a physical location
    anchored at the file/line a maintainer would fix.  Suppressed
    findings never reach this layer: `run()` filters them first, which
    is exactly the suppression-awareness SARIF consumers expect (a
    waived finding is not an annotation)."""
    by_name = {}
    for r in rules:
        by_name.setdefault(r.name, r.description)
    results = []
    for v in violations:
        results.append({
            "ruleId": v.rule,
            "level": "error",
            "message": {"text": v.message},
            "locations": [{
                "physicalLocation": {
                    # repo-relative URI: github's SARIF upload resolves it
                    # against the checkout root for inline PR annotations
                    "artifactLocation": {"uri": v.path},
                    "region": {"startLine": v.line},
                },
            }],
        })
        by_name.setdefault(v.rule, "")
    doc = {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
            "Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "dynolint",
                    "informationUri": (
                        "https://github.com/ltalal/dynamo-tpu/blob/main/"
                        "docs/static_analysis.md"
                    ),
                    "rules": [
                        {
                            "id": name,
                            "shortDescription": {"text": desc or name},
                        }
                        for name, desc in sorted(by_name.items())
                    ],
                },
            },
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)


# --------------------------------------------------------------------- #
# shared AST helpers used by several rules
# --------------------------------------------------------------------- #


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target: `time.sleep(..)` -> "time.sleep",
    `sleep(..)` -> "sleep". Unresolvable targets -> ""."""
    return dotted_name(node.func)


def dotted_name(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
