"""dynolint CLI.

    python -m dynamo_tpu.analysis                      # lint, text output
    python -m dynamo_tpu.analysis --format=json        # lint, JSON output
    python -m dynamo_tpu.analysis --format=sarif       # SARIF 2.1.0 (CI
                                                       # PR annotations)
    python -m dynamo_tpu.analysis --rules silent-drop  # subset
    python -m dynamo_tpu.analysis --rules race         # a whole pack
    python -m dynamo_tpu.analysis --changed-only       # report only files
                                                       # touched vs HEAD
    python -m dynamo_tpu.analysis --list-rules
    python -m dynamo_tpu.analysis --emit-env-docs docs/configuration.md
    python -m dynamo_tpu.analysis --emit-sync-docs     # docs/concurrency.md
    python -m dynamo_tpu.analysis --emit-metrics-docs  # docs/observability.md
    python -m dynamo_tpu.analysis --emit-compile-docs  # docs/compilation.md

Exit status: 0 clean, 1 violations found, 2 usage error.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path
from typing import List, Optional

from .core import Project, format_json, format_sarif, format_text, run
from .rules import ALL_RULES, PACKS, default_rules


def emit_env_docs(root: Path) -> str:
    """Render runtime/config.py's ENV_REGISTRY as the configuration doc.

    config.py is executed in ISOLATION (spec_from_file_location, no package
    __init__) so the CLI needs none of the package's dependencies and
    renders the registry of the tree under --root, not whatever
    installation happens to be importable."""
    import importlib.util

    cfg_path = root / "dynamo_tpu" / "runtime" / "config.py"
    spec = importlib.util.spec_from_file_location("_dynolint_config", cfg_path)
    module = importlib.util.module_from_spec(spec)
    # dataclasses resolves the module through sys.modules at class-creation
    # time; exec without registration breaks it
    sys.modules[spec.name] = module
    try:
        spec.loader.exec_module(module)
        ENV_REGISTRY = module.ENV_REGISTRY
    finally:
        sys.modules.pop(spec.name, None)

    lines = [
        "# Configuration — environment variables",
        "",
        "<!-- GENERATED FILE — do not edit by hand.",
        "     Regenerate: python -m dynamo_tpu.analysis --emit-env-docs"
        " docs/configuration.md -->",
        "",
        "Every environment variable the package consults, from the single",
        "registry in `dynamo_tpu/runtime/config.py` (`ENV_REGISTRY`). The",
        "`env-registry` dynolint rule fails CI on any env read that",
        "bypasses this table.",
        "",
        "| Variable | Type | Default | Consumed by | Description |",
        "|---|---|---|---|---|",
    ]
    for var in sorted(ENV_REGISTRY, key=lambda v: v.name):
        default = "—" if var.default is None else f"`{var.default}`"
        lines.append(
            f"| `{var.name}` | {var.type} | {default} | `{var.module}` "
            f"| {var.description} |"
        )
    lines.append("")
    return "\n".join(lines)


#: markers delimiting the generated block in docs/fault_tolerance.md
FAULT_BEGIN = (
    "<!-- FAULT_POINTS:BEGIN — generated from runtime/faults.py:"
    "KNOWN_FAULT_POINTS; regenerate: python -m dynamo_tpu.analysis"
    " --emit-fault-docs -->"
)
FAULT_END = "<!-- FAULT_POINTS:END -->"


def render_fault_table(root: Path) -> str:
    """Render runtime/faults.py's KNOWN_FAULT_POINTS as a markdown table.

    Parsed from the AST (never imported — faults.py installs a process
    injector at import time), so this works on hosts without the
    package's deps, like --emit-env-docs."""
    import ast

    from .flow.fault_registry import FAULTS_MODULE, load_fault_points

    tree = ast.parse((root / FAULTS_MODULE).read_text())
    points, _, err = load_fault_points(tree)
    if err is not None:
        raise SystemExit(f"error: {err}")
    lines = [
        "| Point | Actions — where it bites |",
        "|---|---|",
    ]
    for name, desc in points.items():  # registry order is the doc order
        lines.append(f"| `{name}` | {desc.replace('|', chr(92) + '|')} |")
    return "\n".join(lines)


def splice_generated(text: str, begin: str, end: str, table: str,
                     target: Path, what: str) -> str:
    """Replace the block between the `begin`/`end` markers of `text` with
    `table`; every generated-docs emitter shares this shape."""
    if begin not in text or end not in text:
        raise SystemExit(
            f"error: {target} has no {what}:BEGIN/END markers to "
            "splice the generated table into"
        )
    head, rest = text.split(begin, 1)
    _, tail = rest.split(end, 1)
    return head + begin + "\n" + table + "\n" + end + tail


def emit_fault_docs(root: Path, target: Path) -> str:
    """Splice the generated point table between the FAULT_POINTS markers
    of `target` (docs/fault_tolerance.md) and return the new content."""
    return splice_generated(
        target.read_text(), FAULT_BEGIN, FAULT_END,
        render_fault_table(root), target, "FAULT_POINTS",
    )


#: markers delimiting the generated block in docs/concurrency.md
SYNC_BEGIN = (
    "<!-- GUARDED_STATE:BEGIN — generated from runtime/sync.py:"
    "GUARDED_STATE; regenerate: python -m dynamo_tpu.analysis"
    " --emit-sync-docs -->"
)
SYNC_END = "<!-- GUARDED_STATE:END -->"

_GUARD_DOC = {
    "lock": "every access holds `with self.{target}`",
    "single-task": "mutations confined to the `{target}` task",
    "thread": "mutations confined to `{target}` (dedicated thread); "
              "cross-thread readers snapshot",
}


def render_sync_table(root: Path) -> str:
    """Render runtime/sync.py's GUARDED_STATE as a markdown table (parsed
    from the AST via the race pack's loader, never imported — same
    contract as the fault table)."""
    from .core import SourceFile
    from .race.registry import SYNC_MODULE, load_guarded_state

    # a one-file Project: the loader only ever reads the registry module,
    # so parsing the whole package here would be pure waste on the CI
    # freshness path
    project = Project(root, [SourceFile(root, root / SYNC_MODULE)])
    entries, err = load_guarded_state(project)
    if err is not None:
        raise SystemExit(f"error: {err}")
    lines = [
        "| Attribute | Guard | Discipline the `race-guarded-state` rule enforces |",
        "|---|---|---|",
    ]
    for e in entries:  # registry order is the doc order
        doc = _GUARD_DOC[e.kind].format(target=e.target)
        lines.append(f"| `{e.key}` | `{e.kind}:{e.target}` | {doc} |")
    return "\n".join(lines)


def emit_sync_docs(root: Path, target: Path) -> str:
    """Splice the generated guard table between the GUARDED_STATE markers
    of `target` (docs/concurrency.md) and return the new content."""
    return splice_generated(
        target.read_text(), SYNC_BEGIN, SYNC_END,
        render_sync_table(root), target, "GUARDED_STATE",
    )


#: markers delimiting the generated block in docs/observability.md
METRICS_BEGIN = (
    "<!-- METRICS:BEGIN — generated from runtime/metrics.py:"
    "METRICS; regenerate: python -m dynamo_tpu.analysis"
    " --emit-metrics-docs -->"
)
METRICS_END = "<!-- METRICS:END -->"

_FLAG_DOC = {
    "wire": "wire",
    "export": "export",
    "dynamic": "dynamic",
}


def render_metrics_table(root: Path) -> str:
    """Render runtime/metrics.py's METRICS as a markdown table (parsed
    from the AST via the met pack's loader, never imported — same
    contract as the fault and sync tables)."""
    from .core import SourceFile
    from .met.registry import METRICS_MODULE, load_metrics_registry

    project = Project(root, [SourceFile(root, root / METRICS_MODULE)])
    entries, _, err = load_metrics_registry(project)
    if err is not None:
        raise SystemExit(f"error: {err}")
    lines = [
        "| Metric | Kind | Layer | Unit | Labels | Flags | Description |",
        "|---|---|---|---|---|---|---|",
    ]
    for name, spec in entries.items():  # registry order is the doc order
        labels = ", ".join(
            f"`{label}`" for label in spec.get("labels", ()) or ()
        ) or "—"
        flags = ", ".join(
            doc for flag, doc in _FLAG_DOC.items() if spec.get(flag)
        ) or "—"
        unit = spec.get("unit") or "—"
        help_text = spec.get("help", "").replace("|", chr(92) + "|")
        lines.append(
            f"| `{name}` | {spec['kind']} | {spec.get('layer', '—')} "
            f"| {unit} | {labels} | {flags} | {help_text} |"
        )
    return "\n".join(lines)


def emit_metrics_docs(root: Path, target: Path) -> str:
    """Splice the generated metrics table between the METRICS markers of
    `target` (docs/observability.md) and return the new content."""
    return splice_generated(
        target.read_text(), METRICS_BEGIN, METRICS_END,
        render_metrics_table(root), target, "METRICS",
    )


#: markers delimiting the generated block in docs/compilation.md
COMPILE_BEGIN = (
    "<!-- COMPILE_SURFACES:BEGIN — generated from engine/"
    "compile_registry.py:COMPILE_SURFACES + engine/bucketing.py:"
    "BUCKETING_HELPERS; regenerate: python -m dynamo_tpu.analysis"
    " --emit-compile-docs -->"
)
COMPILE_END = "<!-- COMPILE_SURFACES:END -->"


def render_compile_table(root: Path) -> str:
    """Render the compile contract — COMPILE_SURFACES plus
    BUCKETING_HELPERS — as markdown tables (parsed from the AST via the
    comp pack's loaders, never imported — same contract as the fault,
    sync, and metrics tables)."""
    from .comp.registry import (
        BUCKETING_MODULE,
        COMPILE_MODULE,
        load_bucketing_helpers,
        load_compile_surfaces,
    )
    from .core import SourceFile

    project = Project(root, [
        SourceFile(root, root / COMPILE_MODULE),
        SourceFile(root, root / BUCKETING_MODULE),
    ])
    surfaces, _, err = load_compile_surfaces(project)
    if err is not None:
        raise SystemExit(f"error: {err}")
    helpers, _, err = load_bucketing_helpers(project)
    if err is not None:
        raise SystemExit(f"error: {err}")

    def esc(s: str) -> str:
        return s.replace("|", chr(92) + "|")

    lines = [
        "| Surface | Module | Kind | Donated | Static | Warmup "
        "| Variant axes | What it stages |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for name, spec in surfaces.items():  # registry order is the doc order
        module = spec["module"].removeprefix("dynamo_tpu/")
        donate = ", ".join(str(i) for i in spec.get("donate", ())) or "—"
        static = ", ".join(
            f"`{s}`" for s in spec.get("static", ())
        ) or "—"
        axes = "; ".join(
            f"`{ax}` ≤ {esc(bound)}"
            for ax, bound in (spec.get("axes") or {}).items()
        ) or "—"
        warm = "yes" if spec.get("warmup") else "no (cold-compile OK)"
        lines.append(
            f"| `{name}` | `{module}` | {spec['kind']} | {donate} "
            f"| {static} | {warm} | {axes} | {esc(spec.get('help', ''))} |"
        )
    lines += [
        "",
        "Registered bounded shape sources (`comp-shape-bucketing` "
        "resolves dispatch-operand dimensions against these):",
        "",
        "| Helper | Module | Bound | Returns |",
        "|---|---|---|---|",
    ]
    for name, spec in helpers.items():
        module = spec["module"].removeprefix("dynamo_tpu/")
        lines.append(
            f"| `{name}` | `{module}` | {esc(spec.get('bound', ''))} "
            f"| {esc(spec.get('returns', ''))} |"
        )
    return "\n".join(lines)


def emit_compile_docs(root: Path, target: Path) -> str:
    """Splice the generated compile-contract tables between the
    COMPILE_SURFACES markers of `target` (docs/compilation.md) and
    return the new content."""
    return splice_generated(
        target.read_text(), COMPILE_BEGIN, COMPILE_END,
        render_compile_table(root), target, "COMPILE_SURFACES",
    )


def changed_files(root: Path, base: str) -> Optional[List[str]]:
    """Repo-relative .py paths under dynamo_tpu/ that differ from `base`
    (committed diff + working tree + untracked). None when git is
    unavailable — the caller falls back to a full run rather than
    silently skipping the gate."""
    try:
        # --relative: paths relative to cwd (= root), matching
        # Violation.path even when root is nested inside a larger git
        # repo (git diff is toplevel-relative by default; ls-files is
        # already cwd-relative)
        diff = subprocess.run(
            ["git", "diff", "--name-only", "--relative", base, "--", "dynamo_tpu"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard", "dynamo_tpu"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0 or untracked.returncode != 0:
        return None
    out = set(diff.stdout.split()) | set(untracked.stdout.split())
    return sorted(p for p in out if p.endswith(".py"))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.analysis",
        description="dynolint: AST invariant checker for the serving stack",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="violation report format (sarif: SARIF 2.1.0 for CI "
        "code-scanning uploads / inline PR annotations)",
    )
    parser.add_argument(
        "--root", default=None,
        help="repo root containing the dynamo_tpu package "
        "(default: autodetect from this file)",
    )
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated rule names or pack aliases "
        f"({', '.join(sorted(PACKS))}, or 'all') to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    parser.add_argument(
        "--changed-only", action="store_true",
        help="report violations only in files changed vs --diff-base "
        "(committed + working tree + untracked). Rules still see the "
        "whole tree — interprocedural context is never truncated — but "
        "findings in untouched files are filtered, and a no-change diff "
        "exits immediately. Intended for fast pre-pytest gating",
    )
    parser.add_argument(
        "--diff-base", default="HEAD", metavar="REF",
        help="git ref --changed-only diffs against (default: HEAD)",
    )
    parser.add_argument(
        "--emit-env-docs", nargs="?", const="-", metavar="PATH",
        help="render the env-var registry as markdown to PATH ('-' = stdout) "
        "and exit",
    )
    parser.add_argument(
        "--emit-fault-docs", nargs="?", const="docs/fault_tolerance.md",
        metavar="PATH",
        help="regenerate the fault-point table between the FAULT_POINTS "
        "markers of PATH (default docs/fault_tolerance.md; '-' = print the "
        "table) from runtime/faults.py KNOWN_FAULT_POINTS, and exit",
    )
    parser.add_argument(
        "--emit-sync-docs", nargs="?", const="docs/concurrency.md",
        metavar="PATH",
        help="regenerate the guarded-state table between the GUARDED_STATE "
        "markers of PATH (default docs/concurrency.md; '-' = print the "
        "table) from runtime/sync.py GUARDED_STATE, and exit",
    )
    parser.add_argument(
        "--emit-metrics-docs", nargs="?", const="docs/observability.md",
        metavar="PATH",
        help="regenerate the metrics table between the METRICS markers of "
        "PATH (default docs/observability.md; '-' = print the table) from "
        "runtime/metrics.py METRICS, and exit",
    )
    parser.add_argument(
        "--emit-compile-docs", nargs="?", const="docs/compilation.md",
        metavar="PATH",
        help="regenerate the compile-contract tables between the "
        "COMPILE_SURFACES markers of PATH (default docs/compilation.md; "
        "'-' = print the tables) from engine/compile_registry.py "
        "COMPILE_SURFACES + engine/bucketing.py BUCKETING_HELPERS, and "
        "exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for alias, pack in PACKS.items():
            print(f"[{alias}]")
            for cls in pack:
                print(f"  {cls.name:26} {cls.description}")
        return 0

    root = Path(args.root) if args.root else Path(__file__).resolve().parents[2]
    if not (root / "dynamo_tpu").is_dir():
        print(f"error: no dynamo_tpu package under {root}", file=sys.stderr)
        return 2

    if args.emit_env_docs is not None:
        doc = emit_env_docs(root)
        if args.emit_env_docs == "-":
            sys.stdout.write(doc)
        else:
            Path(args.emit_env_docs).write_text(doc)
            print(f"wrote {args.emit_env_docs}")
        return 0

    if args.emit_fault_docs is not None:
        if args.emit_fault_docs == "-":
            sys.stdout.write(render_fault_table(root) + "\n")
        else:
            target = Path(args.emit_fault_docs)
            if not target.is_absolute() and not target.exists():
                target = root / args.emit_fault_docs
            target.write_text(emit_fault_docs(root, target))
            print(f"wrote {target}")
        return 0

    if args.emit_sync_docs is not None:
        if args.emit_sync_docs == "-":
            sys.stdout.write(render_sync_table(root) + "\n")
        else:
            target = Path(args.emit_sync_docs)
            if not target.is_absolute() and not target.exists():
                target = root / args.emit_sync_docs
            target.write_text(emit_sync_docs(root, target))
            print(f"wrote {target}")
        return 0

    if args.emit_metrics_docs is not None:
        if args.emit_metrics_docs == "-":
            sys.stdout.write(render_metrics_table(root) + "\n")
        else:
            target = Path(args.emit_metrics_docs)
            if not target.is_absolute() and not target.exists():
                target = root / args.emit_metrics_docs
            target.write_text(emit_metrics_docs(root, target))
            print(f"wrote {target}")
        return 0

    if args.emit_compile_docs is not None:
        if args.emit_compile_docs == "-":
            sys.stdout.write(render_compile_table(root) + "\n")
        else:
            target = Path(args.emit_compile_docs)
            if not target.is_absolute() and not target.exists():
                target = root / args.emit_compile_docs
            target.write_text(emit_compile_docs(root, target))
            print(f"wrote {target}")
        return 0

    rules = default_rules()
    if args.rules:
        wanted = set()
        for token in args.rules.split(","):
            token = token.strip()
            if not token:
                continue
            if token == "all":
                wanted |= {r.name for r in rules}
            elif token in PACKS:
                wanted |= {cls.name for cls in PACKS[token]}
            else:
                wanted.add(token)
        known = {r.name for r in rules}
        unknown = wanted - known
        if unknown:
            print(
                f"error: unknown rule(s): {', '.join(sorted(unknown))}; "
                f"known: {', '.join(sorted(known | set(PACKS) | {'all'}))}",
                file=sys.stderr,
            )
            return 2
        rules = [r for r in rules if r.name in wanted]

    scope: Optional[List[str]] = None
    if args.changed_only:
        scope = changed_files(root, args.diff_base)
        if scope is not None and not scope:
            print(
                f"dynolint: no package files changed vs {args.diff_base}; "
                "nothing to lint"
            )
            return 0
        if scope is None:
            print(
                "dynolint: --changed-only could not read git state; "
                "falling back to a full run",
                file=sys.stderr,
            )

    project = Project.load(root)
    violations = run(project, rules)
    if scope is not None:
        scoped = set(scope)
        violations = [v for v in violations if v.path in scoped]
    if args.format == "json":
        out = format_json(violations)
    elif args.format == "sarif":
        out = format_sarif(violations, rules)
    else:
        out = format_text(violations)
    print(out)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
