"""dynolint CLI.

    python -m dynamo_tpu.analysis                      # lint, text output
    python -m dynamo_tpu.analysis --format=json        # lint, JSON output
    python -m dynamo_tpu.analysis --rules silent-drop  # subset
    python -m dynamo_tpu.analysis --rules shard        # a whole pack
    python -m dynamo_tpu.analysis --changed-only       # report only files
                                                       # touched vs HEAD
    python -m dynamo_tpu.analysis --list-rules
    python -m dynamo_tpu.analysis --emit-env-docs docs/configuration.md

Exit status: 0 clean, 1 violations found, 2 usage error.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path
from typing import List, Optional

from .core import Project, format_json, format_text, run
from .rules import ALL_RULES, PACKS, default_rules


def emit_env_docs(root: Path) -> str:
    """Render runtime/config.py's ENV_REGISTRY as the configuration doc.

    config.py is executed in ISOLATION (spec_from_file_location, no package
    __init__) so the CLI needs none of the package's dependencies and
    renders the registry of the tree under --root, not whatever
    installation happens to be importable."""
    import importlib.util

    cfg_path = root / "dynamo_tpu" / "runtime" / "config.py"
    spec = importlib.util.spec_from_file_location("_dynolint_config", cfg_path)
    module = importlib.util.module_from_spec(spec)
    # dataclasses resolves the module through sys.modules at class-creation
    # time; exec without registration breaks it
    sys.modules[spec.name] = module
    try:
        spec.loader.exec_module(module)
        ENV_REGISTRY = module.ENV_REGISTRY
    finally:
        sys.modules.pop(spec.name, None)

    lines = [
        "# Configuration — environment variables",
        "",
        "<!-- GENERATED FILE — do not edit by hand.",
        "     Regenerate: python -m dynamo_tpu.analysis --emit-env-docs"
        " docs/configuration.md -->",
        "",
        "Every environment variable the package consults, from the single",
        "registry in `dynamo_tpu/runtime/config.py` (`ENV_REGISTRY`). The",
        "`env-registry` dynolint rule fails CI on any env read that",
        "bypasses this table.",
        "",
        "| Variable | Type | Default | Consumed by | Description |",
        "|---|---|---|---|---|",
    ]
    for var in sorted(ENV_REGISTRY, key=lambda v: v.name):
        default = "—" if var.default is None else f"`{var.default}`"
        lines.append(
            f"| `{var.name}` | {var.type} | {default} | `{var.module}` "
            f"| {var.description} |"
        )
    lines.append("")
    return "\n".join(lines)


#: markers delimiting the generated block in docs/fault_tolerance.md
FAULT_BEGIN = (
    "<!-- FAULT_POINTS:BEGIN — generated from runtime/faults.py:"
    "KNOWN_FAULT_POINTS; regenerate: python -m dynamo_tpu.analysis"
    " --emit-fault-docs -->"
)
FAULT_END = "<!-- FAULT_POINTS:END -->"


def render_fault_table(root: Path) -> str:
    """Render runtime/faults.py's KNOWN_FAULT_POINTS as a markdown table.

    Parsed from the AST (never imported — faults.py installs a process
    injector at import time), so this works on hosts without the
    package's deps, like --emit-env-docs."""
    import ast

    from .flow.fault_registry import FAULTS_MODULE, load_fault_points

    tree = ast.parse((root / FAULTS_MODULE).read_text())
    points, _, err = load_fault_points(tree)
    if err is not None:
        raise SystemExit(f"error: {err}")
    lines = [
        "| Point | Actions — where it bites |",
        "|---|---|",
    ]
    for name, desc in points.items():  # registry order is the doc order
        lines.append(f"| `{name}` | {desc.replace('|', chr(92) + '|')} |")
    return "\n".join(lines)


def emit_fault_docs(root: Path, target: Path) -> str:
    """Splice the generated point table between the FAULT_POINTS markers
    of `target` (docs/fault_tolerance.md) and return the new content."""
    text = target.read_text()
    if FAULT_BEGIN not in text or FAULT_END not in text:
        raise SystemExit(
            f"error: {target} has no FAULT_POINTS:BEGIN/END markers to "
            "splice the generated table into"
        )
    head, rest = text.split(FAULT_BEGIN, 1)
    _, tail = rest.split(FAULT_END, 1)
    return head + FAULT_BEGIN + "\n" + render_fault_table(root) + "\n" + FAULT_END + tail


def changed_files(root: Path, base: str) -> Optional[List[str]]:
    """Repo-relative .py paths under dynamo_tpu/ that differ from `base`
    (committed diff + working tree + untracked). None when git is
    unavailable — the caller falls back to a full run rather than
    silently skipping the gate."""
    try:
        # --relative: paths relative to cwd (= root), matching
        # Violation.path even when root is nested inside a larger git
        # repo (git diff is toplevel-relative by default; ls-files is
        # already cwd-relative)
        diff = subprocess.run(
            ["git", "diff", "--name-only", "--relative", base, "--", "dynamo_tpu"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard", "dynamo_tpu"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0 or untracked.returncode != 0:
        return None
    out = set(diff.stdout.split()) | set(untracked.stdout.split())
    return sorted(p for p in out if p.endswith(".py"))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.analysis",
        description="dynolint: AST invariant checker for the serving stack",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="violation report format",
    )
    parser.add_argument(
        "--root", default=None,
        help="repo root containing the dynamo_tpu package "
        "(default: autodetect from this file)",
    )
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated rule names or pack aliases "
        f"({', '.join(sorted(PACKS))}) to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    parser.add_argument(
        "--changed-only", action="store_true",
        help="report violations only in files changed vs --diff-base "
        "(committed + working tree + untracked). Rules still see the "
        "whole tree — interprocedural context is never truncated — but "
        "findings in untouched files are filtered, and a no-change diff "
        "exits immediately. Intended for fast pre-pytest gating",
    )
    parser.add_argument(
        "--diff-base", default="HEAD", metavar="REF",
        help="git ref --changed-only diffs against (default: HEAD)",
    )
    parser.add_argument(
        "--emit-env-docs", nargs="?", const="-", metavar="PATH",
        help="render the env-var registry as markdown to PATH ('-' = stdout) "
        "and exit",
    )
    parser.add_argument(
        "--emit-fault-docs", nargs="?", const="docs/fault_tolerance.md",
        metavar="PATH",
        help="regenerate the fault-point table between the FAULT_POINTS "
        "markers of PATH (default docs/fault_tolerance.md; '-' = print the "
        "table) from runtime/faults.py KNOWN_FAULT_POINTS, and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for alias, pack in PACKS.items():
            print(f"[{alias}]")
            for cls in pack:
                print(f"  {cls.name:26} {cls.description}")
        return 0

    root = Path(args.root) if args.root else Path(__file__).resolve().parents[2]
    if not (root / "dynamo_tpu").is_dir():
        print(f"error: no dynamo_tpu package under {root}", file=sys.stderr)
        return 2

    if args.emit_env_docs is not None:
        doc = emit_env_docs(root)
        if args.emit_env_docs == "-":
            sys.stdout.write(doc)
        else:
            Path(args.emit_env_docs).write_text(doc)
            print(f"wrote {args.emit_env_docs}")
        return 0

    if args.emit_fault_docs is not None:
        if args.emit_fault_docs == "-":
            sys.stdout.write(render_fault_table(root) + "\n")
        else:
            target = Path(args.emit_fault_docs)
            if not target.is_absolute() and not target.exists():
                target = root / args.emit_fault_docs
            target.write_text(emit_fault_docs(root, target))
            print(f"wrote {target}")
        return 0

    rules = default_rules()
    if args.rules:
        wanted = set()
        for token in args.rules.split(","):
            token = token.strip()
            if not token:
                continue
            if token in PACKS:
                wanted |= {cls.name for cls in PACKS[token]}
            else:
                wanted.add(token)
        known = {r.name for r in rules}
        unknown = wanted - known
        if unknown:
            print(
                f"error: unknown rule(s): {', '.join(sorted(unknown))}; "
                f"known: {', '.join(sorted(known | set(PACKS)))}",
                file=sys.stderr,
            )
            return 2
        rules = [r for r in rules if r.name in wanted]

    scope: Optional[List[str]] = None
    if args.changed_only:
        scope = changed_files(root, args.diff_base)
        if scope is not None and not scope:
            print(
                f"dynolint: no package files changed vs {args.diff_base}; "
                "nothing to lint"
            )
            return 0
        if scope is None:
            print(
                "dynolint: --changed-only could not read git state; "
                "falling back to a full run",
                file=sys.stderr,
            )

    project = Project.load(root)
    violations = run(project, rules)
    if scope is not None:
        scoped = set(scope)
        violations = [v for v in violations if v.path in scoped]
    out = (
        format_json(violations)
        if args.format == "json"
        else format_text(violations)
    )
    print(out)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
