"""Rule: env-registry — every `DYN_*` env var read must be declared.

Config discoverability contract: `runtime/config.py` owns the single
registry (`ENV_REGISTRY`) of every environment variable the package
consults, with type, default, and consuming module. `python -m
dynamo_tpu.analysis --emit-env-docs` renders it to docs/configuration.md.
An env read that bypasses the registry is invisible to operators — the
`DYN_HBM_BYTES` shape of bug: a load-bearing knob documented nowhere.

Detection: any string literal fully matching `DYN_[A-Z0-9_]+` or
`DYNAMO_TPU_[A-Z0-9_]+` used in an ACCESS position — a call argument, a
subscript index, or an `in`/`not in` comparison — anywhere in the package.
Docstrings and comments never match (they are not access positions).
Registry keys are read from the AST of `runtime/config.py` (first argument
of each `EnvVar(...)` entry), so this rule works on fixture trees too.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Set, Tuple

from ..core import Project, Rule, Violation, str_const

_ENV_NAME = re.compile(r"^(DYN|DYNAMO_TPU)_[A-Z0-9_]+$")

REGISTRY_FILE = "dynamo_tpu/runtime/config.py"
REGISTRY_NAME = "ENV_REGISTRY"


def registry_keys(project: Project) -> Tuple[Set[str], bool]:
    """(declared env names, registry_found) from the registry file's AST."""
    src = project.get(REGISTRY_FILE)
    if src is None:
        return set(), False
    keys: Set[str] = set()
    found = False
    for node in ast.walk(src.tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id == REGISTRY_NAME:
                found = True
                for call in ast.walk(node):
                    if not isinstance(call, ast.Call):
                        continue
                    key = str_const(call.args[0]) if call.args else None
                    if key is None:
                        key = next(
                            (
                                str_const(kw.value)
                                for kw in call.keywords
                                if kw.arg == "name"
                            ),
                            None,
                        )
                    if key is not None:
                        keys.add(key)
    return keys, found


def _access_literals(tree: ast.AST) -> List[Tuple[str, int]]:
    """(env name, line) for every DYN_* literal in an access position."""
    out: List[Tuple[str, int]] = []

    def grab(node):
        s = str_const(node)
        if s is not None and _ENV_NAME.match(s):
            out.append((s, node.lineno))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            for arg in node.args:
                grab(arg)
            for kw in node.keywords:
                grab(kw.value)
        elif isinstance(node, ast.Subscript):
            grab(node.slice)
        elif isinstance(node, ast.Compare):
            grab(node.left)
            for c in node.comparators:
                grab(c)
    return out


class EnvRegistryRule(Rule):
    name = "env-registry"
    description = (
        "every DYN_*/DYNAMO_TPU_* env var accessed anywhere in the package "
        "must be declared in runtime/config.py's ENV_REGISTRY"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        keys, found = registry_keys(project)
        if not found:
            src = project.get(REGISTRY_FILE)
            if src is not None:
                yield Violation(
                    rule=self.name,
                    path=REGISTRY_FILE,
                    line=1,
                    message=(
                        f"no `{REGISTRY_NAME}` table found — declare the env "
                        "var registry here"
                    ),
                )
            return
        for src in project.files:
            for name, line in _access_literals(src.tree):
                if name not in keys:
                    yield Violation(
                        rule=self.name,
                        path=src.rel,
                        line=line,
                        message=(
                            f"env var `{name}` is read but not declared in "
                            f"runtime/config.py:{REGISTRY_NAME} — register "
                            "it (name, type, default, description, module)"
                        ),
                    )
