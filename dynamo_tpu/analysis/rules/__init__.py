"""dynolint rule pack: the invariants this codebase has been burned by."""

from .async_safety import AsyncBlockingRule
from .env_registry import EnvRegistryRule
from .jax_purity import JaxPurityRule
from .lock_discipline import LockDisciplineRule
from .silent_drop import SilentDropRule

ALL_RULES = (
    SilentDropRule,
    AsyncBlockingRule,
    JaxPurityRule,
    EnvRegistryRule,
    LockDisciplineRule,
)


def default_rules():
    return [cls() for cls in ALL_RULES]


__all__ = [
    "ALL_RULES",
    "AsyncBlockingRule",
    "EnvRegistryRule",
    "JaxPurityRule",
    "LockDisciplineRule",
    "SilentDropRule",
    "default_rules",
]
