"""dynolint rule pack: the invariants this codebase has been burned by."""

from ..comp import (
    COMP_RULES,
    CompDonationSafetyRule,
    CompShapeBucketingRule,
    CompSurfaceRegistryRule,
    CompWarmupCoverageRule,
)
from ..flow import (
    FLOW_RULES,
    CancellationSafetyRule,
    FaultPointRegistryRule,
    FrameProtocolRule,
    TaskLifecycleRule,
)
from ..met import (
    MET_RULES,
    MetConsumeSymmetryRule,
    MetKindDisciplineRule,
    MetLabelCardinalityRule,
    MetRegistryRule,
)
from ..race import (
    RACE_RULES,
    RaceAwaitAtomicityRule,
    RaceGuardedStateRule,
    RaceIterMutationRule,
    RaceLockOrderRule,
)
from ..shard import SHARD_RULES, AxisRegistryRule, CollectiveSymmetryRule, PallasGridRule
from .async_safety import AsyncBlockingRule
from .env_registry import EnvRegistryRule
from .jax_purity import JaxPurityRule
from .lock_discipline import LockDisciplineRule
from .silent_drop import SilentDropRule

CORE_RULES = (
    SilentDropRule,
    AsyncBlockingRule,
    JaxPurityRule,
    EnvRegistryRule,
    LockDisciplineRule,
)

ALL_RULES = (
    CORE_RULES + SHARD_RULES + FLOW_RULES + RACE_RULES + MET_RULES
    + COMP_RULES
)

#: pack aliases accepted by the CLI's --rules (e.g. `--rules shard`)
PACKS = {
    "core": CORE_RULES,
    "shard": SHARD_RULES,
    "flow": FLOW_RULES,
    "race": RACE_RULES,
    "met": MET_RULES,
    "comp": COMP_RULES,
}


def default_rules():
    return [cls() for cls in ALL_RULES]


__all__ = [
    "ALL_RULES",
    "COMP_RULES",
    "CORE_RULES",
    "FLOW_RULES",
    "MET_RULES",
    "PACKS",
    "RACE_RULES",
    "AsyncBlockingRule",
    "AxisRegistryRule",
    "CancellationSafetyRule",
    "CollectiveSymmetryRule",
    "CompDonationSafetyRule",
    "CompShapeBucketingRule",
    "CompSurfaceRegistryRule",
    "CompWarmupCoverageRule",
    "EnvRegistryRule",
    "FaultPointRegistryRule",
    "FrameProtocolRule",
    "JaxPurityRule",
    "LockDisciplineRule",
    "MetConsumeSymmetryRule",
    "MetKindDisciplineRule",
    "MetLabelCardinalityRule",
    "MetRegistryRule",
    "PallasGridRule",
    "RaceAwaitAtomicityRule",
    "RaceGuardedStateRule",
    "RaceIterMutationRule",
    "RaceLockOrderRule",
    "SilentDropRule",
    "TaskLifecycleRule",
    "default_rules",
]
