"""Rule: silent-drop — every accepted request field must have a consumer.

THE recurring bug class in this repo's history: the OpenAI frontend
accepts a sampling parameter, the preprocessor packs it into
`sampling_options`, and the engine never reads it — the request succeeds
and silently returns output computed with a different distribution than
the client asked for. The penalties trio (`presence_penalty`,
`frequency_penalty`, `repetition_penalty`) shipped exactly this way and
was only caught by a human reading benchmark output.

Contract enforced:
  * PRODUCERS (`llm/preprocessor.py`, `llm/http/service.py`): a request
    field is "accepted" when it is stored into a sampling dict — either
    via the canonical loop `for key in ("temperature", ...): sampling[key]
    = v`, or an explicit `sampling["logprobs"] = ...` /
    `p.sampling_options["seed"] = ...` store.
  * CONSUMERS (`engine/engine.py`, `engine/sampling.py`,
    `llm/http/service.py`): the same field name must appear in a read
    position — a `.get("field")` call, a `[...]"field"...]` subscript
    load, or a `req.field` attribute access on a request object.

An accepted field with zero consumption sites fails the tree, reported at
the producer line that accepts it. Deleting the last consumer of e.g.
`frequency_penalty` re-creates the historical bug and turns the tree red.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from ..core import Project, Rule, SourceFile, Violation, dotted_name, str_const

# request-object receivers whose attribute reads count as consumption
# (`req.n` in the http service is the fan-out consumer of `n`)
_REQUEST_NAMES = {"req", "request", "pre", "p", "r"}


def _is_sampling_dict(node: ast.AST) -> bool:
    return "sampling" in dotted_name(node).lower()


def accepted_fields(src: SourceFile) -> List[Tuple[str, int]]:
    """(field, line) pairs this producer file accepts into sampling dicts."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(src.tree):
        if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            # for key in ("temperature", "top_p", ...): ... sampling[key] = v
            if not isinstance(node.iter, (ast.Tuple, ast.List)):
                continue
            consts = [str_const(e) for e in node.iter.elts]
            if not consts or any(c is None for c in consts):
                continue
            loop_var = node.target.id
            stores_into_sampling = any(
                isinstance(sub, ast.Subscript)
                and isinstance(sub.ctx, ast.Store)
                and isinstance(sub.slice, ast.Name)
                and sub.slice.id == loop_var
                and _is_sampling_dict(sub.value)
                for stmt in node.body
                for sub in ast.walk(stmt)
            )
            if stores_into_sampling:
                out.extend((c, node.iter.lineno) for c in consts)
        elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Store):
            key = str_const(node.slice)
            if key is not None and _is_sampling_dict(node.value):
                out.append((key, node.lineno))
    return out


def consumed_fields(src: SourceFile) -> Set[str]:
    """Field names this consumer file reads."""
    out: Set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in ("get", "pop")
                and node.args
            ):
                key = str_const(node.args[0])
                if key is not None:
                    out.add(key)
        elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
            key = str_const(node.slice)
            if key is not None:
                out.add(key)
        elif isinstance(node, ast.Attribute):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id in _REQUEST_NAMES
            ):
                out.add(node.attr)
    return out


class SilentDropRule(Rule):
    name = "silent-drop"
    description = (
        "every sampling/request field the frontend accepts must be read "
        "somewhere in the engine (or the http fan-out layer)"
    )
    producer_files = (
        "dynamo_tpu/llm/preprocessor.py",
        "dynamo_tpu/llm/http/service.py",
    )
    consumer_files = (
        "dynamo_tpu/engine/engine.py",
        "dynamo_tpu/engine/sampling.py",
        "dynamo_tpu/llm/http/service.py",
    )

    def check(self, project: Project) -> Iterator[Violation]:
        producers = [
            p for rel in self.producer_files
            if (p := project.get(rel)) is not None
        ]
        consumers = [
            c for rel in self.consumer_files
            if (c := project.get(rel)) is not None
        ]
        if not producers or not consumers:
            return
        consumed: Set[str] = set()
        for src in consumers:
            consumed |= consumed_fields(src)
        seen: Dict[str, bool] = {}
        for src in producers:
            for field, line in accepted_fields(src):
                if field in consumed or seen.get(field):
                    continue
                seen[field] = True
                yield Violation(
                    rule=self.name,
                    path=src.rel,
                    line=line,
                    message=(
                        f"request field `{field}` is accepted here but "
                        "never consumed in "
                        f"{', '.join(self.consumer_files)} — the request "
                        "succeeds while silently ignoring the parameter "
                        "(the penalties-bug shape); consume it or reject "
                        "the request with a 400"
                    ),
                )
