"""Rule: jax-purity — no host side effects or tracer coercions in staged code.

A function under `jax.jit`/`pjit` (or handed to `lax.scan`/`lax.cond`/
`lax.while_loop`) runs ONCE as a trace; Python-level effects inside it
either crash at trace time (`float(tracer)` → ConcretizationTypeError,
usually only on the rarely-taken branch that CI never compiles) or silently
bake in stale values. On the decode hot path a stray `.item()`/
`device_get` is worse than a crash: it inserts a synchronous device
round-trip (~10-100x an async dispatch) into a program the engine believes
is fully pipelined.

Flags, inside staged bodies in `engine/` and `ops/`:
  * `float()/int()/bool()` on non-static expressions (tracer coercion)
  * `.item()`, `.tolist()`, `jax.device_get`, `np.asarray`/`np.array`
    (host sync / host materialization)
  * `print(...)`, `time.time()`, `time.perf_counter()`, `random.*`,
    `np.random.*` (impure; use `jax.debug.print` / `jax.random`)
  * iterating a `set` literal or `set(...)` call (nondeterministic order
    across runs — a silent cache-key/compile-variant hazard)

"Staged" = decorated with jit/pjit (directly or via `partial(jax.jit, ..)`)
or passed by name to `lax.scan`/`lax.cond`/`lax.while_loop`/`lax.fori_loop`
— nested defs inside a staged function are staged too.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from ..core import Project, Rule, SourceFile, Violation, call_name, dotted_name

_JIT_NAMES = {"jit", "jax.jit", "pjit", "jax.pjit", "pallas_call", "pl.pallas_call"}
_STAGING_CALLS = {
    "jax.lax.scan", "lax.scan",
    "jax.lax.cond", "lax.cond",
    "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop",
    "jax.lax.switch", "lax.switch",
    "pl.pallas_call", "pallas_call", "pltpu.emit_pipeline",
}
_PARTIAL_NAMES = {"partial", "functools.partial"}
_HOST_SYNC_METHODS = {"item", "tolist"}
_IMPURE_CALLS = {
    "print": "use jax.debug.print (or hoist to the host loop)",
    "jax.device_get": "host sync inside a staged program",
    "time.time": "wall clock is not a traced value",
    "time.perf_counter": "wall clock is not a traced value",
    "np.asarray": "host materialization of a tracer",
    "np.array": "host materialization of a tracer",
    "numpy.asarray": "host materialization of a tracer",
    "numpy.array": "host materialization of a tracer",
}
_IMPURE_PREFIXES = {
    "np.random.": "host RNG inside a staged program; use jax.random",
    "numpy.random.": "host RNG inside a staged program; use jax.random",
    "random.": "host RNG inside a staged program; use jax.random",
}


def _is_jit_decorator(dec: ast.AST) -> bool:
    """@jit / @jax.jit / @partial(jax.jit, ...) / @jax.jit(...)."""
    name = dotted_name(dec)
    if name in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        inner = dotted_name(dec.func)
        if inner in _JIT_NAMES:
            return True
        if inner in ("partial", "functools.partial") and dec.args:
            return dotted_name(dec.args[0]) in _JIT_NAMES
    return False


def _is_static_expr(node: ast.AST) -> bool:
    """Expressions whose value is known at trace time: constants, shape/
    dtype/ndim attribute chains, len() and arithmetic over those."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        return node.attr in ("shape", "ndim", "size", "itemsize", "dtype")
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value)
    if isinstance(node, ast.Call):
        return call_name(node) in ("len", "min", "max") and all(
            _is_static_expr(a) for a in node.args
        )
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand)
    return False


class _StagedScanner(ast.NodeVisitor):
    """Finds staged function defs, then scans their whole subtree."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.hits: List[Violation] = []
        self._staged_names: Set[str] = set()

    def run(self):
        # pass 1: names handed to lax.scan/cond/pallas_call anywhere in
        # the module — directly, or wrapped in partial(fn, ...)
        aliases = {}  # name -> function names it may stand for
        for node in ast.walk(self.src.tree):
            if isinstance(node, ast.Call) and call_name(node) in _STAGING_CALLS:
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        self._staged_names.add(arg.id)
                    elif (
                        isinstance(arg, ast.Call)
                        and call_name(arg) in _PARTIAL_NAMES
                        and arg.args
                        and isinstance(arg.args[0], ast.Name)
                    ):
                        self._staged_names.add(arg.args[0].id)
            elif (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                tgt, val = node.targets[0].id, node.value
                if isinstance(val, ast.Name):
                    aliases.setdefault(tgt, set()).add(val.id)
                elif (
                    isinstance(val, ast.Call)
                    and call_name(val) in _PARTIAL_NAMES
                    and val.args
                    and isinstance(val.args[0], ast.Name)
                ):
                    aliases.setdefault(tgt, set()).add(val.args[0].id)
        # resolve `kernel = partial(_decode_kernel, ...)` one hop at a time
        for _ in range(3):
            extra = set()
            for name in self._staged_names:
                extra |= aliases.get(name, set())
            if extra <= self._staged_names:
                break
            self._staged_names |= extra
        # pass 2: scan bodies of jit-decorated or staged-by-name defs
        self._descend(self.src.tree)

    def _descend(self, node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if (
                    any(_is_jit_decorator(d) for d in child.decorator_list)
                    or child.name in self._staged_names
                ):
                    # _scan_body covers the whole subtree incl. nested defs
                    self._scan_body(child)
                    continue
            self._descend(child)

    def _scan_body(self, fn: ast.AST):
        for node in ast.walk(fn):
            v = self._check_node(node, fn)
            if v is not None:
                self.hits.append(v)

    def _check_node(self, node: ast.AST, fn) -> Optional[Violation]:
        mk = lambda msg: Violation(  # noqa: E731
            rule=JaxPurityRule.name,
            path=self.src.rel,
            line=getattr(node, "lineno", fn.lineno),
            message=f"in staged `{fn.name}`: {msg}",
        )
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in ("float", "int", "bool") and node.args and not all(
                _is_static_expr(a) for a in node.args
            ):
                return mk(
                    f"`{name}(...)` coerces a (possible) tracer to a Python "
                    "scalar — ConcretizationTypeError on the traced branch; "
                    "keep it as an array op"
                )
            if name in _IMPURE_CALLS:
                return mk(f"`{name}(...)` — {_IMPURE_CALLS[name]}")
            for prefix, why in _IMPURE_PREFIXES.items():
                if name.startswith(prefix):
                    return mk(f"`{name}(...)` — {why}")
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _HOST_SYNC_METHODS
            ):
                return mk(
                    f"`.{node.func.attr}()` forces a host sync inside a "
                    "staged program"
                )
        if isinstance(node, (ast.For, ast.comprehension)):
            it = node.iter
            if isinstance(it, ast.Set) or (
                isinstance(it, ast.Call) and call_name(it) == "set"
            ):
                return mk(
                    "iterating a set inside staged code — nondeterministic "
                    "order changes the traced program between runs"
                )
        return None


class JaxPurityRule(Rule):
    name = "jax-purity"
    description = (
        "no Python side effects, tracer coercions, or host syncs inside "
        "jit/pjit/lax-staged functions in engine/ and ops/"
    )
    scopes = ("engine/", "ops/")

    def check(self, project: Project) -> Iterator[Violation]:
        for src in project.in_scope(self.scopes):
            scanner = _StagedScanner(src)
            scanner.run()
            yield from scanner.hits
