"""Rule: lock-discipline — no mixed locked/unlocked mutation of shared state.

Scope: the three modules whose objects are touched from more than one
execution context (the event loop plus the device-executor / fetch / store
threads): `runtime/component.py`, `runtime/request_plane.py`,
`kvbm/manager.py`.

The check is a CONSISTENCY invariant, which keeps it free of false
positives on loop-confined state: if a class guards mutations of
`self.x` with one of its own locks anywhere, then EVERY mutation of
`self.x` in that class must hold that lock (or carry an explicit
`# dynolint: disable=lock-discipline -- reason` waiver). Mutations in
`__init__` are exempt — the object is not yet shared.

A "lock" is an attribute assigned from `threading.Lock/RLock` or
`asyncio.Lock`. A "mutation" is an assignment/augmented assignment to
`self.attr` (or `self.attr[...]`), or a call of a known mutator method
(`append/add/pop/update/clear/remove/extend/discard/setdefault/put_nowait`)
on `self.attr`.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import Project, Rule, SourceFile, Violation, dotted_name

_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "asyncio.Lock",
    "Lock", "RLock", "multiprocessing.Lock",
}
_MUTATORS = {
    "append", "add", "pop", "update", "clear", "remove", "extend",
    "discard", "setdefault", "put_nowait", "insert", "popitem",
}


def _self_attr(node: ast.AST) -> Optional[str]:
    """`self.x` -> "x", `self.x[..]` -> "x" (the container is the state)."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _ClassAuditor:
    def __init__(self, src: SourceFile, cls: ast.ClassDef):
        self.src = src
        self.cls = cls
        self.locks: Set[str] = set()
        # attr -> [(line, method, lock_held | None)]
        self.mutations: Dict[str, List[Tuple[int, str, Optional[str]]]] = {}

    def run(self):
        self._find_locks()
        if not self.locks:
            return
        for item in self.cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_method(item)

    def _find_locks(self):
        for node in ast.walk(self.cls):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                # `self._lock: threading.Lock = threading.Lock()` must
                # register too, or a typed refactor silently disables
                # the whole class audit
                targets = [node.target]
            if (
                targets
                and isinstance(getattr(node, "value", None), ast.Call)
                and dotted_name(node.value.func) in _LOCK_FACTORIES
            ):
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        self.locks.add(attr)

    def _held_lock(self, stack: List[ast.AST]) -> Optional[str]:
        for node in reversed(stack):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr in self.locks:
                        return attr
        return None

    def _scan_method(self, fn: ast.AST):
        if fn.name == "__init__":
            return

        def walk(node: ast.AST, stack: List[ast.AST]):
            stack.append(node)
            attr = None
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None and attr not in self.locks:
                        self._record(attr, node.lineno, fn.name, stack)
                attr = None
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                    attr = _self_attr(f.value)
                    if attr is not None and attr not in self.locks:
                        self._record(attr, node.lineno, fn.name, stack)
            for child in ast.iter_child_nodes(node):
                walk(child, stack)
            stack.pop()

        walk(fn, [])

    def _record(self, attr: str, line: int, method: str, stack: List[ast.AST]):
        self.mutations.setdefault(attr, []).append(
            (line, method, self._held_lock(stack))
        )

    def violations(self) -> Iterator[Violation]:
        for attr, sites in self.mutations.items():
            held = {lock for _, _, lock in sites if lock is not None}
            if not held:
                continue  # never lock-guarded: loop-confined state
            lock = sorted(held)[0]
            for line, method, got in sites:
                if got is None:
                    yield Violation(
                        rule=LockDisciplineRule.name,
                        path=self.src.rel,
                        line=line,
                        message=(
                            f"{self.cls.name}.{attr} is mutated under "
                            f"self.{lock} elsewhere but unlocked in "
                            f"`{method}` — hold the lock or waive with a "
                            "reason"
                        ),
                    )


class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = (
        "attributes guarded by a lock anywhere in a class must be guarded "
        "at every mutation site (component/request_plane/kvbm-manager)"
    )
    files = (
        "dynamo_tpu/runtime/component.py",
        "dynamo_tpu/runtime/request_plane.py",
        "dynamo_tpu/kvbm/manager.py",
    )

    def check(self, project: Project) -> Iterator[Violation]:
        for rel in self.files:
            src = project.get(rel)
            if src is None:
                continue
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef):
                    auditor = _ClassAuditor(src, node)
                    auditor.run()
                    yield from auditor.violations()
