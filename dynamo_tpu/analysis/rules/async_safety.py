"""Rule: async-blocking — no synchronous blocking calls inside `async def`.

The serving stack is one event loop per process; a single blocking call on
it stalls EVERY in-flight stream (the round-4 failure mode: an on-path XLA
compile starved discovery-lease renewal and the control plane dropped the
worker). The compute pool (`runtime/compute.py`) and `asyncio.to_thread`
exist precisely so CPU-bound or blocking work rides a worker thread.

Flags, inside `async def` bodies in `runtime/` and `llm/`:
  * `time.sleep(...)` (use `asyncio.sleep`)
  * `subprocess.run/call/check_call/check_output/Popen`, `os.system`
  * `socket.create_connection`, `requests.*`, `urllib.request.*`
  * bare `open(...)` and Path-style `.read_text()/.write_text()/
    .read_bytes()/.write_bytes()` (use the compute pool / to_thread)
  * zero-argument `.result()` / `.join()` — the concurrent.futures /
    threading blocking waits. The zero-arg restriction keeps `str.join`
    (one arg) and `os.path.join` (>=1 args) out of scope; `.result()` on
    an already-completed asyncio task is non-blocking and gets a line
    waiver with its reason.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from ..core import Project, Rule, SourceFile, Violation, call_name

# dotted-prefix -> remedy
_BLOCKING_CALLS = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "subprocess.run": "offload via asyncio.to_thread / create_subprocess_exec",
    "subprocess.call": "offload via asyncio.to_thread / create_subprocess_exec",
    "subprocess.check_call": "offload via asyncio.to_thread",
    "subprocess.check_output": "offload via asyncio.to_thread",
    "subprocess.Popen": "use asyncio.create_subprocess_exec",
    "os.system": "use asyncio.create_subprocess_shell",
    "socket.create_connection": "use asyncio.open_connection",
    "requests.get": "use an async HTTP client (aiohttp)",
    "requests.post": "use an async HTTP client (aiohttp)",
    "urllib.request.urlopen": "use an async HTTP client (aiohttp)",
}

_BLOCKING_METHODS = {
    "read_text": "sync file I/O on the event loop; offload to the compute pool",
    "write_text": "sync file I/O on the event loop; offload to the compute pool",
    "read_bytes": "sync file I/O on the event loop; offload to the compute pool",
    "write_bytes": "sync file I/O on the event loop; offload to the compute pool",
}

# blocking waits when called with NO arguments (str.join/os.path.join take
# arguments; future.result(timeout) at least states its bound)
_BLOCKING_WAITS = {
    "result": "blocking Future wait; await the future or run_in_executor",
    "join": "blocking thread/process join; await or offload",
}


class _AsyncBodyVisitor(ast.NodeVisitor):
    """Collects blocking calls whose NEAREST enclosing function is async.
    A sync helper nested inside an async def is excluded: it is a callable
    the async code may hand to an executor, not loop-resident code."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.stack: List[ast.AST] = []
        self.hits: List[Violation] = []

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self.stack.append(node)
        self.generic_visit(node)
        self.stack.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self.stack.append(node)
        self.generic_visit(node)
        self.stack.pop()

    def _in_async(self) -> bool:
        return bool(self.stack) and isinstance(
            self.stack[-1], ast.AsyncFunctionDef
        )

    def visit_Call(self, node: ast.Call):
        if self._in_async():
            name = call_name(node)
            remedy = _BLOCKING_CALLS.get(name)
            if remedy is None and name == "open":
                remedy = "sync file I/O on the event loop; offload it"
            if (
                remedy is None
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCKING_METHODS
            ):
                remedy = _BLOCKING_METHODS[node.func.attr]
                name = f".{node.func.attr}"
            if (
                remedy is None
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCKING_WAITS
                and not node.args
                and not node.keywords
            ):
                remedy = _BLOCKING_WAITS[node.func.attr]
                name = f".{node.func.attr}"
            if remedy is not None:
                self.hits.append(
                    Violation(
                        rule=AsyncBlockingRule.name,
                        path=self.src.rel,
                        line=node.lineno,
                        message=(
                            f"blocking call `{name}(...)` inside "
                            f"`async def {self.stack[-1].name}` — {remedy}"
                        ),
                    )
                )
        self.generic_visit(node)


class AsyncBlockingRule(Rule):
    name = "async-blocking"
    description = (
        "no synchronous blocking calls (sleep/subprocess/sync I/O/"
        "Future waits) inside async def bodies in runtime/ and llm/"
    )
    scopes = ("runtime/", "llm/")

    def check(self, project: Project) -> Iterator[Violation]:
        for src in project.in_scope(self.scopes):
            visitor = _AsyncBodyVisitor(src)
            visitor.visit(src.tree)
            yield from visitor.hits
