"""METRICS registry extraction (AST of runtime/metrics.py, never imported).

The observability contract lives in `runtime/metrics.py:METRICS`: one
entry per metric key the package emits — stats()-dict keys published on
the kv_metrics topic, prometheus families minted by the frontend, and
the hand-assembled exposition lines. The met rules parse the dict out of
the AST (same contract as KNOWN_AXES / FRAME_TAGS: the checker must run
on hosts without the runtime importable), so every registry VALUE must
stay a pure literal — `ast.literal_eval`-able — and every KEY must be a
string literal or a same-module string constant (`SCHED_EST_TTFT_MS`).
"""

from __future__ import annotations

import ast
from typing import Dict, Optional, Tuple

from ..core import Project, str_const

METRICS_MODULE = "dynamo_tpu/runtime/metrics.py"

VALID_KINDS = {"counter", "gauge", "histogram", "info"}
VALID_LAYERS = {
    "engine", "worker", "frontend", "kvbm", "router", "sched", "planner",
    "gate",
}


def load_metrics_registry(
    project: Project,
) -> Tuple[Optional[Dict[str, dict]], Optional[Dict[str, int]], Optional[str]]:
    """Parse METRICS out of runtime/metrics.py.

    Returns (entries, lines, error): entries maps metric name -> spec
    dict (kind/layer/unit/help/labels/wire/export/dynamic/buckets);
    lines maps metric name -> registry line for anchoring stale-entry
    and no-producer findings; error is a human message when the registry
    is missing or malformed (reported as a violation, mirroring
    KNOWN_AXES / FRAME_TAGS).
    """
    src = project.get(METRICS_MODULE)
    if src is None:
        return None, None, (
            f"{METRICS_MODULE} not found: the metrics registry is gone"
        )
    consts: Dict[str, str] = {}
    table: Optional[ast.Dict] = None
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            if isinstance(node.value, ast.Constant) and isinstance(
                node.value.value, str
            ):
                consts[tgt.id] = node.value.value
            elif tgt.id == "METRICS" and isinstance(node.value, ast.Dict):
                table = node.value
    if table is None:
        return None, None, (
            f"{METRICS_MODULE} defines no METRICS dict literal — the met "
            "rules need the metrics registry as their source of truth"
        )
    entries: Dict[str, dict] = {}
    lines: Dict[str, int] = {}
    for k, v in zip(table.keys, table.values):
        if k is None:
            return None, None, (
                f"{METRICS_MODULE}: METRICS must not use ** merges — every "
                "entry must be spelled at its own line"
            )
        name = str_const(k)
        if name is None and isinstance(k, ast.Name):
            name = consts.get(k.id)
        if name is None:
            return None, None, (
                f"{METRICS_MODULE}: METRICS key {ast.dump(k)} is not a "
                "resolvable string — keep keys as literals or same-module "
                "string constants"
            )
        try:
            spec = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            return None, None, (
                f"{METRICS_MODULE}: METRICS['{name}'] value is not a pure "
                "literal — the registry must stay literal_eval-able"
            )
        if not isinstance(spec, dict):
            return None, None, (
                f"{METRICS_MODULE}: METRICS['{name}'] must be a dict"
            )
        kind = spec.get("kind")
        if kind not in VALID_KINDS:
            return None, None, (
                f"{METRICS_MODULE}: METRICS['{name}'] kind {kind!r} is not "
                f"one of {sorted(VALID_KINDS)}"
            )
        layer = spec.get("layer")
        if layer is not None and layer not in VALID_LAYERS:
            return None, None, (
                f"{METRICS_MODULE}: METRICS['{name}'] layer {layer!r} is "
                f"not one of {sorted(VALID_LAYERS)}"
            )
        if name in entries:
            return None, None, (
                f"{METRICS_MODULE}: METRICS registers '{name}' twice"
            )
        entries[name] = spec
        lines[name] = k.lineno
    return entries, lines, None


def strip_series_suffix(
    name: str, entries: Dict[str, dict]
) -> Optional[str]:
    """Map a prometheus series name back to its registered family:
    `<hist>_bucket`/`_sum`/`_count` resolve to a registered histogram.
    Returns the family name, or None when `name` is no known series."""
    if name in entries:
        return name
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if entries.get(base, {}).get("kind") == "histogram":
                return base
    return None
