"""Rule: met-registry — every metric emission resolves into METRICS.

The ENV_REGISTRY pattern applied to the observability surface: a
stats()-dict key, a hand-assembled exposition family, or a
prometheus_client constructor that spells a name the registry does not
know is a contract violation at the emission site — and a registry
entry that no producer emits and no consumer reads is dead weight and
fires at its registry line (entries marked `dynamic: True` are excused:
their producers are f-strings the analyzer cannot read).

Under-approximation: emission sites the resolver cannot read (f-string
keys, loop variables) never fire — they are recorded as dynamic sites
and the known-limits section of docs/static_analysis.md counts them.
"""

from __future__ import annotations

from typing import Iterator, Set, Tuple

from ..core import Project, Rule, Violation
from ..shard.callgraph import FunctionIndex
from .registry import METRICS_MODULE, load_metrics_registry, strip_series_suffix
from .scan import MetScan, build_scan


def _consumed(scan: MetScan, name: str) -> bool:
    if name in scan.consumers:
        return True
    return any(
        name + sfx in scan.consumers for sfx in ("_sum", "_count", "_bucket")
    )


class MetRegistryRule(Rule):
    name = "met-registry"
    description = (
        "every metric emission site — stats() dict keys, hand-assembled "
        "exposition families, prometheus_client constructors — resolves "
        "into runtime/metrics.py METRICS, and no registry entry is dead "
        "weight"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        entries, reg_lines, err = load_metrics_registry(project)
        if err is not None:
            yield Violation(
                rule=self.name, path=METRICS_MODULE, line=1, message=err
            )
            return
        index = FunctionIndex(project)
        scan = build_scan(project, index)
        seen: Set[Tuple[str, int, str]] = set()

        def fire(path: str, line: int, msg: str):
            key = (path, line, msg)
            if key in seen:
                return None
            seen.add(key)
            return Violation(rule=self.name, path=path, line=line, message=msg)

        for key, sites in sorted(scan.stat_producers.items()):
            if key in entries:
                continue
            for path, line in sites:
                v = fire(
                    path, line,
                    f"stats() emits unregistered metric key '{key}' — "
                    f"register it in METRICS ({METRICS_MODULE}) or rename "
                    "it to a registered key",
                )
                if v:
                    yield v
        for name in sorted(scan.expo_names()):
            if strip_series_suffix(name, entries) is not None:
                continue
            sites = (
                [s for s, _ in scan.expo_types.get(name, [])]
                + [s.site for s in scan.expo_samples.get(name, [])]
                + [c.site for c in scan.ctors.get(name, [])]
            )
            for path, line in sorted(set(sites)):
                v = fire(
                    path, line,
                    f"exposition publishes unregistered metric family "
                    f"'{name}' — register it in METRICS "
                    f"({METRICS_MODULE})",
                )
                if v:
                    yield v
        expo_families = {
            strip_series_suffix(n, entries) for n in scan.expo_names()
        }
        for name, spec in entries.items():
            if spec.get("dynamic"):
                continue
            produced = (
                name in scan.stat_producers or name in expo_families
            )
            if produced or _consumed(scan, name):
                continue
            yield Violation(
                rule=self.name,
                path=METRICS_MODULE,
                line=reg_lines.get(name, 1),
                message=(
                    f"METRICS entry '{name}' is emitted nowhere and "
                    "consumed nowhere — dead registry weight (remove it, "
                    "or wire it up)"
                ),
            )
