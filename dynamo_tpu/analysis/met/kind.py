"""Rule: met-kind-discipline — a metric's registered kind is enforced.

Counters only count: once a registry COUNTER's backing attribute
(`self.admitted_total` behind a stats()-dict value or an exposition
sample) is plainly REASSIGNED outside `__init__`/`__post_init__`/
`reset*()`, every consumer differencing it across scrapes reads a
negative rate — so assignment fires at the assignment line while `+=`
stays legal anywhere. The exposition side must agree with the registry
too: a `# TYPE` declaration or prometheus_client constructor whose kind
differs from METRICS fires, exposition names ending `_total` must be
registered counters and registered counters exposed under any name must
end `_total` (the prometheus naming contract scrape pipelines assume),
histogram constructors must declare exactly the registry's buckets, and
`export: True` requires a scalar kind (the jax_worker gauge loop calls
float() on the value — an info string or histogram blob would export
garbage).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from ..core import Project, Rule, SourceFile, Violation
from ..shard.callgraph import FunctionIndex, _walk_with_chain
from .registry import METRICS_MODULE, load_metrics_registry, strip_series_suffix
from .scan import build_scan

#: scopes where a counter backing may legally be (re)set
_RESET_SCOPES = ("__init__", "__post_init__")


class MetKindDisciplineRule(Rule):
    name = "met-kind-discipline"
    description = (
        "registered counters only increment (no reassignment outside "
        "__init__/reset), exposition TYPE lines and prometheus_client "
        "constructors match the registered kind, _total names are "
        "counters and vice versa, histogram buckets match the registry, "
        "and exported stats are scalar"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        entries, reg_lines, err = load_metrics_registry(project)
        if err is not None:
            yield Violation(
                rule=self.name, path=METRICS_MODULE, line=1, message=err
            )
            return
        index = FunctionIndex(project)
        scan = build_scan(project, index)

        # exposition-sample values also back metrics (the gate renders
        # `{self.admitted_total}` straight into a counter sample)
        backings: Dict[Tuple[str, str], Set[str]] = {}
        for name, attrs in scan.backings.items():
            if entries.get(name, {}).get("kind") != "counter":
                continue
            for rel_attr in attrs:
                backings.setdefault(rel_attr, set()).add(name)
        for name, samples in scan.expo_samples.items():
            family = strip_series_suffix(name, entries)
            if entries.get(family, {}).get("kind") != "counter":
                continue
            for s in samples:
                attr = _value_attr(s.value_expr)
                if attr is not None:
                    backings.setdefault((s.site[0], attr), set()).add(family)

        for src in project.files:
            if src.rel == METRICS_MODULE:
                continue
            yield from self._check_backing_assigns(src, backings)

        for name, decls in sorted(scan.expo_types.items()):
            family = strip_series_suffix(name, entries)
            if family is None:
                continue  # met-registry already owns unregistered names
            kind = entries[family]["kind"]
            for (path, line), declared in decls:
                if declared != kind:
                    yield Violation(
                        rule=self.name, path=path, line=line,
                        message=(
                            f"# TYPE declares '{name}' as {declared} but "
                            f"METRICS registers it as {kind} — scrape "
                            "pipelines trust the TYPE line"
                        ),
                    )
        for name, ctors in sorted(scan.ctors.items()):
            family = strip_series_suffix(name, entries)
            if family is None:
                continue
            spec = entries[family]
            for c in ctors:
                if c.kind != spec["kind"]:
                    yield Violation(
                        rule=self.name, path=c.site[0], line=c.site[1],
                        message=(
                            f"'{name}' is constructed as a {c.kind} but "
                            f"METRICS registers it as {spec['kind']}"
                        ),
                    )
                reg_buckets = spec.get("buckets")
                if spec["kind"] == "histogram" and c.kind == "histogram":
                    got = c.buckets
                    want = (
                        tuple(float(b) for b in reg_buckets)
                        if reg_buckets else None
                    )
                    if got != want:
                        yield Violation(
                            rule=self.name, path=c.site[0], line=c.site[1],
                            message=(
                                f"histogram '{name}' buckets {_fmt(got)} "
                                f"differ from the registry's {_fmt(want)} "
                                "— dashboards and the planner's averages "
                                "assume the registered bounds"
                            ),
                        )

        # the prometheus naming contract, on every exposed family
        for name in sorted(scan.expo_names()):
            family = strip_series_suffix(name, entries)
            if family is None or family != name:
                continue  # series suffixes (_bucket/_sum/_count) are exempt
            kind = entries[name]["kind"]
            sites = (
                [s for s, _ in scan.expo_types.get(name, [])]
                + [s.site for s in scan.expo_samples.get(name, [])]
                + [c.site for c in scan.ctors.get(name, [])]
            )
            path, line = sorted(set(sites))[0]
            if name.endswith("_total") and kind != "counter":
                yield Violation(
                    rule=self.name, path=path, line=line,
                    message=(
                        f"exposed metric '{name}' ends in _total but "
                        f"METRICS registers it as a {kind} — _total is "
                        "the counter suffix"
                    ),
                )
            elif kind == "counter" and not name.endswith("_total"):
                yield Violation(
                    rule=self.name, path=path, line=line,
                    message=(
                        f"exposed counter '{name}' does not end in _total "
                        "— scrape pipelines use the suffix to pick "
                        "rate() over last-value"
                    ),
                )

        for name, spec in entries.items():
            if spec.get("export") and spec["kind"] not in ("counter", "gauge"):
                yield Violation(
                    rule=self.name,
                    path=METRICS_MODULE,
                    line=reg_lines.get(name, 1),
                    message=(
                        f"METRICS entry '{name}' sets export=True but its "
                        f"kind is {spec['kind']} — the jax_worker gauge "
                        "loop float()s the value, so only scalar "
                        "counters/gauges can be exported"
                    ),
                )

    def _check_backing_assigns(
        self, src: SourceFile, backings: Dict[Tuple[str, str], Set[str]]
    ) -> Iterator[Violation]:
        for node, chain in _walk_with_chain(src.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            scope = ""
            for f in reversed(chain):
                if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scope = f.name
                    break
            if scope in _RESET_SCOPES or scope.startswith("reset"):
                continue
            for tgt in targets:
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                names = backings.get((src.rel, tgt.attr))
                if not names:
                    continue
                metric = sorted(names)[0]
                yield Violation(
                    rule=self.name, path=src.rel, line=node.lineno,
                    message=(
                        f"registered counter '{metric}' backing attribute "
                        f"self.{tgt.attr} is REASSIGNED here — counters "
                        "only increment (+=) outside __init__/reset*, or "
                        "every consumer differencing scrapes reads a "
                        "negative rate"
                    ),
                )


def _value_attr(expr) -> "str | None":
    from .scan import _self_attr

    if expr is None:
        return None
    return _self_attr(expr)


def _fmt(buckets) -> str:
    if buckets is None:
        return "(none)"
    return "(" + ", ".join(f"{b:g}" for b in buckets) + ")"
