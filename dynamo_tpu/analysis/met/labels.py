"""Rule: met-label-cardinality — exposition labels stay bounded + escaped.

RTP-LLM's production lesson: label CARDINALITY is capacity. A label
value interpolated from a client-controlled string (the tenant header)
without a bound+escape pass grows the scrape payload without limit and
can break the exposition line format outright (a `"` or newline in the
value). This rule pins every labeled exposition to the registry:

  * prometheus_client constructors must declare exactly the label names
    METRICS registers for the family (order included — `.labels()` is
    positional);
  * every label NAME on a hand-assembled sample must be registered for
    its family (`le` is allowed on `_bucket` series);
  * every label VALUE interpolated into a hand-assembled sample must be
    a static literal or a bare `_prom_label(...)` call — the PR-12
    bound+escape helper that truncates and escapes; anything else (a
    raw f-string field, an expression wrapped around the helper) fires.
"""

from __future__ import annotations

from typing import Iterator

from ..core import Project, Rule, Violation
from ..shard.callgraph import FunctionIndex
from .registry import METRICS_MODULE, load_metrics_registry, strip_series_suffix
from .scan import build_scan


class MetLabelCardinalityRule(Rule):
    name = "met-label-cardinality"
    description = (
        "exposition label names match the registry's declared labels, "
        "and every interpolated label value goes through the "
        "_prom_label bound+escape helper or is a static literal"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        entries, _reg_lines, err = load_metrics_registry(project)
        if err is not None:
            yield Violation(
                rule=self.name, path=METRICS_MODULE, line=1, message=err
            )
            return
        index = FunctionIndex(project)
        scan = build_scan(project, index)

        for name, ctors in sorted(scan.ctors.items()):
            family = strip_series_suffix(name, entries)
            if family is None:
                continue  # met-registry owns unregistered names
            declared = tuple(entries[family].get("labels", ()) or ())
            for c in ctors:
                if c.labelnames is None:
                    continue  # unresolvable labelnames: stay quiet
                if tuple(c.labelnames) != declared:
                    yield Violation(
                        rule=self.name, path=c.site[0], line=c.site[1],
                        message=(
                            f"'{name}' is constructed with labels "
                            f"{list(c.labelnames)} but METRICS declares "
                            f"{list(declared)} — label sets (and order: "
                            ".labels() is positional) are part of the "
                            "contract"
                        ),
                    )

        for name, samples in sorted(scan.expo_samples.items()):
            family = strip_series_suffix(name, entries)
            if family is None:
                continue
            declared = set(entries[family].get("labels", ()) or ())
            if name.endswith("_bucket"):
                declared = declared | {"le"}
            for s in samples:
                for label in s.labels:
                    if label.name not in declared:
                        yield Violation(
                            rule=self.name, path=s.site[0], line=s.site[1],
                            message=(
                                f"sample for '{name}' carries label "
                                f"'{label.name}' that METRICS does not "
                                f"declare for '{family}' — undeclared "
                                "labels are unbounded cardinality"
                            ),
                        )
                    if not label.safe:
                        yield Violation(
                            rule=self.name, path=s.site[0], line=s.site[1],
                            message=(
                                f"label '{label.name}' on '{name}' "
                                "interpolates a value without the "
                                "_prom_label bound+escape helper — a raw "
                                "string in a label value can break the "
                                "exposition format and explode "
                                "cardinality"
                            ),
                        )
