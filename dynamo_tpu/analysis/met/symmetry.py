"""Rule: met-consume-symmetry — cross-process metric keys stay paired.

The flow-frame-protocol shape applied to the metrics topic: worker
stats() dicts cross a process boundary before the gate, the disagg
router, the KV router's scheduler, or the planner reads them — so a
rename at either end fails SILENTLY into fail-open admission or a
stale-metrics planner hold. This rule checks:

  * every cross-process READ (a `.get`/`[]`/`in` off a stats envelope,
    a planner scrape series name) resolves into METRICS — a consumer
    spelling a key no registry entry knows fires at the read site;
  * every registry entry marked `wire: True` has >=1 producer AND >=1
    consumer, or it fires at its registry line — the exact drift a
    one-ended rename creates.

Under-approximation, per direction: a wire entry marked `dynamic: True`
is excused from the producer check when unreadable producer sites
exist; ANY unresolvable envelope read suppresses the no-consumer
direction globally (the rule never accuses symmetric code it cannot
fully read). Bench parsers under the repo root earn consumer credit but
never fire — they live outside the lint project.
"""

from __future__ import annotations

from typing import Iterator, Set, Tuple

from ..core import Project, Rule, Violation
from ..shard.callgraph import FunctionIndex
from .registry import METRICS_MODULE, load_metrics_registry, strip_series_suffix
from .scan import build_scan


class MetConsumeSymmetryRule(Rule):
    name = "met-consume-symmetry"
    description = (
        "cross-process metric reads resolve into METRICS, and every "
        "wire-crossing registry entry has >=1 producer and >=1 consumer "
        "(a one-ended rename fires instead of failing open)"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        entries, reg_lines, err = load_metrics_registry(project)
        if err is not None:
            yield Violation(
                rule=self.name, path=METRICS_MODULE, line=1, message=err
            )
            return
        index = FunctionIndex(project)
        scan = build_scan(project, index)
        seen: Set[Tuple[str, int, str]] = set()

        def fire(path: str, line: int, msg: str):
            key = (path, line, msg)
            if key in seen:
                return None
            seen.add(key)
            return Violation(rule=self.name, path=path, line=line, message=msg)

        in_project = {f.rel for f in project.files}
        for key, sites in sorted(scan.consumers.items()):
            if strip_series_suffix(key, entries) is not None:
                continue
            for path, line in sites:
                if path not in in_project:
                    continue  # bench credit is match-only, never a finding
                v = fire(
                    path, line,
                    f"consumer reads metric key '{key}' that METRICS does "
                    f"not register — the producer side will never publish "
                    f"it (register it in {METRICS_MODULE}, or fix the "
                    "spelling)",
                )
                if v:
                    yield v

        expo_families = {
            strip_series_suffix(n, entries) for n in scan.expo_names()
        }

        def consumed(name: str) -> bool:
            if name in scan.consumers:
                return True
            return any(
                name + sfx in scan.consumers
                for sfx in ("_sum", "_count", "_bucket")
            )

        for name, spec in entries.items():
            if not spec.get("wire"):
                continue
            produced = (
                name in scan.stat_producers or name in expo_families
            )
            dynamic_excused = spec.get("dynamic") and (
                scan.dynamic_stat_sites or scan.dynamic_expo_sites
            )
            if not produced and not dynamic_excused:
                yield Violation(
                    rule=self.name,
                    path=METRICS_MODULE,
                    line=reg_lines.get(name, 1),
                    message=(
                        f"wire-crossing metric '{name}' has no producer — "
                        "its consumers will read absent keys forever "
                        "(fail-open admission / stale planner signal); "
                        "restore the publisher spelling or drop the entry"
                    ),
                )
            if not consumed(name) and not scan.unresolved_consumer_sites:
                yield Violation(
                    rule=self.name,
                    path=METRICS_MODULE,
                    line=reg_lines.get(name, 1),
                    message=(
                        f"wire-crossing metric '{name}' has no consumer — "
                        "it is published across a process boundary that "
                        "nobody reads (drop wire=True, or wire up the "
                        "reader)"
                    ),
                )
