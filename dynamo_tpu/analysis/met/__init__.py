"""dynomet: the observability contract pack.

Four rules anchored to `runtime/metrics.py:METRICS` (AST-parsed, never
imported): met-registry (every emission site resolves into the
registry, no dead entries), met-consume-symmetry (cross-process reads
resolve, wire-crossing keys have both ends), met-kind-discipline
(counters only increment, TYPE/constructor kinds and buckets match,
_total naming), met-label-cardinality (declared label names only,
bound+escaped label values). See docs/static_analysis.md and
docs/observability.md.
"""

from .emission import MetRegistryRule
from .kind import MetKindDisciplineRule
from .labels import MetLabelCardinalityRule
from .registry import METRICS_MODULE, load_metrics_registry
from .symmetry import MetConsumeSymmetryRule

MET_RULES = (
    MetRegistryRule,
    MetConsumeSymmetryRule,
    MetKindDisciplineRule,
    MetLabelCardinalityRule,
)

__all__ = [
    "MET_RULES",
    "METRICS_MODULE",
    "MetConsumeSymmetryRule",
    "MetKindDisciplineRule",
    "MetLabelCardinalityRule",
    "MetRegistryRule",
    "load_metrics_registry",
]
