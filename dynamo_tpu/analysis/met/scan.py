"""Shared emission/consumption scan for the met rules pack.

One pass over the project, four rule views. The scan finds every place a
metric key is born or read:

  * stats()-dict producers — dict-literal keys (and `out["k"] = ...`
    subscript-assign keys, and `.setdefault("k", ...)` keys) inside any
    function named `stats`/`_stats`; keys resolve through module
    constants and import chains (callgraph.py), so `SCHED_EST_TTFT_MS:`
    resolves to "sched_est_ttft_ms". Keys the resolver cannot read
    (f-strings, loop variables) become DYNAMIC producer sites.
  * hand-assembled exposition — string elements of list literals and
    `.append(...)` arguments inside `render_prometheus*` functions,
    reconstructed from their f-string templates (`{ns}` local constants
    inline; everything else becomes a placeholder). `# TYPE name kind`
    declarations, `name{label="..."} value` samples with per-label
    escape-safety, and the backing `self.<attr>` behind a sample value.
  * prometheus_client constructors — Counter/Gauge/Histogram calls that
    pass a `registry=` keyword (the kw keeps collections.Counter out),
    with resolved name, labelnames and buckets.
  * the jax_worker export marker — a `worker_exported_stats()` call
    anywhere means every `export: True` registry entry is structurally
    republished as a `dynamo_worker_<name>` gauge.
  * cross-process consumers — reads off a STATS ENVELOPE: a value that
    arrived as `msg.get("stats")`/`msg["stats"]`, a parameter literally
    named `stats`, or a parameter that provably receives one of those at
    a call site (3-round interprocedural propagation, so
    `update_load(wid, msg.get("stats", {}))` marks `stats` and
    `ForwardPassMetrics.from_stats_dict(stats)` marks `d`). Reads are
    `env.get(k)`, `env[k]`, and `k in env`; unresolvable keys make the
    consumer direction INCOMPLETE and absence findings stay quiet.
  * literal scrape consumers — planner/metrics_source.py call-argument
    strings (prometheus series names the planner differences), and
    repo-root bench_*.py parsers (match-only: bench files live outside
    the lint project, so they earn consumer credit but never fire).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core import Project, SourceFile, call_name, str_const
from ..shard.callgraph import (
    Chain,
    FunctionIndex,
    _walk_with_chain,
    chain_value,
    iter_calls,
    scoped_assignments,
)
from .registry import METRICS_MODULE

#: the one consumer module that parses prometheus text by series name
SCRAPE_MODULES = ("dynamo_tpu/planner/metrics_source.py",)

_STATS_FN_NAMES = ("stats", "_stats")
_PROM_CTORS = {"Counter": "counter", "Gauge": "gauge", "Histogram": "histogram"}

#: placeholder sentinel for unresolvable f-string fields in templates
_PH = "\x00"

_SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:\x00]*)(?:\{(.*)\})?[ \t]+(\S.*)$"
)
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="([^"]*)"')

Site = Tuple[str, int]  # (repo-relative path, line)


@dataclasses.dataclass(frozen=True)
class Label:
    name: str
    #: the static text of the value, or None when it interpolates code
    static: Optional[str]
    #: True when the value is a static literal or a bare
    #: `_prom_label(...)` call — the only shapes that cannot break the
    #: exposition line or explode cardinality unboundedly
    safe: bool


@dataclasses.dataclass
class Sample:
    site: Site
    name: str
    labels: List[Label]
    #: the expression interpolated as the sample value (None when the
    #: value is static text or more than a single placeholder)
    value_expr: Optional[ast.AST]


@dataclasses.dataclass
class Ctor:
    site: Site
    name: str
    kind: str  # counter | gauge | histogram (from the class name)
    labelnames: Optional[Tuple[str, ...]]  # None = unresolvable
    buckets: Optional[Tuple[float, ...]]  # None = not passed


@dataclasses.dataclass
class MetScan:
    stat_producers: Dict[str, List[Site]] = dataclasses.field(
        default_factory=dict
    )
    dynamic_stat_sites: List[Site] = dataclasses.field(default_factory=list)
    #: metric name -> {(rel, attr)} `self.<attr>` expressions backing it
    backings: Dict[str, Set[Tuple[str, str]]] = dataclasses.field(
        default_factory=dict
    )
    expo_types: Dict[str, List[Tuple[Site, str]]] = dataclasses.field(
        default_factory=dict
    )
    expo_samples: Dict[str, List[Sample]] = dataclasses.field(
        default_factory=dict
    )
    ctors: Dict[str, List[Ctor]] = dataclasses.field(default_factory=dict)
    dynamic_expo_sites: List[Site] = dataclasses.field(default_factory=list)
    export_marker: bool = False
    consumers: Dict[str, List[Site]] = dataclasses.field(default_factory=dict)
    unresolved_consumer_sites: List[Site] = dataclasses.field(
        default_factory=list
    )
    #: resolvable scrape names that match nothing in the registry
    scrape_unregistered: List[Tuple[Site, str]] = dataclasses.field(
        default_factory=list
    )

    def expo_names(self) -> Set[str]:
        return (
            set(self.expo_types) | set(self.expo_samples) | set(self.ctors)
        )


def build_scan(project: Project, index: FunctionIndex) -> MetScan:
    scan = MetScan()
    envelopes = _build_envelopes(project, index)
    for src in project.files:
        if src.rel == METRICS_MODULE:
            # the registry module also hosts the generic MetricsRegistry
            # renderer (dynamic names by construction) — the contract
            # test covers its output; the static rules skip it
            continue
        _scan_file(src, index, scan, envelopes)
    _scan_scrapers(project, index, scan)
    _scan_bench(project, scan)
    return scan


# --------------------------------------------------------------------- #
# template reconstruction
# --------------------------------------------------------------------- #


def resolve_template(
    index: FunctionIndex, src: SourceFile, chain: Chain, node: ast.AST
) -> Optional[Tuple[str, List[ast.AST]]]:
    """Rebuild the text of a string expression. Returns (text, exprs)
    where each unresolvable interpolation appears as `\\x00<i>\\x00` and
    exprs[i] is its AST; None when `node` is not a string at all.
    A JoinedStr field that resolves to exactly one string (a local
    `ns = "dynamo_frontend"`, a module constant) is inlined as text."""
    if isinstance(node, ast.Constant):
        return (node.value, []) if isinstance(node.value, str) else None
    if not isinstance(node, ast.JoinedStr):
        return None
    parts: List[str] = []
    exprs: List[ast.AST] = []
    for piece in node.values:
        if isinstance(piece, ast.Constant):
            parts.append(str(piece.value))
            continue
        if isinstance(piece, ast.FormattedValue):
            res = index.resolve_strings(src, chain, piece.value)
            if res.complete and len(res.values) == 1:
                parts.append(next(iter(res.values)).value)
            else:
                parts.append(f"{_PH}{len(exprs)}{_PH}")
                exprs.append(piece.value)
            continue
        return None
    return "".join(parts), exprs


def _unwrap_numeric(expr: ast.AST) -> ast.AST:
    """Strip single-arg numeric wrappers: `round(int(self.x))` -> self.x."""
    while (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Name)
        and expr.func.id in ("round", "int", "float")
        and expr.args
    ):
        expr = expr.args[0]
    return expr


def _self_attr(expr: ast.AST) -> Optional[str]:
    expr = _unwrap_numeric(expr)
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return expr.attr
    return None


# --------------------------------------------------------------------- #
# per-file scan
# --------------------------------------------------------------------- #


def _scan_file(
    src: SourceFile,
    index: FunctionIndex,
    scan: MetScan,
    envelopes: Dict[int, Set[str]],
) -> None:
    for node, chain in _walk_with_chain(src.tree):
        fn_names = [
            f.name
            for f in chain
            if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        in_stats = any(n in _STATS_FN_NAMES for n in fn_names)
        in_render = any(n.startswith("render_prometheus") for n in fn_names)

        if in_stats and isinstance(node, ast.Return) and isinstance(
            node.value, ast.Dict
        ):
            _scan_producing_dict(src, index, chain, node.value, scan)
        elif in_stats and isinstance(node, ast.Assign):
            tgt = node.targets[0] if len(node.targets) == 1 else None
            if isinstance(tgt, ast.Name) and isinstance(node.value, ast.Dict):
                _scan_producing_dict(src, index, chain, node.value, scan)
            elif isinstance(tgt, ast.Subscript):
                _record_producer_key(
                    src, index, chain, tgt.slice, node.value, scan
                )
        elif in_stats and isinstance(node, ast.AnnAssign):
            if isinstance(node.target, ast.Name) and isinstance(
                node.value, ast.Dict
            ):
                _scan_producing_dict(src, index, chain, node.value, scan)
        elif in_stats and isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            if node.func.attr == "setdefault" and node.args:
                _record_producer_key(
                    src, index, chain, node.args[0],
                    node.args[1] if len(node.args) > 1 else None, scan,
                )
            elif (
                node.func.attr == "update"
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Dict)
            ):
                _scan_producing_dict(src, index, chain, node.args[0], scan)

        if in_render:
            if isinstance(node, ast.List):
                for el in node.elts:
                    _scan_expo_string(src, index, chain, el, scan)
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                if node.func.attr == "append" and len(node.args) == 1:
                    _scan_expo_string(src, index, chain, node.args[0], scan)
                elif node.func.attr == "extend" and len(node.args) == 1 and \
                        isinstance(node.args[0], (ast.List, ast.Tuple)):
                    for el in node.args[0].elts:
                        _scan_expo_string(src, index, chain, el, scan)

        if isinstance(node, ast.Call):
            name = call_name(node)
            simple = name.split(".")[-1] if name else ""
            if simple == "worker_exported_stats":
                scan.export_marker = True
            if simple in _PROM_CTORS and any(
                kw.arg == "registry" for kw in node.keywords
            ):
                _scan_prom_ctor(src, index, chain, node, simple, scan)
            # envelope reads: env.get(key)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and node.args
                and _is_envelope_expr(node.func.value, chain, envelopes)
            ):
                _record_consumer_key(
                    src, index, chain, node.args[0], node.lineno, scan
                )
        elif isinstance(node, ast.Subscript) and isinstance(
            getattr(node, "ctx", None), ast.Load
        ):
            if _is_envelope_expr(node.value, chain, envelopes):
                _record_consumer_key(
                    src, index, chain, node.slice, node.lineno, scan
                )
        elif isinstance(node, ast.Compare) and len(node.ops) == 1:
            if isinstance(node.ops[0], (ast.In, ast.NotIn)):
                if _is_envelope_expr(node.comparators[0], chain, envelopes):
                    _record_consumer_key(
                        src, index, chain, node.left, node.lineno, scan
                    )


def _scan_producing_dict(
    src: SourceFile,
    index: FunctionIndex,
    chain: Chain,
    node: ast.Dict,
    scan: MetScan,
) -> None:
    """Top-level keys of a stats()-shaped dict literal. Nested dict
    VALUES (histogram blobs like kvbm_onboard_hist) are one metric, not
    many — their inner keys are never scanned."""
    for k, v in zip(node.keys, node.values):
        if k is None:
            continue  # ** merge: the merged dict is scanned at its source
        _record_producer_key(src, index, chain, k, v, scan)


def _record_producer_key(
    src: SourceFile,
    index: FunctionIndex,
    chain: Chain,
    key: ast.AST,
    value: Optional[ast.AST],
    scan: MetScan,
) -> None:
    res = index.resolve_strings(src, chain, key)
    if not res.complete:
        scan.dynamic_stat_sites.append((src.rel, key.lineno))
    for r in res.values:
        scan.stat_producers.setdefault(r.value, []).append(
            (src.rel, key.lineno)
        )
        if value is not None:
            attr = _self_attr(value)
            if attr is not None:
                scan.backings.setdefault(r.value, set()).add((src.rel, attr))


def _scan_expo_string(
    src: SourceFile,
    index: FunctionIndex,
    chain: Chain,
    node: ast.AST,
    scan: MetScan,
) -> None:
    t = resolve_template(index, src, chain, node)
    if t is None:
        return
    text, exprs = t
    site = (src.rel, node.lineno)
    if text.startswith("# TYPE "):
        fields = text[len("# TYPE "):].split()
        if len(fields) >= 2:
            name, kind = fields[0], fields[1]
            if _PH in name:
                scan.dynamic_expo_sites.append(site)
            else:
                scan.expo_types.setdefault(name, []).append((site, kind))
        return
    if text.startswith("# HELP ") or text.startswith("#"):
        return
    m = _SAMPLE_RE.match(text)
    if m is None:
        return
    name, labels_raw, value_raw = m.group(1), m.group(2), m.group(3)
    if _PH in name:
        scan.dynamic_expo_sites.append(site)
        return
    labels: List[Label] = []
    for lname, lvalue in _LABEL_RE.findall(labels_raw or ""):
        if _PH not in lvalue:
            labels.append(Label(lname, lvalue, True))
            continue
        # safe iff the whole value is ONE placeholder whose expression
        # is a bare _prom_label(...) escape call
        m2 = re.fullmatch(f"{_PH}(\\d+){_PH}", lvalue)
        safe = False
        if m2 is not None:
            expr = exprs[int(m2.group(1))]
            safe = (
                isinstance(expr, ast.Call)
                and call_name(expr).split(".")[-1] == "_prom_label"
            )
        labels.append(Label(lname, None, safe))
    value_expr: Optional[ast.AST] = None
    m3 = re.fullmatch(f"{_PH}(\\d+){_PH}", value_raw.strip())
    if m3 is not None:
        value_expr = exprs[int(m3.group(1))]
    sample = Sample(site, name, labels, value_expr)
    scan.expo_samples.setdefault(name, []).append(sample)


def _scan_prom_ctor(
    src: SourceFile,
    index: FunctionIndex,
    chain: Chain,
    node: ast.Call,
    cls: str,
    scan: MetScan,
) -> None:
    if not node.args:
        return
    t = resolve_template(index, src, chain, node.args[0])
    if t is None or _PH in t[0]:
        scan.dynamic_expo_sites.append((src.rel, node.lineno))
        return
    name = t[0]
    labelnames: Optional[Tuple[str, ...]] = ()
    labels_node: Optional[ast.AST] = None
    if len(node.args) > 2:
        labels_node = node.args[2]
    for kw in node.keywords:
        if kw.arg == "labelnames":
            labels_node = kw.value
    if labels_node is not None:
        res = index.resolve_strings(src, chain, labels_node)
        if not res.complete:
            labelnames = None
        else:
            # element order matters (.labels() is positional): re-read
            # the literal in source order rather than the resolved set
            if isinstance(labels_node, (ast.List, ast.Tuple)):
                out = []
                ok = True
                for el in labels_node.elts:
                    s = str_const(el)
                    if s is None:
                        ok = False
                        break
                    out.append(s)
                labelnames = tuple(out) if ok else None
            else:
                labelnames = None
    buckets: Optional[Tuple[float, ...]] = None
    for kw in node.keywords:
        if kw.arg == "buckets":
            try:
                raw = ast.literal_eval(kw.value)
                buckets = tuple(float(b) for b in raw)
            except (ValueError, SyntaxError, TypeError):
                buckets = None
    scan.ctors.setdefault(name, []).append(
        Ctor((src.rel, node.lineno), name, _PROM_CTORS[cls], labelnames,
             buckets)
    )


# --------------------------------------------------------------------- #
# stats-envelope consumers
# --------------------------------------------------------------------- #


def _is_stats_get(expr: ast.AST) -> bool:
    """`<e>.get("stats", ...)` or `<e>["stats"]` — a stats envelope being
    taken off a metrics-topic message."""
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr == "get"
        and expr.args
        and str_const(expr.args[0]) == "stats"
    ):
        return True
    if isinstance(expr, ast.Subscript) and str_const(expr.slice) == "stats":
        return True
    return False


def _params(func: ast.AST) -> List[str]:
    a = func.args
    return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]


def _is_envelope_expr(
    expr: ast.AST, chain: Chain, envelopes: Dict[int, Set[str]]
) -> bool:
    if _is_stats_get(expr):
        return True
    if isinstance(expr, ast.Name):
        for f in reversed(chain):
            if scoped_assignments(f, expr.id):
                break  # a local: one-hop through its assignment below
            if expr.id in _params(f):
                return expr.id in envelopes.get(id(f), set())
        hop = chain_value(chain, expr)
        if hop is not expr:
            return _is_stats_get(hop)
    return False


def _build_envelopes(
    project: Project, index: FunctionIndex
) -> Dict[int, Set[str]]:
    """id(funcdef) -> params that receive a stats envelope. Seeded with
    params literally named `stats`; propagated 3 rounds through call
    sites whose actual argument is itself an envelope expression."""
    envelopes: Dict[int, Set[str]] = {}
    defs: Dict[int, ast.AST] = {}
    for src in project.files:
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[id(node)] = node
                if "stats" in _params(node):
                    envelopes.setdefault(id(node), set()).add("stats")
    for _ in range(3):
        changed = False
        for src in project.files:
            for call, chain in iter_calls(src):
                name = call_name(call)
                if not name:
                    continue
                callees = index.functions.get(name.split(".")[-1])
                if not callees:
                    continue
                bindings: List[Tuple[Optional[int], Optional[str], ast.AST]] = [
                    (i, None, a)
                    for i, a in enumerate(call.args)
                    if not isinstance(a, ast.Starred)
                ]
                bindings += [
                    (None, kw.arg, kw.value)
                    for kw in call.keywords
                    if kw.arg is not None
                ]
                for pos, kwname, actual in bindings:
                    if not _is_envelope_expr(actual, chain, envelopes):
                        continue
                    for info in callees:
                        params = _params(info.node)
                        target: Optional[str] = kwname
                        if target is None and pos is not None:
                            # method receiver: `obj.f(a)` binds a to the
                            # param AFTER self/cls
                            shift = (
                                1
                                if isinstance(call.func, ast.Attribute)
                                and params
                                and params[0] in ("self", "cls")
                                else 0
                            )
                            if pos + shift < len(params):
                                target = params[pos + shift]
                        if target is None or target not in params:
                            continue
                        marked = envelopes.setdefault(id(info.node), set())
                        if target not in marked:
                            marked.add(target)
                            changed = True
        if not changed:
            break
    return envelopes


def _record_consumer_key(
    src: SourceFile,
    index: FunctionIndex,
    chain: Chain,
    key: ast.AST,
    line: int,
    scan: MetScan,
) -> None:
    if str_const(key) == "stats":
        return  # the envelope accessor itself, not a metric read
    res = index.resolve_strings(src, chain, key)
    if not res.complete:
        scan.unresolved_consumer_sites.append((src.rel, line))
    for r in res.values:
        scan.consumers.setdefault(r.value, []).append((src.rel, line))


# --------------------------------------------------------------------- #
# literal scrape + bench consumers
# --------------------------------------------------------------------- #


def _scan_scrapers(
    project: Project, index: FunctionIndex, scan: MetScan
) -> None:
    """Planner-side prometheus series names: every call-argument string
    in the scrape modules that spells a `dynamo_*` family must exist in
    the registry (matching happens in the symmetry rule; here every
    resolvable candidate is recorded)."""
    for rel in SCRAPE_MODULES:
        src = project.get(rel)
        if src is None:
            continue
        for call, chain in iter_calls(src):
            args = list(call.args) + [
                kw.value for kw in call.keywords if kw.arg is not None
            ]
            for arg in args:
                t = resolve_template(index, src, chain, arg)
                if t is None or _PH in t[0]:
                    continue
                name = t[0]
                if not name.startswith("dynamo_"):
                    continue
                scan.consumers.setdefault(name, []).append(
                    (src.rel, arg.lineno)
                )


def bench_files(root: Path) -> Sequence[Path]:
    return sorted(Path(root).glob("bench_*.py"))


def _scan_bench(project: Project, scan: MetScan) -> None:
    """Repo-root bench parsers earn consumer credit (a stats key a bench
    asserts on IS consumed), but never fire: bench files live outside
    the lint project, so there is no suppression channel for them."""
    for path in bench_files(project.root):
        try:
            tree = ast.parse(path.read_text())
        except (OSError, SyntaxError):  # pragma: no cover - bench parses
            continue
        rel = path.name
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("get", "startswith")
                and node.args
            ):
                key = str_const(node.args[0])
                if key:
                    scan.consumers.setdefault(key, []).append(
                        (rel, node.lineno)
                    )
            elif isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                if node.value.startswith("dynamo_"):
                    scan.consumers.setdefault(node.value, []).append(
                        (rel, node.lineno)
                    )
