"""dynoflow: the async task-lifecycle / cancellation / wire-protocol pack.

Third rules pack on the analysis core. Where dynolint (core) is per-file
and dynoshard covers the parallelism layer, this pack covers the layer
where the serving plane's worst bugs have actually lived: orphaned
`asyncio.create_task` results whose exceptions vanish (the silent mocker
step-loop death), cleanup `await`s that a cancellation rips through
mid-drain, wire-frame tags that drift between producer and consumer,
and fault-injection points that fall out of the documented set. See
docs/static_analysis.md ("The flow pack") and docs/wire_protocol.md.

Interprocedural resolution (module constants through import chains,
call-site argument chasing) is shared with dynoshard via
shard/callgraph.py.
"""

from .cancellation_safety import CancellationSafetyRule
from .fault_registry import FaultPointRegistryRule
from .frame_protocol import FrameProtocolRule, load_frame_tags
from .task_lifecycle import TaskLifecycleRule

FLOW_RULES = (
    TaskLifecycleRule,
    CancellationSafetyRule,
    FrameProtocolRule,
    FaultPointRegistryRule,
)

__all__ = [
    "CancellationSafetyRule",
    "FLOW_RULES",
    "FaultPointRegistryRule",
    "FrameProtocolRule",
    "TaskLifecycleRule",
    "load_frame_tags",
]
