"""Rule: flow-fault-point-registry — fault injection points stay documented.

dynochaos plans (`DYN_FAULT_PLAN`) are written from the docs: an operator
spells `request_plane.frame:sever,after=3` trusting that the point name
in docs/fault_tolerance.md matches a live `faults.FAULTS.on/check(...)`
site. That trust is only as good as the table. This rule pins both ends
to `runtime/faults.py:KNOWN_FAULT_POINTS`:

  * every injection site in the package — `await f.on("point")` /
    `f.check("point")` where `f` is (or was assigned from)
    `faults.FAULTS` — must name a registered point. The point string is
    resolved through the call graph (constants, defaults, call-site
    args), and the violation anchors at the line the literal was
    written;
  * every registry entry must still have at least one site — a point
    that was refactored away must leave the table (and the generated
    docs) with it.

The table itself renders into docs/fault_tolerance.md via
`python -m dynamo_tpu.analysis --emit-fault-docs`, freshness-tested like
docs/configuration.md.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Tuple

from ..core import Project, Rule, SourceFile, Violation, dotted_name, str_const
from ..shard.callgraph import Chain, FunctionIndex, chain_value, iter_calls

FAULTS_MODULE = "dynamo_tpu/runtime/faults.py"


def load_fault_points(
    tree: ast.AST,
) -> Tuple[Optional[Dict[str, str]], Optional[Dict[str, int]], Optional[str]]:
    """Parse KNOWN_FAULT_POINTS from faults.py's AST (never imported — the
    module installs an injector at import time). Returns (points, lines,
    error); points maps name -> description, lines anchor stale-entry
    findings."""
    table: Optional[ast.Dict] = None
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name) and tgt.id == "KNOWN_FAULT_POINTS" \
                    and isinstance(node.value, ast.Dict):
                table = node.value
    if table is None:
        return None, None, (
            f"{FAULTS_MODULE} defines no KNOWN_FAULT_POINTS dict literal — "
            "the fault-point registry is the source DYN_FAULT_PLAN docs "
            "are generated from"
        )
    points: Dict[str, str] = {}
    lines: Dict[str, int] = {}
    for k, v in zip(table.keys, table.values):
        name = str_const(k) if k is not None else None
        if name is None:
            return None, None, (
                f"{FAULTS_MODULE}: KNOWN_FAULT_POINTS keys must be string "
                "literals"
            )
        points[name] = str_const(v) or ""
        lines[name] = k.lineno
    return points, lines, None


def _is_faults_receiver(chain: Chain, expr: ast.AST) -> bool:
    """True when `expr` is (or is locally assigned from) faults.FAULTS."""
    d = dotted_name(expr)
    if d == "FAULTS" or d.endswith(".FAULTS"):
        return True
    if isinstance(expr, ast.Name):
        v = chain_value(chain, expr)
        if v is not expr:
            dv = dotted_name(v)
            return dv == "FAULTS" or dv.endswith(".FAULTS")
    return False


class FaultPointRegistryRule(Rule):
    name = "flow-fault-point-registry"
    description = (
        "every faults.FAULTS.on/check(...) site names a point registered "
        "in runtime/faults.py KNOWN_FAULT_POINTS, and every registered "
        "point still has a site (DYN_FAULT_PLAN stays spellable from docs)"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        src = project.get(FAULTS_MODULE)
        if src is None:
            yield Violation(
                rule=self.name, path=FAULTS_MODULE, line=1,
                message=f"{FAULTS_MODULE} not found: the fault-point registry is gone",
            )
            return
        points, lines, err = load_fault_points(src.tree)
        if err is not None:
            yield Violation(rule=self.name, path=FAULTS_MODULE, line=1, message=err)
            return
        index = FunctionIndex(project)
        used = set()
        for f in project.files:
            if f.rel == FAULTS_MODULE:
                continue
            yield from self._check_file(f, index, points, used)
        for point in points:
            if point not in used:
                yield Violation(
                    rule=self.name,
                    path=FAULTS_MODULE,
                    line=lines[point],
                    message=(
                        f"KNOWN_FAULT_POINTS entry '{point}' has no "
                        "injection site left in the package — remove it so "
                        "the generated docs stop advertising a dead point"
                    ),
                )

    def _check_file(
        self,
        src: SourceFile,
        index: FunctionIndex,
        points: Dict[str, str],
        used: set,
    ) -> Iterator[Violation]:
        for call, chain in iter_calls(src):
            if not isinstance(call.func, ast.Attribute):
                continue
            if call.func.attr not in ("on", "check") or not call.args:
                continue
            if not _is_faults_receiver(chain, call.func.value):
                continue
            res = index.resolve_strings(src, chain, call.args[0])
            for r in sorted(res.values, key=lambda r: (r.path, r.line, r.value)):
                if r.value in points:
                    used.add(r.value)
                else:
                    yield Violation(
                        rule=self.name,
                        path=r.path,
                        line=r.line,
                        message=(
                            f"fault point '{r.value}' (injected at "
                            f"{src.rel}:{call.lineno}) is not in "
                            f"KNOWN_FAULT_POINTS ({FAULTS_MODULE}: "
                            f"{', '.join(sorted(points))}) — register it "
                            "with a one-line description so DYN_FAULT_PLAN "
                            "stays spellable from docs"
                        ),
                    )
