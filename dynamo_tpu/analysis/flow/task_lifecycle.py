"""Rule: flow-task-lifecycle — every spawned asyncio task has an owner.

The worst async bug this stack has shipped was silent: the mocker's step
loop died on an exception inside a task nobody held, the exception was
never retrieved, and every active stream hung forever without a log line
(fixed by hand in the dynochaos PR). This rule makes the ownership
contract checkable. The task object returned by `asyncio.create_task` /
`loop.create_task` / `asyncio.ensure_future` must be provably

  * awaited — directly, or through `asyncio.wait`/`gather`/`wait_for`/
    `shield`;
  * reaped — `.cancel()`/`.result()`/`.exception()`, including a sweep
    `for t in <tracked>: t.cancel()`; or
  * registered with an owner — stored into an attribute or container
    that is cancelled/awaited/swept ANYWHERE in the project (that is,
    reachable from some `close()`/drain path), or returned to a caller
    that does one of the above (call sites resolved through
    shard/callgraph.py's project-wide index).

A bare `asyncio.create_task(...)` expression statement, or a binding
with no such evidence, is a violation anchored at the spawn site — the
line a maintainer fixes or waives — even when the missing evidence would
live in another file.

Deliberate approximations (both biased toward silence, never invention):
  * evidence is matched by NAME project-wide — any `<e>._task.cancel()`
    anywhere vouches for every task bound to an attribute `_task`;
  * a task handed as an argument into an arbitrary call is assumed
    owned by the callee.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set, Tuple

from ..core import Project, Rule, SourceFile, Violation, call_name
from ..shard.callgraph import Chain, FunctionIndex, _walk_with_chain

_SPAWN_NAMES = {"create_task", "ensure_future"}
#: calls whose await covers the tasks passed into them
_WAIT_FNS = {"wait", "wait_for", "gather", "shield", "as_completed"}
#: methods that consume a task's fate (cancellation or its result/exception)
_REAP_METHODS = {"cancel", "result", "exception"}
_MAX_RETURN_DEPTH = 3


def is_spawn(call: ast.Call) -> bool:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr in _SPAWN_NAMES
    if isinstance(call.func, ast.Name):
        return call.func.id in _SPAWN_NAMES
    return False


def _simple_fn(call: ast.Call) -> str:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return ""


def _parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    out: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _loop_cancels_target(loop: ast.AST) -> bool:
    """`for t in <iter>: ... t.cancel()/.result()/.exception() ...`"""
    targets = _names_in(loop.target)
    for stmt in loop.body:
        for sub in ast.walk(stmt):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _REAP_METHODS
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id in targets
            ):
                return True
    return False


class EvidenceIndex:
    """Project-wide ownership evidence, keyed by attribute name.

    Built once per rule run. Name-keyed on purpose: the owner's cancel
    path (a `close()` in another file) references the task through the
    same attribute spelling the spawn site stored it under.
    """

    def __init__(self, project: Project):
        #: X such that `<e>.X.cancel()` / `.result()` / `.exception()` exists
        self.reaped_attrs: Set[str] = set()
        #: X such that `await <e>.X` or `<e>.X` rides a wait-fn call
        self.awaited_attrs: Set[str] = set()
        #: X such that a loop over an iterable mentioning `.X` reaps its target
        self.swept_attrs: Set[str] = set()
        for src in project.files:
            self._scan(src.tree)

    def _scan(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REAP_METHODS
                    and isinstance(node.func.value, ast.Attribute)
                ):
                    self.reaped_attrs.add(node.func.value.attr)
                # wait-fns match by simple name: `asyncio.gather(...)` AND
                # bare `gather(...)` after a from-import both count
                if _simple_fn(node) in _WAIT_FNS:
                    for arg in node.args:
                        for sub in ast.walk(arg):
                            if isinstance(sub, ast.Attribute):
                                self.awaited_attrs.add(sub.attr)
            elif isinstance(node, ast.Await):
                if isinstance(node.value, ast.Attribute):
                    self.awaited_attrs.add(node.value.attr)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if _loop_cancels_target(node):
                    for sub in ast.walk(node.iter):
                        if isinstance(sub, ast.Attribute):
                            self.swept_attrs.add(sub.attr)


class TaskLifecycleRule(Rule):
    name = "flow-task-lifecycle"
    description = (
        "every asyncio.create_task/ensure_future result is awaited, "
        "cancelled, or registered in a tracked attribute/container some "
        "close()/drain path reaps (ownership chased cross-file)"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        index = FunctionIndex(project)
        evidence = EvidenceIndex(project)
        parent_cache: Dict[str, Dict[ast.AST, ast.AST]] = {}

        def parents_for(src: SourceFile) -> Dict[ast.AST, ast.AST]:
            if src.rel not in parent_cache:
                parent_cache[src.rel] = _parent_map(src.tree)
            return parent_cache[src.rel]

        for src in project.files:
            for node, chain in _walk_with_chain(src.tree):
                if not (isinstance(node, ast.Call) and is_spawn(node)):
                    continue
                reason = self._site_reason(
                    index, evidence, parents_for, src, node, chain, 0
                )
                if reason is not None:
                    target = call_name(node.args[0]) if node.args else ""
                    what = f"task `{target}(...)`" if target else "task"
                    yield Violation(
                        rule=self.name,
                        path=src.rel,
                        line=node.lineno,
                        message=(
                            f"{what} spawned here is orphaned: {reason}. "
                            "An unowned task swallows its exception and "
                            "outlives shutdown — await it, cancel it from "
                            "the owning close()/drain path, or register "
                            "it in a tracked set that path sweeps"
                        ),
                    )

    # ----------------------------------------------------------------- #
    # classification: what does the spawn expression bind to?
    # ----------------------------------------------------------------- #

    def _classify(
        self, parents: Dict[ast.AST, ast.AST], node: ast.AST
    ) -> Tuple[Optional[str], object]:
        parent = parents.get(node)
        while True:
            if isinstance(parent, ast.Await):
                return ("owned", None)
            if isinstance(parent, ast.IfExp) and node in (parent.body, parent.orelse):
                node, parent = parent, parents.get(parent)
                continue
            if (
                isinstance(parent, (ast.ListComp, ast.SetComp))
                and node is parent.elt
            ):
                node, parent = parent, parents.get(parent)
                continue
            if isinstance(parent, ast.Starred):
                node, parent = parent, parents.get(parent)
                continue
            if isinstance(parent, ast.Call) and node in parent.args:
                fn = parent.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in ("append", "add")
                    and len(parent.args) == 1
                ):
                    return ("container", fn.value)
                if _simple_fn(parent) in _WAIT_FNS:
                    node, parent = parent, parents.get(parent)
                    continue
                # handed to an arbitrary callee: assume the callee owns it
                return (None, None)
            break
        if isinstance(parent, ast.Expr):
            return ("bare", None)
        if isinstance(parent, ast.Return):
            return ("returned", None)
        if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.NamedExpr)):
            targets = (
                parent.targets if isinstance(parent, ast.Assign) else [parent.target]
            )
            value = parent.value
            if value is not node:
                if (
                    isinstance(value, ast.Tuple)
                    and node in value.elts
                    and len(targets) == 1
                    and isinstance(targets[0], ast.Tuple)
                    and len(targets[0].elts) == len(value.elts)
                ):
                    return self._target_kind(targets[0].elts[value.elts.index(node)])
                return (None, None)
            return self._target_kind(targets[0])
        return (None, None)

    @staticmethod
    def _target_kind(tgt: ast.AST) -> Tuple[Optional[str], object]:
        if isinstance(tgt, ast.Name):
            return ("local", tgt.id)
        if isinstance(tgt, ast.Attribute):
            return ("attr", tgt.attr)
        if isinstance(tgt, ast.Subscript):
            return ("container", tgt.value)
        return (None, None)

    # ----------------------------------------------------------------- #
    # ownership evidence
    # ----------------------------------------------------------------- #

    def _site_reason(
        self,
        index: FunctionIndex,
        evidence: EvidenceIndex,
        parents_for,
        src: SourceFile,
        node: ast.AST,
        chain: Chain,
        depth: int,
    ) -> Optional[str]:
        """None = owned (or unprovable: stay quiet); else the reason."""
        kind, data = self._classify(parents_for(src), node)
        scope = chain[0] if chain else src.tree
        if kind is None or kind == "owned":
            return None
        if kind == "bare":
            return "its task object is discarded at the call site (fire-and-forget)"
        if kind == "attr":
            return self._attr_reason(evidence, data)
        if kind == "container":
            return self._container_reason(evidence, scope, data)
        if kind == "local":
            return self._local_reason(
                index, evidence, parents_for, src, scope, data, chain, depth
            )
        if kind == "returned":
            return self._returned_reason(
                index, evidence, parents_for, chain, depth
            )
        return None  # pragma: no cover - kinds are exhaustive

    @staticmethod
    def _attr_reason(evidence: EvidenceIndex, attr: str) -> Optional[str]:
        if attr in (
            evidence.reaped_attrs | evidence.awaited_attrs | evidence.swept_attrs
        ):
            return None
        return (
            f"bound to attribute `.{attr}`, which no close()/drain path in "
            "the project cancels, awaits, or sweeps"
        )

    def _container_reason(
        self, evidence: EvidenceIndex, scope: ast.AST, container: ast.AST
    ) -> Optional[str]:
        if isinstance(container, ast.Attribute):
            if container.attr in (evidence.swept_attrs | evidence.awaited_attrs):
                return None
            return (
                f"tracked in container `.{container.attr}`, but no path in "
                "the project sweeps that container with cancel()"
            )
        if isinstance(container, ast.Name):
            if self._local_sweep(scope, container.id):
                return None
            return (
                f"tracked in local container `{container.id}`, which is "
                "never swept with cancel() in the enclosing scope"
            )
        return None  # container shape we cannot follow: stay quiet

    @staticmethod
    def _local_sweep(scope: ast.AST, name: str) -> bool:
        for sub in ast.walk(scope):
            if isinstance(sub, (ast.For, ast.AsyncFor)):
                if name in _names_in(sub.iter) and _loop_cancels_target(sub):
                    return True
            elif isinstance(sub, ast.Call) and _simple_fn(sub) in _WAIT_FNS:
                if any(name in _names_in(a) for a in sub.args):
                    return True
        return False

    def _local_reason(
        self,
        index: FunctionIndex,
        evidence: EvidenceIndex,
        parents_for,
        src: SourceFile,
        scope: ast.AST,
        name: str,
        chain: Chain,
        depth: int,
        _seen: Optional[Set[str]] = None,
    ) -> Optional[str]:
        seen = _seen or {name}
        # a failed ownership TRANSFER (stored into an unswept container /
        # unreaped attribute) is a better diagnosis than the generic
        # "never awaited" — remember it
        transfer_reason: Optional[str] = None
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Await) and name in _names_in(sub.value):
                return None
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _REAP_METHODS
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id == name
            ):
                return None
            if isinstance(sub, ast.Call) and _simple_fn(sub) in _WAIT_FNS:
                if any(name in _names_in(a) for a in sub.args):
                    return None
            if isinstance(sub, (ast.For, ast.AsyncFor)):
                if name in _names_in(sub.iter) and _loop_cancels_target(sub):
                    return None
            if isinstance(sub, ast.Return) and sub.value is not None:
                if (
                    isinstance(sub.value, ast.Name)
                    and sub.value.id == name
                ):
                    return None  # escapes to a caller we did not spawn-site: quiet
            # ownership transfers: container store, attribute store, alias
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in ("append", "add")
                and len(sub.args) == 1
                and isinstance(sub.args[0], ast.Name)
                and sub.args[0].id == name
            ):
                r = self._container_reason(evidence, scope, sub.func.value)
                if r is None:
                    return None
                transfer_reason = transfer_reason or r
            if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Name) \
                    and sub.value.id == name:
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Subscript):
                        r = self._container_reason(evidence, scope, tgt.value)
                        if r is None:
                            return None
                        transfer_reason = transfer_reason or r
                    elif isinstance(tgt, ast.Attribute):
                        r = self._attr_reason(evidence, tgt.attr)
                        if r is None:
                            return None
                        transfer_reason = transfer_reason or r
                    elif isinstance(tgt, ast.Name) and tgt.id not in seen:
                        seen.add(tgt.id)
                        if (
                            self._local_reason(
                                index, evidence, parents_for, src, scope,
                                tgt.id, chain, depth, seen,
                            )
                            is None
                        ):
                            return None
        return transfer_reason or (
            f"local `{name}` is never awaited, cancelled, swept, or handed "
            "to a tracked owner in its enclosing scope"
        )

    def _returned_reason(
        self,
        index: FunctionIndex,
        evidence: EvidenceIndex,
        parents_for,
        chain: Chain,
        depth: int,
    ) -> Optional[str]:
        """The spawn is `return create_task(...)`: ownership moves to the
        callers. Chase every call site of the enclosing function; fire
        only when sites exist and EVERY one provably drops the task."""
        if depth >= _MAX_RETURN_DEPTH or not chain:
            return None
        func = chain[-1]
        sites = index.call_sites.get(func.name, [])
        if not sites:
            return None  # exported factory / dynamic dispatch: stay quiet
        reasons = []
        for site in sites:
            if site.is_partial:
                return None
            r = self._site_reason(
                index, evidence, parents_for, site.src, site.call,
                site.chain, depth + 1,
            )
            if r is None:
                return None
            reasons.append(f"{site.src.rel}:{site.call.lineno}")
        return (
            f"returned from `{func.name}`, but every call site drops it "
            f"({'; '.join(sorted(set(reasons))[:3])})"
        )
