"""Rule: flow-frame-protocol — wire-frame tags stay registered and symmetric.

The serving plane speaks three framed dialects (docs/wire_protocol.md):
the request plane's `"t"` channel, discovery's `"op"` request channel and
`"push"` server-push channel. Tags are plain strings in control dicts, so
nothing in the runtime stops a producer from emitting a frame no consumer
dispatches on — the frame is silently dropped on the floor (or worse, a
stream hangs waiting for a terminal tag that will never come). This rule
pins the protocol to one registry, `runtime/codec.py:FRAME_TAGS`, and
checks both directions of every channel:

  * every tag VALUE reaching a frame-dict literal (`{"t": <tag>, ...}`)
    in a protocol module must resolve into the registry — resolution
    goes through module constants and import chains (callgraph.py), so
    `T_DATA` imported from codec.py resolves to "data";
  * every tag a dispatch comparison consumes (`t == T_DATA` where `t`
    came from `control.get("t")`, or `control.get("push") == PUSH_MSG`,
    or `t in (T_DONE, T_ERR)`) must resolve into the registry;
  * per channel, the emitted and consumed sets must MATCH: a tag emitted
    with no dispatch arm, or a dispatch arm no producer can reach, is
    protocol drift and fires at the offending site;
  * a registry entry that neither side uses is dead weight and fires at
    the registry line.

Under-approximation: a channel with any UNRESOLVABLE emit (or consume)
site suppresses that channel's absence findings in the matching
direction — the rule never accuses symmetric code it cannot fully read.
Unregistered-tag findings still fire on whatever does resolve.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import Project, Rule, SourceFile, Violation, str_const
from ..shard.callgraph import Chain, FunctionIndex, chain_value, _walk_with_chain

CODEC_MODULE = "dynamo_tpu/runtime/codec.py"

#: the modules that put frames on (or take frames off) the wire
PROTOCOL_MODULES = (
    "dynamo_tpu/runtime/request_plane.py",
    "dynamo_tpu/runtime/discovery.py",
    "dynamo_tpu/llm/kv_transfer.py",
)

_Site = Tuple[str, int]  # (repo-relative path, line)


def load_frame_tags(
    project: Project,
) -> Tuple[Optional[Dict[str, Dict[str, str]]],
           Optional[Dict[Tuple[str, str], int]],
           Optional[str]]:
    """Parse FRAME_TAGS out of runtime/codec.py (AST only, never imported).

    Returns (registry, lines, error): registry maps channel -> {tag:
    description}; lines maps (channel, tag) -> codec.py line for anchoring
    dead-entry findings; error is a human message when the registry is
    missing or malformed (reported as a violation, mirroring KNOWN_AXES).
    """
    src = project.get(CODEC_MODULE)
    if src is None:
        return None, None, f"{CODEC_MODULE} not found: the frame-tag registry is gone"
    consts: Dict[str, str] = {}
    table: Optional[ast.Dict] = None
    err_codes: Optional[ast.Dict] = None
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            if isinstance(node.value, ast.Constant) and isinstance(node.value.value, str):
                consts[tgt.id] = node.value.value
            elif tgt.id == "FRAME_TAGS" and isinstance(node.value, ast.Dict):
                table = node.value
            elif tgt.id == "ERR_CODES" and isinstance(node.value, ast.Dict):
                # wire error codes ride T_ERR frames under the "code" key:
                # same symmetry contract, folded in as one more channel
                err_codes = node.value
    if table is None:
        return None, None, (
            f"{CODEC_MODULE} defines no FRAME_TAGS dict literal — the flow "
            "rules need the frame-tag registry as their source of truth"
        )
    if err_codes is not None:
        table = ast.Dict(
            keys=list(table.keys) + [ast.Constant("code", lineno=err_codes.lineno, col_offset=0)],
            values=list(table.values) + [err_codes],
        )
    registry: Dict[str, Dict[str, str]] = {}
    lines: Dict[Tuple[str, str], int] = {}
    for ck, cv in zip(table.keys, table.values):
        channel = str_const(ck) if ck is not None else None
        if channel is None or not isinstance(cv, ast.Dict):
            return None, None, (
                f"{CODEC_MODULE}: FRAME_TAGS channels must be string "
                "literals mapping to dict literals"
            )
        registry[channel] = {}
        for tk, tv in zip(cv.keys, cv.values):
            if tk is None:
                continue
            tag = str_const(tk)
            if tag is None and isinstance(tk, ast.Name):
                tag = consts.get(tk.id)
            if tag is None:
                return None, None, (
                    f"{CODEC_MODULE}: FRAME_TAGS['{channel}'] key "
                    f"{ast.dump(tk)} is not a resolvable string — keep keys "
                    "as literals or same-module string constants"
                )
            desc = str_const(tv) or ""
            registry[channel][tag] = desc
            lines[(channel, tag)] = tk.lineno
    return registry, lines, None


class FrameProtocolRule(Rule):
    name = "flow-frame-protocol"
    description = (
        "wire-frame tags in the protocol modules resolve into "
        "runtime/codec.py FRAME_TAGS, and every emitted tag has a consumer "
        "dispatch arm (and vice versa) per channel"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        registry, reg_lines, err = load_frame_tags(project)
        if err is not None:
            yield Violation(rule=self.name, path=CODEC_MODULE, line=1, message=err)
            return
        index = FunctionIndex(project)
        emits: Dict[str, Dict[str, List[_Site]]] = {c: {} for c in registry}
        consumes: Dict[str, Dict[str, List[_Site]]] = {c: {} for c in registry}
        incomplete_emit: Set[str] = set()
        incomplete_consume: Set[str] = set()
        scanned_any = False
        for rel in PROTOCOL_MODULES:
            src = project.get(rel)
            if src is None:
                continue
            scanned_any = True
            self._scan(
                src, index, registry, emits, consumes,
                incomplete_emit, incomplete_consume,
            )
        if not scanned_any:
            return
        seen: Set[Tuple[str, int, str, str]] = set()

        def emit_violation(path: str, line: int, channel: str, tag: str, msg: str):
            key = (path, line, channel, tag)
            if key in seen:
                return None
            seen.add(key)
            return Violation(rule=self.name, path=path, line=line, message=msg)

        for channel in registry:
            known = registry[channel]
            for tag, sites in sorted(emits[channel].items()):
                if tag not in known:
                    for path, line in sites:
                        v = emit_violation(
                            path, line, channel, tag,
                            f"producer emits unregistered '{channel}' tag "
                            f"'{tag}' — add it to FRAME_TAGS['{channel}'] in "
                            f"{CODEC_MODULE} (and a consumer dispatch arm)",
                        )
                        if v:
                            yield v
                elif (
                    tag not in consumes[channel]
                    and channel not in incomplete_consume
                ):
                    path, line = sorted(sites)[0]
                    v = emit_violation(
                        path, line, channel, tag,
                        f"'{channel}' tag '{tag}' is emitted here but no "
                        "consumer in the protocol modules dispatches on it "
                        "— the frame is dropped on the floor (protocol "
                        "drift)",
                    )
                    if v:
                        yield v
            for tag, sites in sorted(consumes[channel].items()):
                if tag not in known:
                    for path, line in sites:
                        v = emit_violation(
                            path, line, channel, tag,
                            f"dispatch arm matches unregistered '{channel}' "
                            f"tag '{tag}' — add it to FRAME_TAGS"
                            f"['{channel}'] in {CODEC_MODULE}",
                        )
                        if v:
                            yield v
                elif (
                    tag not in emits[channel]
                    and channel not in incomplete_emit
                ):
                    path, line = sorted(sites)[0]
                    v = emit_violation(
                        path, line, channel, tag,
                        f"dispatch arm for '{channel}' tag '{tag}' is dead: "
                        "no producer in the protocol modules emits it "
                        "(protocol drift)",
                    )
                    if v:
                        yield v
            if channel in incomplete_emit or channel in incomplete_consume:
                continue  # partially-resolved channel: no dead-entry claims
            for tag in sorted(known):
                if tag in emits[channel] or tag in consumes[channel]:
                    continue
                yield Violation(
                    rule=self.name,
                    path=CODEC_MODULE,
                    line=reg_lines.get((channel, tag), 1),
                    message=(
                        f"FRAME_TAGS['{channel}'] entry '{tag}' is used by "
                        "no producer or consumer — dead registry weight "
                        "(remove it, or wire it up)"
                    ),
                )

    # ----------------------------------------------------------------- #

    def _scan(
        self,
        src: SourceFile,
        index: FunctionIndex,
        registry: Dict[str, Dict[str, str]],
        emits: Dict[str, Dict[str, List[_Site]]],
        consumes: Dict[str, Dict[str, List[_Site]]],
        incomplete_emit: Set[str],
        incomplete_consume: Set[str],
    ) -> None:
        for node, chain in _walk_with_chain(src.tree):
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    channel = str_const(k) if k is not None else None
                    if channel not in registry:
                        continue
                    res = index.resolve_strings(src, chain, v)
                    if not res.complete:
                        incomplete_emit.add(channel)
                    for r in res.values:
                        emits[channel].setdefault(r.value, []).append(
                            (src.rel, node.lineno)
                        )
            elif isinstance(node, ast.Compare) and len(node.ops) == 1:
                op = node.ops[0]
                sides = (node.left, node.comparators[0])
                if isinstance(op, (ast.Eq, ast.NotEq)):
                    pairs = ((sides[0], sides[1]), (sides[1], sides[0]))
                elif isinstance(op, (ast.In, ast.NotIn)):
                    pairs = ((sides[0], sides[1]),)
                else:
                    continue
                for read_side, tag_side in pairs:
                    channel = self._tag_read_channel(read_side, chain, registry)
                    if channel is None:
                        continue
                    res = index.resolve_strings(src, chain, tag_side)
                    if not res.complete:
                        incomplete_consume.add(channel)
                    for r in res.values:
                        consumes[channel].setdefault(r.value, []).append(
                            (src.rel, node.lineno)
                        )
                    break

    @staticmethod
    def _tag_read_channel(
        expr: ast.AST, chain: Chain, registry: Dict[str, Dict[str, str]]
    ) -> Optional[str]:
        """Channel name when `expr` reads a frame tag: `<e>.get("t")`,
        `<e>["t"]`, or a name assigned from either in the scope chain."""
        e = chain_value(chain, expr)
        if (
            isinstance(e, ast.Call)
            and isinstance(e.func, ast.Attribute)
            and e.func.attr == "get"
            and e.args
        ):
            key = str_const(e.args[0])
            if key in registry:
                return key
        if isinstance(e, ast.Subscript):
            key = str_const(e.slice)
            if key in registry:
                return key
        return None
