"""Rule: flow-cancellation-safety — cleanup paths must survive cancellation.

The graceful-drain sequence (docs/fault_tolerance.md) relies on `finally:`
blocks actually finishing: a worker that is cancelled mid-shutdown must
still revoke its lease, flush its queues, and close its sockets. But an
`await` inside a `finally:` is a cancellation delivery point — when the
enclosing task has a pending cancellation, the await raises
`CancelledError` immediately and the REST OF THE CLEANUP IS ABANDONED.
Likewise, an `except CancelledError:` that does not re-raise turns a
caller's cancel into a silent no-op: the task reports itself finished,
`Task.cancelled()` is False, and drain accounting wedges.

Three checks, over every `try` in the package:

  * an `await` inside `finally:` must be wrapped in `asyncio.shield(...)`
    or `asyncio.wait_for(...)` (bounding/shielding the cleanup step) —
    or be made synchronous (`put_nowait`, `close()`);
  * an `except CancelledError:` handler must re-raise. The one blessed
    exception is the cancel-then-reap idiom — `t.cancel()` followed by
    `try: await t / except CancelledError: pass` — where the swallowed
    error belongs to the CHILD task just cancelled, not the caller; the
    rule recognizes it when something awaited in the try body received a
    `.cancel()` in the same enclosing scope;
  * an `await` inside an `except CancelledError:` handler gets the same
    shield/wait_for requirement as `finally:`.

Violations anchor at the offending await / handler line. Nested function
definitions are their own coroutines and are not scanned as part of the
enclosing cleanup block.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, List, Set

from ..core import Project, Rule, SourceFile, Violation, dotted_name
from ..shard.callgraph import Chain, _walk_with_chain

#: await wrappers accepted inside cleanup blocks
_SAFE_WRAPPERS = {"shield", "wait_for"}


def _is_cancelled_type(t: ast.AST) -> bool:
    if isinstance(t, ast.Tuple):
        return any(_is_cancelled_type(e) for e in t.elts)
    return (isinstance(t, ast.Name) and t.id == "CancelledError") or (
        isinstance(t, ast.Attribute) and t.attr == "CancelledError"
    )


def _walk_same_coroutine(stmts: Iterable[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested def/lambda bodies —
    their awaits belong to a different coroutine."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_wrapped(await_node: ast.Await) -> bool:
    v = await_node.value
    if not isinstance(v, ast.Call):
        return False
    fn = v.func
    name = fn.attr if isinstance(fn, ast.Attribute) else getattr(fn, "id", "")
    return name in _SAFE_WRAPPERS


def _cancelled_receivers(scope: ast.AST) -> Set[str]:
    """Dotted names that receive `.cancel()` anywhere in the scope."""
    out: Set[str] = set()
    for node in ast.walk(scope):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "cancel"
        ):
            d = dotted_name(node.func.value)
            if d:
                out.add(d)
    return out


class CancellationSafetyRule(Rule):
    name = "flow-cancellation-safety"
    description = (
        "awaits in finally:/except CancelledError: blocks are shielded or "
        "bounded (asyncio.shield/wait_for), and CancelledError is re-raised "
        "except in the cancel-then-reap idiom"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        for src in project.files:
            yield from self._check_file(src)

    def _check_file(self, src: SourceFile) -> Iterator[Violation]:
        for node, chain in _walk_with_chain(src.tree):
            if not isinstance(node, ast.Try):
                continue
            yield from self._check_finally(src, node)
            yield from self._check_handlers(src, node, chain)

    def _check_finally(self, src: SourceFile, node: ast.Try) -> Iterator[Violation]:
        for sub in _walk_same_coroutine(node.finalbody):
            if isinstance(sub, ast.Await) and not _is_wrapped(sub):
                yield Violation(
                    rule=self.name,
                    path=src.rel,
                    line=sub.lineno,
                    message=(
                        "`await` inside `finally:` is a cancellation "
                        "delivery point — a pending CancelledError fires "
                        "here and abandons the rest of the cleanup. Wrap "
                        "it in asyncio.shield(...)/wait_for(...) or use a "
                        "synchronous equivalent (put_nowait, close)"
                    ),
                )

    def _check_handlers(
        self, src: SourceFile, node: ast.Try, chain: Chain
    ) -> Iterator[Violation]:
        scope = chain[0] if chain else src.tree
        for handler in node.handlers:
            if handler.type is None or not _is_cancelled_type(handler.type):
                continue
            for sub in _walk_same_coroutine(handler.body):
                if isinstance(sub, ast.Await) and not _is_wrapped(sub):
                    yield Violation(
                        rule=self.name,
                        path=src.rel,
                        line=sub.lineno,
                        message=(
                            "`await` inside `except CancelledError:` runs "
                            "while the task is being torn down — wrap it "
                            "in asyncio.shield(...)/wait_for(...) or make "
                            "it synchronous"
                        ),
                    )
            if any(
                isinstance(s, ast.Raise)
                for s in _walk_same_coroutine(handler.body)
            ):
                continue
            if self._is_cancel_then_reap(node, scope):
                continue
            yield Violation(
                rule=self.name,
                path=src.rel,
                line=handler.lineno,
                message=(
                    "`except CancelledError:` swallows cancellation — the "
                    "caller's cancel() becomes a no-op and graceful drain "
                    "can wedge on a task that reports itself finished. "
                    "Re-raise after cleanup (the cancel-then-reap idiom, "
                    "`t.cancel(); await t`, is recognized and exempt)"
                ),
            )

    @staticmethod
    def _is_cancel_then_reap(node: ast.Try, scope: ast.AST) -> bool:
        """try-body awaits something that received `.cancel()` in the same
        enclosing scope: the swallowed CancelledError is the child's."""
        cancelled = _cancelled_receivers(scope)
        if not cancelled:
            return False
        for sub in _walk_same_coroutine(node.body):
            if not isinstance(sub, ast.Await):
                continue
            target = sub.value
            if isinstance(target, ast.Call) and _is_wrapped(sub):
                targets = target.args[:1]
            else:
                targets = [target]
            for t in targets:
                d = dotted_name(t)
                if d and d in cancelled:
                    return True
        return False
