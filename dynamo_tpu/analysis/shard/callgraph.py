"""Interprocedural string/constant resolution for the shard rule pack.

The parallelism layer wires mesh-axis names through several indirection
levels before they reach a collective:

    moe.prefill_forward_ring(axis_name=SP_AXIS)          # module constant
      -> llama.prefill_forward_ring(axis_name=axis_name) # keyword forwarding
        -> ring_attention(q, k, v, mesh, axis_name=...)  # default parameter
          -> partial(_ring_attention_local, axis_name=axis_name)  # partial
            -> jax.lax.ppermute(k_blk, axis_name, perm)  # the collective

A per-file syntactic rule cannot see through any of that. This module
builds the project-wide indices the shard rules share:

  * module-level string constants, resolved THROUGH import chains
    (`from ..parallel.mesh import SP_AXIS` binds mesh.py's value);
  * a function index (simple name -> defs) and a call-site index
    (callee simple name -> calls, including `functools.partial(fn, ...)`
    treated as a deferred call site);
  * `resolve_strings`: given an expression in a function context, the set
    of string values it can take — following local assignments, module
    constants, parameter defaults, and actual arguments at every call
    site of the enclosing function (bounded depth, cycle-safe).

Resolution is deliberately UNDER-approximate: anything it cannot prove is
reported as incomplete and the rules stay quiet about it. Every resolved
string carries the (file, line) where the literal was written, so
violations anchor where a maintainer would fix or waive them.

Everything is stdlib `ast`; mesh.py is parsed, never imported, so the
checker runs on hosts without JAX installed (same contract as the env
registry in rules/env_registry.py).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import Project, SourceFile, call_name, dotted_name

MESH_MODULE = "dynamo_tpu/parallel/mesh.py"
_PARTIAL_NAMES = {"partial", "functools.partial"}
_MAX_DEPTH = 6


@dataclasses.dataclass(frozen=True)
class ResolvedStr:
    """A string value plus the site where its literal was written."""

    value: str
    path: str  # repo-relative path of the literal
    line: int


@dataclasses.dataclass
class Resolution:
    """Outcome of resolving one expression: the string values it provably
    takes, and whether the value set is complete (False -> the expression
    has at least one binding the resolver could not follow)."""

    values: Set[ResolvedStr] = dataclasses.field(default_factory=set)
    complete: bool = True

    def merge(self, other: "Resolution") -> None:
        self.values |= other.values
        self.complete = self.complete and other.complete


@dataclasses.dataclass(frozen=True)
class FunctionInfo:
    src: SourceFile
    node: ast.AST  # FunctionDef | AsyncFunctionDef

    @property
    def name(self) -> str:
        return self.node.name


#: nesting chain of function defs around a node, outermost first; () at
#: module level. Closure lookups walk it innermost-outward.
Chain = Tuple[ast.AST, ...]


@dataclasses.dataclass(frozen=True)
class CallSite:
    src: SourceFile
    call: ast.Call
    chain: Chain  # function defs enclosing the call, outermost first
    is_partial: bool  # partial(fn, ...): positional args shift by one


def _module_rel_for_import(src: SourceFile, node: ast.ImportFrom) -> Optional[str]:
    """Repo-relative path of the module an ImportFrom names, or None for
    out-of-package imports. `from ..parallel.mesh import SP_AXIS` inside
    dynamo_tpu/models/moe.py -> dynamo_tpu/parallel/mesh.py."""
    if node.level == 0:
        if not node.module or not node.module.startswith("dynamo_tpu"):
            return None
        return node.module.replace(".", "/") + ".py"
    parts = src.rel.split("/")[:-1]  # package dir of the importing file
    hops = node.level - 1
    if hops > len(parts):
        return None
    base = parts[: len(parts) - hops] if hops else parts
    tail = node.module.split(".") if node.module else []
    return "/".join(base + tail) + ".py"


class FunctionIndex:
    """Project-wide indices; build once per rule run and share."""

    def __init__(self, project: Project):
        self.project = project
        self.functions: Dict[str, List[FunctionInfo]] = {}
        self.call_sites: Dict[str, List[CallSite]] = {}
        #: rel path -> {name: ResolvedStr} module-level string constants
        self.module_consts: Dict[str, Dict[str, ResolvedStr]] = {}
        self._build()

    # ----------------------------------------------------------------- #
    # construction
    # ----------------------------------------------------------------- #

    def _build(self) -> None:
        imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        for src in self.project.files:
            consts: Dict[str, ResolvedStr] = {}
            imps: Dict[str, Tuple[str, str]] = {}
            for node in src.tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt = node.targets[0]
                    if isinstance(tgt, ast.Name) and isinstance(node.value, ast.Constant) \
                            and isinstance(node.value.value, str):
                        consts[tgt.id] = ResolvedStr(
                            node.value.value, src.rel, node.value.lineno
                        )
                elif isinstance(node, ast.ImportFrom):
                    mod = _module_rel_for_import(src, node)
                    if mod is None:
                        continue
                    for alias in node.names:
                        imps[alias.asname or alias.name] = (mod, alias.name)
            self.module_consts[src.rel] = consts
            imports[src.rel] = imps
            self._index_defs_and_calls(src)
        # fixpoint: a constant may be an import of an import
        for _ in range(4):
            changed = False
            for rel, imps in imports.items():
                for local, (mod, orig) in imps.items():
                    if local in self.module_consts[rel]:
                        continue
                    hit = self.module_consts.get(mod, {}).get(orig)
                    if hit is not None:
                        self.module_consts[rel][local] = hit
                        changed = True
            if not changed:
                break

    def _index_defs_and_calls(self, src: SourceFile) -> None:
        for child, chain in _walk_with_chain(src.tree):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(child.name, []).append(
                    FunctionInfo(src, child)
                )
            if isinstance(child, ast.Call):
                name = call_name(child)
                if name in _PARTIAL_NAMES and child.args:
                    inner = dotted_name(child.args[0])
                    if inner:
                        self.call_sites.setdefault(
                            inner.split(".")[-1], []
                        ).append(CallSite(src, child, chain, True))
                elif name:
                    self.call_sites.setdefault(
                        name.split(".")[-1], []
                    ).append(CallSite(src, child, chain, False))

    # ----------------------------------------------------------------- #
    # resolution
    # ----------------------------------------------------------------- #

    def resolve_strings(
        self,
        src: SourceFile,
        chain: Chain,
        expr: ast.AST,
        _depth: int = 0,
        _visited: Optional[Set[Tuple[int, str]]] = None,
    ) -> Resolution:
        """All string values `expr` can take in the context of the scope
        chain (() = module level). Tuples/lists resolve element-wise; None
        constants resolve to nothing (complete) so PartitionSpec entries
        like `P(pp, None, "tp")` work unmodified."""
        res = Resolution()
        if _depth > _MAX_DEPTH:
            res.complete = False
            return res
        visited = _visited if _visited is not None else set()

        if isinstance(expr, ast.Constant):
            if isinstance(expr.value, str):
                res.values.add(ResolvedStr(expr.value, src.rel, expr.lineno))
            elif expr.value is not None:
                res.complete = False  # a non-str, non-None constant
            return res
        if isinstance(expr, (ast.Tuple, ast.List)):
            for el in expr.elts:
                res.merge(
                    self.resolve_strings(src, chain, el, _depth, visited)
                )
            return res
        if isinstance(expr, ast.IfExp):
            res.merge(self.resolve_strings(src, chain, expr.body, _depth, visited))
            res.merge(self.resolve_strings(src, chain, expr.orelse, _depth, visited))
            return res
        if isinstance(expr, ast.Name):
            return self._resolve_name(src, chain, expr.id, _depth, visited)
        res.complete = False
        return res

    def _resolve_name(
        self,
        src: SourceFile,
        chain: Chain,
        name: str,
        depth: int,
        visited: Set[Tuple[int, str]],
    ) -> Resolution:
        res = Resolution()
        # innermost scope outward: closure variables resolve against the
        # def that owns them (a ppermute inside a scan-body `tick` reads
        # `perm` assigned in the enclosing schedule function)
        for i in range(len(chain) - 1, -1, -1):
            func = chain[i]
            key = (id(func), name)
            if key in visited:
                return res  # cycle: contributes nothing, stays complete
            visited.add(key)
            local = scoped_assignments(func, name)
            if local:
                for val in local:
                    res.merge(
                        self.resolve_strings(
                            src, chain[: i + 1], val, depth + 1, visited
                        )
                    )
                return res
            if self._is_param(func, name):
                res.merge(
                    self._resolve_param(src, chain[: i + 1], name, depth, visited)
                )
                return res
        const = self.module_consts.get(src.rel, {}).get(name)
        if const is not None:
            res.values.add(const)
            return res
        res.complete = False
        return res

    @staticmethod
    def _is_param(func: ast.AST, name: str) -> bool:
        a = func.args
        params = a.posonlyargs + a.args + a.kwonlyargs
        return any(p.arg == name for p in params)

    def _resolve_param(
        self,
        src: SourceFile,
        chain: Chain,
        name: str,
        depth: int,
        visited: Set[Tuple[int, str]],
    ) -> Resolution:
        """Default value plus every actual argument for `name` across the
        project's call sites of chain[-1] (by simple name; partial()
        shifts positional indexing by one)."""
        res = Resolution()
        func = chain[-1]
        a = func.args
        params = a.posonlyargs + a.args
        # default, if any
        defaults = dict(zip([p.arg for p in params[len(params) - len(a.defaults):]], a.defaults))
        kw_defaults = {
            p.arg: d for p, d in zip(a.kwonlyargs, a.kw_defaults) if d is not None
        }
        default = defaults.get(name, kw_defaults.get(name))
        if default is not None:
            res.merge(self.resolve_strings(src, chain, default, depth + 1, visited))
        try:
            pos_index = [p.arg for p in params].index(name)
        except ValueError:
            pos_index = None
        for site in self.call_sites.get(func.name, []):
            actual: Optional[ast.AST] = None
            for kw in site.call.keywords:
                if kw.arg == name:
                    actual = kw.value
                    break
            if actual is None and pos_index is not None:
                args = site.call.args[1:] if site.is_partial else site.call.args
                if pos_index < len(args):
                    arg = args[pos_index]
                    if isinstance(arg, ast.Starred):
                        res.complete = False
                        continue
                    actual = arg
            if actual is None:
                continue  # call site relies on the default, already merged
            res.merge(
                self.resolve_strings(
                    site.src, site.chain, actual, depth + 1, visited
                )
            )
        return res


# --------------------------------------------------------------------- #
# axis registry extraction (AST of parallel/mesh.py, never imported)
# --------------------------------------------------------------------- #


def load_axis_registry(project: Project) -> Tuple[Optional[Dict[str, str]], Optional[str]]:
    """Parse KNOWN_AXES out of parallel/mesh.py. Returns (registry, error):
    registry maps axis name -> role; error is a human message when the
    registry is missing or unreadable (the rule reports it as a violation,
    mirroring the env-registry contract)."""
    src = project.get(MESH_MODULE)
    if src is None:
        return None, f"{MESH_MODULE} not found: the mesh-axis registry is gone"
    consts: Dict[str, str] = {}
    known: Optional[ast.Dict] = None
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            if isinstance(node.value, ast.Constant) and isinstance(node.value.value, str):
                consts[tgt.id] = node.value.value
            elif tgt.id == "KNOWN_AXES" and isinstance(node.value, ast.Dict):
                known = node.value
    if known is None:
        return None, (
            f"{MESH_MODULE} defines no KNOWN_AXES dict literal — the shard "
            "rules need the axis registry as their source of truth"
        )
    registry: Dict[str, str] = {}
    for k, v in zip(known.keys, known.values):
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            axis = k.value
        elif isinstance(k, ast.Name) and k.id in consts:
            axis = consts[k.id]
        else:
            return None, (
                f"{MESH_MODULE}: KNOWN_AXES key {ast.dump(k)} is not a "
                "resolvable string — keep keys as literals or same-module "
                "string constants"
            )
        role = v.value if isinstance(v, ast.Constant) and isinstance(v.value, str) else ""
        registry[axis] = role
    return registry, None


def _walk_with_chain(tree: ast.AST) -> Iterable[Tuple[ast.AST, Chain]]:
    """Every node paired with its enclosing-function chain (outermost
    first; the node's OWN def is not part of its chain)."""
    stack: List[Tuple[ast.AST, Chain]] = [(tree, ())]
    while stack:
        node, chain = stack.pop()
        for child in ast.iter_child_nodes(node):
            yield child, chain
            child_chain = chain
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_chain = chain + (child,)
            stack.append((child, child_chain))


def iter_calls(src: SourceFile) -> Iterable[Tuple[ast.Call, Chain]]:
    """(call, enclosing scope chain) pairs for every Call in a file."""
    for node, chain in _walk_with_chain(src.tree):
        if isinstance(node, ast.Call):
            yield node, chain


def scoped_assignments(func: ast.AST, name: str) -> List[ast.AST]:
    """Values assigned to `name` DIRECTLY in func's scope — nested defs
    are their own scopes and are not descended into."""
    out: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    out.append(node.value)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name) and node.target.id == name:
                out.append(node.value)
        stack.extend(ast.iter_child_nodes(node))
    out.sort(key=lambda n: getattr(n, "lineno", 0))
    return out


def chain_value(chain: Chain, expr: ast.AST) -> ast.AST:
    """Follow ONE `name = <expr>` hop through the scope chain, innermost
    scope that assigns the name wins (last assignment in that scope)."""
    if not isinstance(expr, ast.Name):
        return expr
    for func in reversed(chain):
        hops = scoped_assignments(func, expr.id)
        if hops:
            return hops[-1]
    return expr
