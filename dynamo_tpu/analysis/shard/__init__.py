"""dynoshard: the interprocedural shard-consistency rule pack.

PR 1's dynolint rules are per-file and syntactic; this pack adds the
parallelism layer's contracts, which are inherently cross-module: axis
names flow through call chains before reaching a collective, and Pallas
grid arithmetic spans wrapper + kernel. See docs/static_analysis.md
("The shard pack") and shard/callgraph.py for the resolution machinery.
"""

from .axis_registry import AxisRegistryRule
from .callgraph import FunctionIndex, load_axis_registry
from .collective_symmetry import CollectiveSymmetryRule
from .pallas_grid import PallasGridRule

SHARD_RULES = (
    AxisRegistryRule,
    PallasGridRule,
    CollectiveSymmetryRule,
)

__all__ = [
    "AxisRegistryRule",
    "CollectiveSymmetryRule",
    "FunctionIndex",
    "PallasGridRule",
    "SHARD_RULES",
    "load_axis_registry",
]
