"""Rule: shard-pallas-grid — pallas_call grid/BlockSpec arithmetic is
internally consistent.

Mosaic only rejects a malformed grid spec at lowering time, on TPU, with
an error pointing into generated MLIR — and some mismatches don't even
fail there: an `index_map` lambda with the wrong arity under
`PrefetchScalarGridSpec` silently binds a scalar-prefetch ref as a grid
index (the `lambda b, *_:` convention exists precisely because the index
map receives `(*grid_indices, *scalar_refs)`). This rule checks, per
`pl.pallas_call` site in `ops/`:

  * index_map arity: each BlockSpec's lambda must name exactly
    `len(grid)` positional parameters; with `num_scalar_prefetch=S > 0`
    it must also carry a vararg (`*_`) to absorb the S scalar refs.
  * block rank: a BlockSpec's block-shape tuple and its index_map's
    returned tuple must have the same length.
  * out rank: the out_specs block tuple and the
    `jax.ShapeDtypeStruct((...), ...)` out_shape must have equal rank.
  * operand count: when the pallas_call is invoked in the same function
    (directly or through one local name), the number of operands must be
    `num_scalar_prefetch + len(in_specs)`.
  * guarded divisibility: a grid entry computed as `a // b` (directly or
    via one local assignment) must be guarded by an `a % b` test
    (assert / if-raise) in the same wrapper — an unguarded floor division
    silently drops the remainder rows of the last tile. `pl.cdiv` needs
    no guard.

Everything literal-only and under-approximate: specs built in ways the
rule cannot see (spec lists from helpers, computed grids) are skipped,
never guessed at.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from ..core import Project, Rule, SourceFile, Violation, call_name
from .callgraph import Chain, chain_value, iter_calls

_PALLAS_CALL = {"pl.pallas_call", "pallas_call", "pallas.pallas_call"}
_GRID_SPECS = {"GridSpec", "PrefetchScalarGridSpec"}
_BLOCK_SPEC = "BlockSpec"
_SHAPE_STRUCT = "ShapeDtypeStruct"


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _tuple_len(expr: ast.AST) -> Optional[int]:
    if isinstance(expr, (ast.Tuple, ast.List)):
        return len(expr.elts)
    return None


class _Site:
    """One pallas_call with its resolved grid/spec components."""

    def __init__(self, src: SourceFile, call: ast.Call, chain: Chain):
        self.src = src
        self.call = call
        self.chain = chain
        self.grid: Optional[ast.AST] = None
        self.in_specs: Optional[List[ast.AST]] = None
        self.out_specs: Optional[ast.AST] = None
        self.num_scalar_prefetch = 0
        self.out_shape = _kw(call, "out_shape")
        spec_call = self._grid_spec_call()
        source = spec_call if spec_call is not None else call
        self.grid = _kw(source, "grid")
        if self.grid is not None:
            self.grid = chain_value(chain, self.grid)
        in_specs = _kw(source, "in_specs")
        if in_specs is not None:
            in_specs = chain_value(chain, in_specs)
            if isinstance(in_specs, (ast.List, ast.Tuple)):
                self.in_specs = list(in_specs.elts)
        out_specs = _kw(source, "out_specs")
        if out_specs is not None:
            self.out_specs = chain_value(chain, out_specs)
        nsp = _kw(source, "num_scalar_prefetch")
        if isinstance(nsp, ast.Constant) and isinstance(nsp.value, int):
            self.num_scalar_prefetch = nsp.value

    def _grid_spec_call(self) -> Optional[ast.Call]:
        spec = _kw(self.call, "grid_spec")
        if spec is None:
            return None
        spec = chain_value(self.chain, spec)
        if isinstance(spec, ast.Call) and \
                call_name(spec).split(".")[-1] in _GRID_SPECS:
            return spec
        return None

    @property
    def grid_rank(self) -> Optional[int]:
        return _tuple_len(self.grid) if self.grid is not None else None


class PallasGridRule(Rule):
    name = "shard-pallas-grid"
    description = (
        "pallas_call sites in ops/: index_map arity == grid rank, block "
        "shapes match index_map/out_shape ranks, operand count matches "
        "in_specs, and grid floor-divisions are divisibility-guarded"
    )
    scopes = ("ops/",)

    def check(self, project: Project) -> Iterator[Violation]:
        for src in project.in_scope(self.scopes):
            for call, chain in iter_calls(src):
                if call_name(call) not in _PALLAS_CALL:
                    continue
                site = _Site(src, call, chain)
                yield from self._check_block_specs(site)
                yield from self._check_out_shape(site)
                yield from self._check_operand_count(site)
                yield from self._check_divisibility(site)

    # ----------------------------------------------------------------- #

    def _iter_block_specs(self, site: _Site) -> Iterator[Tuple[ast.Call, str]]:
        if site.in_specs:
            for i, spec in enumerate(site.in_specs):
                if isinstance(spec, ast.Call) and \
                        call_name(spec).split(".")[-1] == _BLOCK_SPEC:
                    yield spec, f"in_specs[{i}]"
        out = site.out_specs
        if isinstance(out, ast.Call) and \
                call_name(out).split(".")[-1] == _BLOCK_SPEC:
            yield out, "out_specs"

    @staticmethod
    def _spec_parts(spec: ast.Call) -> Tuple[Optional[ast.AST], Optional[ast.Lambda]]:
        block = spec.args[0] if spec.args else _kw(spec, "block_shape")
        imap = spec.args[1] if len(spec.args) > 1 else _kw(spec, "index_map")
        return block, imap if isinstance(imap, ast.Lambda) else None

    def _violation(self, site: _Site, line: int, msg: str) -> Violation:
        return Violation(rule=self.name, path=site.src.rel, line=line, message=msg)

    def _check_block_specs(self, site: _Site) -> Iterator[Violation]:
        rank = site.grid_rank
        for spec, label in self._iter_block_specs(site):
            block, imap = self._spec_parts(spec)
            if imap is None:
                continue
            n_explicit = len(imap.args.posonlyargs) + len(imap.args.args)
            has_vararg = imap.args.vararg is not None
            if rank is not None and n_explicit != rank:
                yield self._violation(
                    site, imap.lineno,
                    f"{label}: index_map names {n_explicit} grid "
                    f"parameter(s) but the grid has rank {rank} — each "
                    "lambda must bind exactly one parameter per grid "
                    "dimension (scalar-prefetch refs ride the vararg)",
                )
            elif site.num_scalar_prefetch > 0 and not has_vararg:
                yield self._violation(
                    site, imap.lineno,
                    f"{label}: num_scalar_prefetch="
                    f"{site.num_scalar_prefetch} appends scalar refs to the "
                    "index_map arguments; add a `*_` vararg or the call "
                    "fails at trace time",
                )
            block_rank = _tuple_len(block) if block is not None else None
            ret_rank = _tuple_len(imap.body)
            if block_rank is not None and ret_rank is not None \
                    and block_rank != ret_rank:
                yield self._violation(
                    site, imap.lineno,
                    f"{label}: block shape has rank {block_rank} but "
                    f"index_map returns {ret_rank} coordinate(s)",
                )

    def _check_out_shape(self, site: _Site) -> Iterator[Violation]:
        out = site.out_specs
        if not (isinstance(out, ast.Call)
                and call_name(out).split(".")[-1] == _BLOCK_SPEC):
            return
        block, _ = self._spec_parts(out)
        block_rank = _tuple_len(block) if block is not None else None
        shape = site.out_shape
        if shape is not None:
            shape = chain_value(site.chain, shape)
        if not (isinstance(shape, ast.Call)
                and call_name(shape).split(".")[-1] == _SHAPE_STRUCT
                and shape.args):
            return
        out_rank = _tuple_len(shape.args[0])
        if block_rank is not None and out_rank is not None \
                and block_rank != out_rank:
            yield self._violation(
                site, site.call.lineno,
                f"out_specs block shape has rank {block_rank} but out_shape "
                f"is rank {out_rank}",
            )

    def _check_operand_count(self, site: _Site) -> Iterator[Violation]:
        if site.in_specs is None:
            return
        expected = site.num_scalar_prefetch + len(site.in_specs)
        invocation = self._find_invocation(site)
        if invocation is None:
            return
        if any(isinstance(a, ast.Starred) for a in invocation.args) \
                or invocation.keywords:
            return
        got = len(invocation.args)
        if got != expected:
            yield self._violation(
                site, invocation.lineno,
                f"pallas_call invoked with {got} operand(s) but "
                f"num_scalar_prefetch ({site.num_scalar_prefetch}) + "
                f"len(in_specs) ({len(site.in_specs)}) = {expected}",
            )

    def _find_invocation(self, site: _Site) -> Optional[ast.Call]:
        """The Call applying this pallas_call's result: `pl.pallas_call(
        ...)(ops...)` directly, or through one local name."""
        scope = site.chain[-1] if site.chain else site.src.tree
        bound: Optional[str] = None
        for node in ast.walk(scope):
            if isinstance(node, ast.Call) and node.func is site.call:
                return node
            if isinstance(node, ast.Assign) and node.value is site.call \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                bound = node.targets[0].id
        if bound is not None:
            for node in ast.walk(scope):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name) \
                        and node.func.id == bound:
                    return node
        return None

    def _check_divisibility(self, site: _Site) -> Iterator[Violation]:
        if not isinstance(site.grid, (ast.Tuple, ast.List)) or not site.chain:
            return
        guards = {
            (ast.unparse(n.left), ast.unparse(n.right))
            for n in self._guard_exprs(site.chain[-1])
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mod)
        }
        for el in site.grid.elts:
            div = chain_value(site.chain, el)
            if not (isinstance(div, ast.BinOp) and isinstance(div.op, ast.FloorDiv)):
                continue
            key = (ast.unparse(div.left), ast.unparse(div.right))
            if key not in guards:
                yield self._violation(
                    site, el.lineno,
                    f"grid entry `{ast.unparse(div)}` floor-divides without "
                    f"a `{key[0]} % {key[1]}` guard in the wrapper — the "
                    "remainder rows of the last tile are silently dropped "
                    "(use pl.cdiv, or assert divisibility)",
                )

    @staticmethod
    def _guard_exprs(func: ast.AST) -> Iterator[ast.AST]:
        """Expressions acting as divisibility guards: assert tests, if/
        while tests (an `if x % y: raise` wrapper counts)."""
        for node in ast.walk(func):
            if isinstance(node, ast.Assert):
                yield from ast.walk(node.test)
            elif isinstance(node, (ast.If, ast.While)):
                yield from ast.walk(node.test)
