"""Rule: shard-axis-registry — every mesh-axis reference resolves into
KNOWN_AXES (parallel/mesh.py).

A collective over a mistyped axis name is the worst kind of bug this stack
has: `jax.lax.psum(x, "qp")` does not fail until a mesh is in context, and
under `shard_map` an axis that exists-but-is-wrong silently reduces over
the wrong device group (a numerics bug, not a crash). Axis names travel
through default parameters, keyword forwarding, and functools.partial
before reaching the collective, so the check is interprocedural: axis
arguments are resolved through the call graph (shard/callgraph.py) and
every string they can take must be registered in mesh.py's KNOWN_AXES.

Checked reference positions:
  * collectives: `psum`/`pmean`/`pmax`/`pmin`/`ppermute`/`all_gather`/
    `all_to_all`/`psum_scatter`/`axis_index`/`axis_size`/`pbroadcast`
    (under `jax.lax`/`lax` or imported bare)
  * `PartitionSpec(...)` entries (incl. tuple entries), under any alias
  * `Mesh(..., axis_names=...)`
  * `mesh.shape[...]` / `mesh.shape.get(...)` / `<name> in mesh.shape` /
    `<name> in mesh.axis_names`

Violations anchor at the line the offending string literal was WRITTEN
(default value, constant, or call argument), which is where the fix or
waiver belongs — not at the collective that happened to consume it.
Unresolvable expressions are skipped: the rule under-approximates and
never guesses.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set, Tuple

from ..core import Project, Rule, SourceFile, Violation, call_name, dotted_name
from .callgraph import FunctionIndex, MESH_MODULE, iter_calls, load_axis_registry

#: collective -> positional index of its axis-name argument
_COLLECTIVES = {
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "ppermute": 1,
    "all_gather": 1,
    "all_to_all": 1,
    "psum_scatter": 1,
    "pbroadcast": 1,
    "axis_index": 0,
    "axis_size": 0,
}
_LAX_PREFIXES = ("", "lax", "jax.lax")
_PSPEC_SOURCES = {"jax.sharding", "jax.sharding.partition_spec"}


def _pspec_aliases(src: SourceFile) -> Set[str]:
    """Local names PartitionSpec is bound to in this file (`P`, ...)."""
    names = {"jax.sharding.PartitionSpec", "sharding.PartitionSpec"}
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ImportFrom) and node.module in _PSPEC_SOURCES:
            for alias in node.names:
                if alias.name == "PartitionSpec":
                    names.add(alias.asname or alias.name)
    return names


class AxisRegistryRule(Rule):
    name = "shard-axis-registry"
    description = (
        "collectives, PartitionSpecs, and mesh lookups only reference axes "
        "registered in parallel/mesh.py KNOWN_AXES (resolved through call "
        "chains, defaults, and partial application)"
    )

    def check(self, project: Project) -> Iterator[Violation]:
        registry, err = load_axis_registry(project)
        if err is not None:
            yield Violation(
                rule=self.name,
                path=MESH_MODULE,
                line=1,
                message=err,
            )
            return
        index = FunctionIndex(project)
        # one violation per offending LITERAL: a bad default reaching three
        # collectives is one typo to fix, not three findings
        seen: Set[Tuple[str, int, str]] = set()
        for src in project.files:
            for violation, axis in self._check_file(src, index, registry):
                key = (violation.path, violation.line, axis)
                if key not in seen:
                    seen.add(key)
                    yield violation

    # ----------------------------------------------------------------- #

    def _check_file(
        self, src: SourceFile, index: FunctionIndex, registry: Dict[str, str]
    ) -> Iterator[Tuple[Violation, str]]:
        pspec_names = _pspec_aliases(src)
        for call, enclosing in iter_calls(src):
            name = call_name(call)
            yield from self._check_collective(
                src, index, registry, call, enclosing, name
            )
            if name in pspec_names:
                for arg in call.args:
                    yield from self._flag_bad(
                        src, index, registry, enclosing, arg,
                        f"`{name}(...)` entry",
                    )
            if name.split(".")[-1] == "Mesh":
                for kw in call.keywords:
                    if kw.arg == "axis_names":
                        yield from self._flag_bad(
                            src, index, registry, enclosing, kw.value,
                            "`Mesh(axis_names=...)` entry",
                        )
            # mesh.shape.get("pp", 1)
            if name.endswith(".shape.get") and call.args:
                yield from self._flag_bad(
                    src, index, registry, enclosing, call.args[0],
                    f"`{name}(...)` key",
                )
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Subscript):
                base = dotted_name(node.value)
                if base.endswith(".shape") or base.endswith(".axis_names"):
                    yield from self._flag_literal_only(
                        src, index, registry, node.slice, f"`{base}[...]` key"
                    )
            elif isinstance(node, ast.Compare) and len(node.ops) == 1:
                if isinstance(node.ops[0], (ast.In, ast.NotIn)):
                    target = dotted_name(node.comparators[0])
                    if target.endswith(".shape") or target.endswith(".axis_names"):
                        yield from self._flag_literal_only(
                            src, index, registry, node.left,
                            f"membership test on `{target}`",
                        )

    def _check_collective(
        self,
        src: SourceFile,
        index: FunctionIndex,
        registry: Dict[str, str],
        call: ast.Call,
        enclosing,
        name: str,
    ) -> Iterator[Tuple[Violation, str]]:
        simple = name.split(".")[-1]
        if simple not in _COLLECTIVES:
            return
        prefix = name[: -len(simple)].rstrip(".")
        if prefix not in _LAX_PREFIXES:
            return
        pos = _COLLECTIVES[simple]
        axis_expr: Optional[ast.AST] = None
        for kw in call.keywords:
            if kw.arg in ("axis_name", "axis_names"):
                axis_expr = kw.value
                break
        if axis_expr is None and pos < len(call.args):
            axis_expr = call.args[pos]
        if axis_expr is None:
            return
        yield from self._flag_bad(
            src, index, registry, enclosing, axis_expr,
            f"`{name}` at {src.rel}:{call.lineno}",
        )

    def _flag_bad(
        self,
        src: SourceFile,
        index: FunctionIndex,
        registry: Dict[str, str],
        enclosing,
        expr: ast.AST,
        context: str,
    ) -> Iterator[Tuple[Violation, str]]:
        res = index.resolve_strings(src, enclosing, expr)
        for r in sorted(res.values, key=lambda r: (r.path, r.line, r.value)):
            if r.value not in registry:
                yield Violation(
                    rule=self.name,
                    path=r.path,
                    line=r.line,
                    message=(
                        f"axis '{r.value}' (reaching {context}) is not in "
                        f"KNOWN_AXES ({MESH_MODULE}: "
                        f"{', '.join(sorted(registry))})"
                    ),
                ), r.value

    def _flag_literal_only(
        self,
        src: SourceFile,
        index: FunctionIndex,
        registry: Dict[str, str],
        expr: ast.AST,
        context: str,
    ) -> Iterator[Tuple[Violation, str]]:
        """Subscript keys / membership operands: only flag plain string
        literals and module-level constants (incl. imported ones) — and
        only values that LOOK like axis names (<=3 chars, lowercase), so a
        hypothetical dict keyed on `.shape`/`.axis_names` strings can
        never be dragged in. No call-chain resolution here."""
        value: Optional[str] = None
        line = getattr(expr, "lineno", None)
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            value = expr.value
        elif isinstance(expr, ast.Name):
            const = index.module_consts.get(src.rel, {}).get(expr.id)
            if const is None:
                return
            value = const.value
        if value is None or line is None:
            return
        if len(value) > 3 or not value.islower():
            return  # not axis-shaped: a real dict key like "positions"
        if value not in registry:
            yield Violation(
                rule=self.name,
                path=src.rel,
                line=line,
                message=(
                    f"axis '{value}' ({context}) is not in KNOWN_AXES "
                    f"({MESH_MODULE}: {', '.join(sorted(registry))})"
                ),
            ), value
