"""Rule: shard-collective-symmetry — ppermute permutations are total and
masks are applied before, not after, reductions.

Two failure shapes specific to hand-written collectives inside
`scan`/`fori_loop` bodies (ring attention, pipeline schedules):

  * A `ppermute` permutation that is not total on the axis: devices
    missing as SOURCES receive zeros at the destination — silently, since
    ppermute fills unaddressed destinations instead of failing. A ring
    built as `[(i, (i + 1) % n) for i in range(n)]` is total; a schedule
    built over `range(n - 1)` leaves the last device sending to nobody,
    which is only ever correct for deliberately-open topologies (the
    GPipe forward edge) and must carry a waiver saying so.

  * A mask multiplied onto the RESULT of a `psum`-family reduction:
    `psum(x, axis) * mask` has already accumulated every rank's
    contribution — masking after the fact keeps the unwanted ranks' data
    in the sum on the ranks where mask == 1. The correct shape is
    `psum(x * mask, axis)` (pipeline.py's last-stage broadcast does
    exactly this).

Both checks resolve a Name perm/operand through local assignments in the
enclosing function. Literal permutation lists are additionally checked
for duplicate sources (two sends from one device is a trace-time error on
TPU but only when the axis is actually materialized). Anything the rule
cannot resolve it ignores.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import Project, Rule, SourceFile, Violation, call_name
from .callgraph import Chain, chain_value, iter_calls

_REDUCTIONS = {"psum", "pmean", "pmax", "pmin", "psum_scatter"}
_LAX_PREFIXES = ("", "lax", "jax.lax")


def _is_collective(call: ast.Call, names) -> bool:
    name = call_name(call)
    simple = name.split(".")[-1]
    return simple in names and name[: -len(simple)].rstrip(".") in _LAX_PREFIXES


def _mentions_mask(expr: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Name) and "mask" in n.id.lower()
        for n in ast.walk(expr)
    )


class CollectiveSymmetryRule(Rule):
    name = "shard-collective-symmetry"
    description = (
        "ppermute permutations are total on the axis (non-total topologies "
        "need a waiver) and masks multiply the operand, not the result, of "
        "psum-family reductions"
    )
    scopes = ("ops/", "parallel/", "models/", "engine/")

    def check(self, project: Project) -> Iterator[Violation]:
        for src in project.in_scope(self.scopes):
            for call, chain in iter_calls(src):
                if _is_collective(call, {"ppermute"}):
                    yield from self._check_perm(src, chain, call)
            for node in ast.walk(src.tree):
                if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
                    yield from self._check_mask_after(src, node)

    # ----------------------------------------------------------------- #

    def _check_perm(
        self, src: SourceFile, chain: Chain, call: ast.Call
    ) -> Iterator[Violation]:
        perm = call.args[2] if len(call.args) > 2 else None
        if perm is None:
            for kw in call.keywords:
                if kw.arg == "perm":
                    perm = kw.value
        if perm is None:
            return
        perm = chain_value(chain, perm)
        msg = self._perm_defect(perm)
        if msg is not None:
            yield Violation(
                rule=self.name, path=src.rel, line=call.lineno,
                message=f"`{call_name(call)}`: {msg}",
            )

    @staticmethod
    def _perm_defect(perm: ast.AST) -> Optional[str]:
        # comprehension over range(...): total iff the range covers the
        # whole axis; `range(n - k)` provably leaves devices out
        if isinstance(perm, ast.ListComp) and len(perm.generators) == 1:
            gen = perm.generators[0]
            it = gen.iter
            if isinstance(it, ast.Call) and call_name(it) == "range" \
                    and len(it.args) == 1:
                arg = it.args[0]
                if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Sub) \
                        and isinstance(arg.right, ast.Constant) \
                        and isinstance(arg.right.value, int) \
                        and arg.right.value > 0:
                    return (
                        f"permutation ranges over `{ast.unparse(arg)}` — not "
                        "total on the axis; devices outside the range "
                        "receive ZEROS from ppermute. If the open topology "
                        "is deliberate (e.g. a pipeline forward edge), "
                        "waive with a reason"
                    )
            # element must send FROM the loop variable for the range
            # argument to say anything about totality of sources; an
            # element like `(0, i)` fans out from one source only
            if isinstance(perm.elt, ast.Tuple) and len(perm.elt.elts) == 2 \
                    and isinstance(gen.target, ast.Name):
                src_el = perm.elt.elts[0]
                if isinstance(src_el, ast.Constant):
                    return (
                        "every pair sends from the same constant source "
                        f"`{ast.unparse(src_el)}` — not a permutation of "
                        "the axis"
                    )
            return None
        # literal list of constant pairs: duplicate sources are always a
        # defect (ppermute requires source-uniqueness)
        if isinstance(perm, (ast.List, ast.Tuple)):
            sources = []
            for el in perm.elts:
                if isinstance(el, ast.Tuple) and len(el.elts) == 2 \
                        and isinstance(el.elts[0], ast.Constant):
                    sources.append(el.elts[0].value)
                else:
                    return None  # not fully literal: stay quiet
            dupes = {s for s in sources if sources.count(s) > 1}
            if dupes:
                return (
                    f"duplicate send source(s) {sorted(dupes)} — a "
                    "permutation sends from each device at most once"
                )
        return None

    def _check_mask_after(
        self, src: SourceFile, node: ast.BinOp
    ) -> Iterator[Violation]:
        for reduced, other in ((node.left, node.right), (node.right, node.left)):
            if isinstance(reduced, ast.Call) \
                    and _is_collective(reduced, _REDUCTIONS) \
                    and _mentions_mask(other):
                yield Violation(
                    rule=self.name, path=src.rel, line=node.lineno,
                    message=(
                        f"mask applied AFTER `{call_name(reduced)}` — the "
                        "reduction has already accumulated every rank's "
                        "contribution; multiply the mask into the operand "
                        "(`psum(x * mask, axis)`) instead"
                    ),
                )
                return
