"""Mocker worker: `python -m dynamo_tpu.mocker` — a fake engine worker.

Mirrors reference components/backends/mocker (main.py): registers a model
card + generate endpoint backed by the block-accounting MockEngine, so
multi-worker routing/disagg/migration can run without TPUs.
"""

import argparse
import asyncio
import logging

from dynamo_tpu.llm.mocker import MockEngine, MockEngineArgs
from dynamo_tpu.llm.model_card import ModelDeploymentCard, register_llm
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig, init_logging

logger = logging.getLogger("dynamo_tpu.mocker")


def parse_args():
    ap = argparse.ArgumentParser(description="dynamo-tpu mocker worker")
    ap.add_argument("--model-name", default="mock-model")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--component", default="mocker")
    ap.add_argument("--endpoint", default="generate")
    ap.add_argument("--discovery", default=None, help="tcp://host:port of discovery")
    ap.add_argument("--block-size", type=int, default=64)
    ap.add_argument("--num-gpu-blocks", type=int, default=4096)
    ap.add_argument("--max-num-seqs", type=int, default=256)
    ap.add_argument("--max-num-batched-tokens", type=int, default=8192)
    ap.add_argument("--speedup-ratio", type=float, default=10.0)
    ap.add_argument("--no-prefix-caching", action="store_true")
    ap.add_argument("--migration-limit", type=int, default=3)
    ap.add_argument("--kv-events", action="store_true", help="publish KV events")
    ap.add_argument("--warmup-delay", type=float, default=0.0,
                    help="extra seconds of simulated compile time during "
                    "warmup (ordering tests observe the pre-registration "
                    "window with this)")
    return ap.parse_args()


async def main():
    init_logging()
    args = parse_args()
    cfg = RuntimeConfig.from_settings()
    if args.discovery:
        cfg.discovery_endpoint = args.discovery
    drt = await DistributedRuntime.create(cfg)
    # SIGTERM (planner scale-down) must walk the graceful drain, not the
    # interpreter's default hard exit that kills in-flight streams
    drt.install_signal_handlers()

    engine_args = MockEngineArgs(
        model_name=args.model_name,
        num_gpu_blocks=args.num_gpu_blocks,
        block_size=args.block_size,
        max_num_seqs=args.max_num_seqs,
        max_num_batched_tokens=args.max_num_batched_tokens,
        enable_prefix_caching=not args.no_prefix_caching,
        speedup_ratio=args.speedup_ratio,
    )

    endpoint = (
        drt.namespace(args.namespace).component(args.component).endpoint(args.endpoint)
    )

    publisher = None
    if args.kv_events:
        from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher

        publisher = KvEventPublisher(drt, endpoint, drt.instance_id)
        await publisher.start()

    engine = MockEngine(engine_args)

    # warmup BEFORE anything is registered in discovery: the worker must
    # not be routable until first-iteration costs are paid (same contract
    # as the jax_worker --warmup flow; the KV-event sink attaches after so
    # warmup prefixes never pollute the router index)
    n_warm = await engine.warmup(extra_delay=args.warmup_delay)
    logger.info("mocker warmup done: %d requests", n_warm)
    if publisher is not None:
        engine.kv.event_sink = publisher.publish

    from dynamo_tpu.llm.kv_router.publisher import WorkerMetricsPublisher

    metrics_pub = WorkerMetricsPublisher(drt, endpoint, drt.instance_id, engine.stats)
    await metrics_pub.start()

    card = ModelDeploymentCard(
        name=args.model_name,
        tokenizer="byte",
        kv_cache_block_size=args.block_size,
        migration_limit=args.migration_limit,
    )

    # metrics publishing for the KV router's scheduler
    async def stats_loop():
        while True:
            stats = drt.server.stats(endpoint.subject)
            if stats is not None:
                stats.data = engine.stats()
            await asyncio.sleep(0.5)

    stats_task = asyncio.create_task(stats_loop())

    async def handler(request, context):
        if request.get("embed"):
            # deterministic fake embedding (hash-seeded) so the embeddings
            # path is testable without a real embedding model
            import hashlib

            token_ids = request.get("token_ids") or []
            h = hashlib.sha256(bytes(str(token_ids), "utf-8")).digest()
            dim = 32
            vec = [((h[i % len(h)] / 255.0) * 2 - 1) for i in range(dim)]
            yield {"embedding": vec, "finish_reason": "stop"}
            return
        # nvext annotation support: announce which worker serves the request
        # (reference annotations e.g. worker_id / kv_hit_rate)
        if "worker_instance_id" in (request.get("annotations") or []):
            yield {
                "event": "worker_instance_id",
                "comment": [f"{drt.instance_id:x}"],
            }
        if "kv_hit_rate" in (request.get("annotations") or []):
            hit = request.get("estimated_prefix_hit_num_blocks") or 0
            yield {"event": "kv_hit_rate", "comment": [str(hit)]}
        async for item in engine.generate(request, context):
            yield item

    # instance first, card second: frontends build a model pipeline the
    # moment the CARD appears, so the instance must already be live when
    # they look — the reverse order opens a routable-but-absent window
    # (StreamLost storms on cold start)
    await endpoint.serve_endpoint(handler)
    await register_llm(endpoint, card)
    logger.info("mocker worker up: model=%s instance=%x", args.model_name, drt.instance_id)
    await drt.wait_for_shutdown()
    stats_task.cancel()
    await drt.close()  # graceful drain (runtime/component.py close())


if __name__ == "__main__":
    asyncio.run(main())
