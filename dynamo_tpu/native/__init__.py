"""ctypes binding for the native core (csrc/dynamo_core.cpp).

Loads csrc/libdynamo_core.so, building it on first use if the toolchain is
available. Every entry point has a pure-Python twin (llm/tokens.py,
llm/kv_router/indexer.py); callers use `native_available()` / the
`NativeRadixTree` class and fall back transparently. Disable with
DYN_NATIVE=0.

Reference parity: lib/llm/src/tokens.rs compute_hash_v2 :36 and
kv_router/indexer.rs RadixTree :224 (Rust there; C++ + ctypes here).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Dict, List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "csrc")
_SO = os.path.join(_CSRC, "libdynamo_core.so")

_lib = None
_load_attempted = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    from ..runtime.config import env_bool

    if not env_bool("DYN_NATIVE", True):
        return None
    # always invoke make: a no-op when the .so is fresh, a rebuild when
    # csrc/ changed (a stale gitignored .so must not silently win)
    try:
        subprocess.run(
            ["make", "-C", _CSRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except Exception as e:  # noqa: BLE001 — fall back to pure Python
        logger.info("native core build failed (%s); using pure Python", e)
        if not os.path.exists(_SO):
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        logger.info("native core load failed (%s); using pure Python", e)
        return None
    u64, i64, p = ctypes.c_uint64, ctypes.c_int64, ctypes.c_void_p
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    lib.dyn_block_hash.restype = u64
    lib.dyn_block_hash.argtypes = [u32p, u64, u64]
    lib.dyn_seq_hashes.restype = u64
    lib.dyn_seq_hashes.argtypes = [u32p, u64, u64, u64, u64p]
    lib.dyn_index_new.restype = p
    lib.dyn_index_free.argtypes = [p]
    lib.dyn_index_apply_stored.argtypes = [p, i64, u64p, u64]
    lib.dyn_index_apply_removed.argtypes = [p, i64, u64p, u64]
    lib.dyn_index_remove_worker.argtypes = [p, i64]
    lib.dyn_index_num_blocks.restype = u64
    lib.dyn_index_num_blocks.argtypes = [p]
    lib.dyn_index_worker_block_count.restype = u64
    lib.dyn_index_worker_block_count.argtypes = [p, i64]
    lib.dyn_index_find_matches.restype = u64
    lib.dyn_index_find_matches.argtypes = [
        p, u64p, u64, ctypes.c_int, i64p, u64p, u64, u64p, u64p,
    ]
    lib.dyn_index_dump.restype = u64
    lib.dyn_index_dump.argtypes = [p, i64p, u64p, u64]
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


def _as_u64_array(hashes: Sequence[int]) -> np.ndarray:
    # Python ints may exceed int64; hashes are u64 by construction
    return np.asarray([h & 0xFFFFFFFFFFFFFFFF for h in hashes], dtype=np.uint64)


def _as_u32_tokens(tokens: Sequence[int]) -> np.ndarray:
    """Match the pure-Python path's `tok & 0xFFFFFFFF` masking (tokens.py)
    instead of letting numpy raise OverflowError on out-of-range ids."""
    arr = np.asarray(tokens)
    if arr.dtype == np.uint32:
        return arr
    return (np.asarray(arr, dtype=np.int64) & 0xFFFFFFFF).astype(np.uint32)


def compute_block_hash(tokens: Sequence[int], parent_hash: int = 0) -> int:
    lib = _load()
    toks = _as_u32_tokens(tokens)
    return int(
        lib.dyn_block_hash(
            toks.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            len(toks),
            parent_hash & 0xFFFFFFFFFFFFFFFF,
        )
    )


def compute_seq_hashes(
    tokens: Sequence[int], block_size: int = 64, salt: int = 0
) -> List[int]:
    lib = _load()
    toks = _as_u32_tokens(tokens)
    out = np.empty(max(len(toks) // block_size, 1), dtype=np.uint64)
    n = lib.dyn_seq_hashes(
        toks.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        len(toks),
        block_size,
        salt & 0xFFFFFFFFFFFFFFFF,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    return [int(h) for h in out[:n]]


class NativeRadixTree:
    """Drop-in for llm.kv_router.indexer.RadixTree backed by the C++ index."""

    MAX_WORKERS = 4096

    def __init__(self):
        self._lib = _load()
        if self._lib is None:
            raise RuntimeError("native core unavailable")
        self._idx = self._lib.dyn_index_new()
        # per-instance scratch (find_matches is called from one scheduler
        # task at a time); avoids per-call allocation overhead
        self._workers_buf = np.empty(self.MAX_WORKERS, dtype=np.int64)
        self._scores_buf = np.empty(self.MAX_WORKERS, dtype=np.uint64)
        self._freqs_buf = np.empty(4096, dtype=np.uint64)
        self._hash_buf = np.empty(4096, dtype=np.uint64)

    def __del__(self):
        lib = getattr(self, "_lib", None)
        idx = getattr(self, "_idx", None)
        if lib is not None and idx:
            lib.dyn_index_free(idx)
            self._idx = None

    def apply_stored(self, worker_id: int, block_hashes: List[int],
                     chained: bool = True, parent=None):
        # chained/parent are the Python tree's bounded-eviction chain
        # metadata; the C++ index is unbounded and ignores them
        arr = _as_u64_array(block_hashes)
        self._lib.dyn_index_apply_stored(
            self._idx,
            worker_id,
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(arr),
        )

    def apply_removed(self, worker_id: int, block_hashes: List[int]):
        arr = _as_u64_array(block_hashes)
        self._lib.dyn_index_apply_removed(
            self._idx,
            worker_id,
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(arr),
        )

    def remove_worker(self, worker_id: int):
        self._lib.dyn_index_remove_worker(self._idx, worker_id)

    def clear_all_blocks(self, worker_id: int):
        self.remove_worker(worker_id)

    def find_matches(self, seq_hashes: List[int], early_exit: bool = False):
        from ..llm.kv_router.indexer import OverlapScores

        result = OverlapScores()
        if not seq_hashes:
            return result
        nh = len(seq_hashes)
        if nh > len(self._hash_buf):
            self._hash_buf = np.empty(nh, dtype=np.uint64)
            self._freqs_buf = np.empty(nh, dtype=np.uint64)
        self._hash_buf[:nh] = np.asarray(seq_hashes, dtype=np.uint64)
        workers, scores, freqs = self._workers_buf, self._scores_buf, self._freqs_buf
        freq_n = ctypes.c_uint64(0)
        n = self._lib.dyn_index_find_matches(
            self._idx,
            self._hash_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            nh,
            1 if early_exit else 0,
            workers.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            scores.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            self.MAX_WORKERS,
            freqs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            ctypes.byref(freq_n),
        )
        result.scores = {int(workers[i]): int(scores[i]) for i in range(n)}
        result.frequencies = freqs[: freq_n.value].tolist()
        return result

    @property
    def num_blocks(self) -> int:
        return int(self._lib.dyn_index_num_blocks(self._idx))

    def worker_block_count(self, worker_id: int) -> int:
        return int(self._lib.dyn_index_worker_block_count(self._idx, worker_id))

    def workers(self) -> List[int]:
        return [w for w, hs in self._dump_pairs().items() if hs]

    def _dump_pairs(self) -> Dict[int, List[int]]:
        total = int(self._lib.dyn_index_dump(self._idx, None, None, 0))
        if total == 0:
            return {}
        workers = np.empty(total, dtype=np.int64)
        hashes = np.empty(total, dtype=np.uint64)
        n = self._lib.dyn_index_dump(
            self._idx,
            workers.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            hashes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            total,
        )
        out: Dict[int, List[int]] = {}
        for i in range(n):
            out.setdefault(int(workers[i]), []).append(int(hashes[i]))
        return out

    def dump(self) -> dict:
        return {str(w): sorted(hs) for w, hs in self._dump_pairs().items()}

    def load(self, snapshot: dict):
        for w_str, hashes in snapshot.items():
            self.apply_stored(int(w_str), list(hashes))


def make_radix_tree(max_blocks=None):
    """Best tree available: native C++ index, else the Python one. A
    block-count cap (`max_blocks`, DYN_ROUTER_INDEX_MAX_BLOCKS) forces
    the Python tree — leaf-first eviction needs the chain bookkeeping the
    C++ index does not carry; a bounded index is chosen for memory, not
    match speed, so that is the right trade."""
    from ..llm.kv_router.indexer import RadixTree

    if max_blocks is not None and max_blocks > 0:
        return RadixTree(max_blocks=max_blocks)
    if native_available():
        return NativeRadixTree()
    return RadixTree()
