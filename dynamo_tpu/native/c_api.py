"""Python side of the C event ABI (reference lib/bindings/c).

Native engine code publishes KV events through dyn_llm_init /
dyn_kv_publish_* (csrc/dynamo_core.cpp); `NativeKvEventQueue` wraps the
handle via ctypes and `pump()` forwards drained events into a
KvEventPublisher so they reach the router's event topic.
"""

from __future__ import annotations

import asyncio
import ctypes
from typing import List, Optional

import numpy as np

from . import _load

EVENT_TYPES = {0: "stored", 1: "removed", 2: "cleared"}


class NativeKvEventQueue:
    """ctypes wrapper over the C ABI's thread-safe event queue."""

    def __init__(self, capacity: int = 65536):
        self._lib = _load()
        if self._lib is None:
            raise RuntimeError("native core unavailable (build csrc/ first)")
        self._bind(self._lib)
        self._h = self._lib.dyn_llm_init(capacity)
        self._buf = np.empty(4096, dtype=np.uint64)

    @staticmethod
    def _bind(lib) -> None:
        if getattr(lib, "_dyn_c_abi_bound", False):
            return
        u64 = ctypes.c_uint64
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64 = ctypes.c_int64
        p = ctypes.c_void_p
        lib.dyn_llm_init.restype = p
        lib.dyn_llm_init.argtypes = [u64]
        lib.dyn_llm_shutdown.argtypes = [p]
        for fn in (lib.dyn_kv_publish_stored, lib.dyn_kv_publish_removed):
            fn.restype = None
            fn.argtypes = [p, i64, u64p, u64]
        lib.dyn_kv_publish_cleared.restype = None
        lib.dyn_kv_publish_cleared.argtypes = [p, i64]
        lib.dyn_kv_event_pop.restype = i64
        lib.dyn_kv_event_pop.argtypes = [
            p, ctypes.POINTER(i64), ctypes.POINTER(ctypes.c_int32), u64p, u64,
            ctypes.POINTER(u64),
        ]
        for fn in (lib.dyn_kv_events_dropped, lib.dyn_kv_events_pending):
            fn.restype = u64
            fn.argtypes = [p]
        lib._dyn_c_abi_bound = True

    def close(self) -> None:
        if self._h:
            self._lib.dyn_llm_shutdown(self._h)
            self._h = None

    def _handle(self):
        if not self._h:
            # a NULL handle into the C ABI is a segfault, not an exception
            raise RuntimeError("NativeKvEventQueue used after close()")
        return self._h

    # -- publish (normally called from native threads; exposed for tests) --
    def _hashes_ptr(self, hashes: List[int]):
        arr = np.asarray(hashes, dtype=np.uint64)
        return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))

    def publish_stored(self, worker_id: int, block_hashes: List[int]) -> None:
        arr, ptr = self._hashes_ptr(block_hashes)
        self._lib.dyn_kv_publish_stored(self._handle(), worker_id, ptr, len(arr))

    def publish_removed(self, worker_id: int, block_hashes: List[int]) -> None:
        arr, ptr = self._hashes_ptr(block_hashes)
        self._lib.dyn_kv_publish_removed(self._handle(), worker_id, ptr, len(arr))

    def publish_cleared(self, worker_id: int) -> None:
        self._lib.dyn_kv_publish_cleared(self._handle(), worker_id)

    # -- drain --------------------------------------------------------------
    def pop(self) -> Optional[dict]:
        h = self._handle()
        worker = ctypes.c_int64(0)
        etype = ctypes.c_int32(0)
        need = ctypes.c_uint64(0)
        while True:
            n = self._lib.dyn_kv_event_pop(
                h, ctypes.byref(worker), ctypes.byref(etype),
                self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                len(self._buf), ctypes.byref(need),
            )
            if n == -1:
                return None
            if n == -2:
                self._buf = np.empty(int(need.value), dtype=np.uint64)
                continue
            return {
                "worker_id": int(worker.value),
                "event_type": EVENT_TYPES[int(etype.value)],
                "block_hashes": self._buf[:n].tolist(),
            }

    def drain(self, limit: int = 1024) -> List[dict]:
        out = []
        for _ in range(limit):
            ev = self.pop()
            if ev is None:
                break
            out.append(ev)
        return out

    @property
    def pending(self) -> int:
        return int(self._lib.dyn_kv_events_pending(self._handle()))

    @property
    def dropped(self) -> int:
        return int(self._lib.dyn_kv_events_dropped(self._handle()))

    async def pump(self, publishers, interval: float = 0.05) -> None:
        """Forward drained events into KvEventPublishers until cancelled.
        `publishers` is a single KvEventPublisher (only its own worker's
        events are forwarded — events the indexer would mis-attribute to
        the wrong worker are dropped with a warning) or a dict
        {worker_id: KvEventPublisher}."""
        import logging

        from ..llm.mocker.kv_manager import KvEvent

        log = logging.getLogger(__name__)
        by_worker = publishers if isinstance(publishers, dict) else None
        single = None if by_worker is not None else publishers
        while True:
            for ev in self.drain():
                if by_worker is not None:
                    pub = by_worker.get(ev["worker_id"])
                elif single is not None and ev["worker_id"] == single.worker_id:
                    pub = single
                else:
                    pub = None
                if pub is None:
                    log.warning(
                        "dropping native KV event for unknown worker %d",
                        ev["worker_id"],
                    )
                    continue
                pub.publish(
                    KvEvent(
                        event_type=ev["event_type"],
                        block_hashes=ev["block_hashes"],
                    )
                )
            await asyncio.sleep(interval)
