"""DynamoGraphDeployment: CRD-grade multi-service reconciliation.

Role of the reference operator's CRD semantics
(deploy/cloud/operator/api/v1alpha1/dynamographdeployment_types.go +
dynamocomponentdeployment_controller.go): one custom resource describes
the WHOLE serving graph — frontend, worker pools by role, planner, encode
worker — and a controller reconciles every service to its declared
replica count, with the SLA planner's decision overlaying the
prefill/decode counts.

The TPU build keeps the reconciler in-process (operator_lite) but adopts
the CR shape: `GraphSpec.from_manifest` parses a DynamoGraphDeployment
manifest (deploy/k8s/crd-dynamographdeployment.yaml defines the CRD;
example-graphdeployment.yaml is a working CR), renders per-service k8s
Deployments for the kubectl backend, or drives local subprocess pools
for tests/single-host serving.
"""

from __future__ import annotations

import asyncio
import logging
import subprocess
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger("dynamo_tpu.deploy.graph")

API_VERSION = "dynamo.tpu/v1alpha1"
KIND = "DynamoGraphDeployment"


@dataclass
class ServiceSpec:
    """One service of the graph (reference: spec.services map entry)."""

    name: str
    module: str  # python -m <module>
    replicas: int = 1
    role: Optional[str] = None  # prefill | decode | None (role-less)
    args: List[str] = field(default_factory=list)

    @property
    def deployment_name(self) -> str:
        return self.name.lower().replace("_", "-")

    def command(self) -> List[str]:
        return ["python", "-m", self.module, *self.args]


@dataclass
class GraphSpec:
    name: str
    namespace: str
    image: str
    services: List[ServiceSpec]

    @classmethod
    def from_manifest(cls, doc: dict) -> "GraphSpec":
        if doc.get("apiVersion") != API_VERSION or doc.get("kind") != KIND:
            raise ValueError(
                f"not a {KIND} ({API_VERSION}): "
                f"{doc.get('apiVersion')}/{doc.get('kind')}"
            )
        meta = doc.get("metadata") or {}
        spec = doc.get("spec") or {}
        raw = spec.get("services") or {}
        if not raw:
            raise ValueError("spec.services is empty")
        services = []
        for name, s in raw.items():
            if "module" not in s:
                raise ValueError(f"service {name!r} has no module")
            role = s.get("role")
            if role not in (None, "prefill", "decode"):
                raise ValueError(f"service {name!r}: unknown role {role!r}")
            services.append(
                ServiceSpec(
                    name=name,
                    module=s["module"],
                    replicas=int(s.get("replicas", 1)),
                    role=role,
                    args=[str(a) for a in (s.get("args") or [])],
                )
            )
        return cls(
            name=meta.get("name", "dynamo-graph"),
            namespace=meta.get("namespace", "default"),
            image=spec.get("image", "dynamo-tpu:latest"),
            services=services,
        )

    def with_planner_overlay(
        self, num_prefill: Optional[int], num_decode: Optional[int]
    ) -> "GraphSpec":
        """The planner's decision overrides replica counts of role-tagged
        services (reference: the planner patches the CRD's worker
        replicas; role-less services keep their declared counts)."""
        out = []
        for s in self.services:
            replicas = s.replicas
            if s.role == "prefill" and num_prefill is not None:
                replicas = num_prefill
            elif s.role == "decode" and num_decode is not None:
                replicas = num_decode
            out.append(ServiceSpec(s.name, s.module, replicas, s.role, list(s.args)))
        return GraphSpec(self.name, self.namespace, self.image, out)

    def render_deployments(self) -> List[dict]:
        """k8s Deployment docs, one per service — what the kubectl backend
        applies. Matches the label scheme of deploy/k8s/ manifests."""
        docs = []
        for s in self.services:
            full = f"{self.name}-{s.deployment_name}"
            docs.append(
                {
                    "apiVersion": "apps/v1",
                    "kind": "Deployment",
                    "metadata": {
                        "name": full,
                        "namespace": self.namespace,
                        "labels": {
                            "app": full,
                            "dynamo.tpu/graph": self.name,
                            "dynamo.tpu/service": s.name,
                        },
                    },
                    "spec": {
                        "replicas": s.replicas,
                        "selector": {"matchLabels": {"app": full}},
                        "template": {
                            "metadata": {"labels": {"app": full}},
                            "spec": {
                                "containers": [
                                    {
                                        "name": s.deployment_name,
                                        "image": self.image,
                                        "command": s.command(),
                                    }
                                ]
                            },
                        },
                    },
                }
            )
        return docs


class LocalGraphBackend:
    """Reconcile every service to N local subprocesses (tests and
    single-host serving; the graph analogue of LocalProcessConnector)."""

    def __init__(self, env: Optional[dict] = None, python: Optional[str] = None):
        self._procs: Dict[str, List[subprocess.Popen]] = {}
        self.env = env
        self.python = python or sys.executable

    def _spawn(self, svc: ServiceSpec) -> subprocess.Popen:
        cmd = [self.python, "-m", svc.module, *svc.args]
        # DEVNULL stdin: services must not share (or die on EOF of) the
        # operator's stdin
        return subprocess.Popen(cmd, env=self.env, stdin=subprocess.DEVNULL)

    async def apply(self, graph: GraphSpec) -> None:
        for svc in graph.services:
            pool = [p for p in self._procs.get(svc.name, []) if p.poll() is None]
            while len(pool) < svc.replicas:
                pool.append(self._spawn(svc))
                logger.info("graph %s: started %s replica (%d/%d)",
                            graph.name, svc.name, len(pool), svc.replicas)
            while len(pool) > svc.replicas:
                p = pool.pop()
                p.terminate()
                logger.info("graph %s: stopped %s replica (%d/%d)",
                            graph.name, svc.name, len(pool), svc.replicas)
            self._procs[svc.name] = pool

    def replica_counts(self) -> Dict[str, int]:
        return {
            name: sum(1 for p in pool if p.poll() is None)
            for name, pool in self._procs.items()
        }

    def shutdown(self) -> None:
        for pool in self._procs.values():
            for p in pool:
                if p.poll() is None:
                    p.terminate()
        for pool in self._procs.values():
            for p in pool:
                try:
                    p.wait(timeout=3)
                except subprocess.TimeoutExpired:
                    p.kill()
        self._procs.clear()


class KubectlGraphBackend:
    """Apply the rendered Deployments with `kubectl apply` (idempotent:
    replica changes ride the same apply)."""

    def __init__(self, kubectl: str = "kubectl"):
        self.kubectl = kubectl

    async def apply(self, graph: GraphSpec) -> None:
        import json as _json

        manifest = _json.dumps(
            {"apiVersion": "v1", "kind": "List",
             "items": graph.render_deployments()}
        )
        proc = await asyncio.create_subprocess_exec(
            self.kubectl, "-n", graph.namespace, "apply", "-f", "-",
            stdin=asyncio.subprocess.PIPE,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        out, err = await proc.communicate(manifest.encode())
        if proc.returncode != 0:
            raise RuntimeError(
                f"kubectl apply failed rc={proc.returncode}: {err.decode()!r}"
            )
        logger.info("applied graph %s: %s", graph.name, out.decode().strip())
