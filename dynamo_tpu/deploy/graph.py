"""DynamoGraphDeployment: CRD-grade multi-service reconciliation.

Role of the reference operator's CRD semantics
(deploy/cloud/operator/api/v1alpha1/dynamographdeployment_types.go +
dynamocomponentdeployment_controller.go): one custom resource describes
the WHOLE serving graph — frontend, worker pools by role, planner, encode
worker — and a controller reconciles every service to its declared
replica count, with the SLA planner's decision overlaying the
prefill/decode counts.

The TPU build keeps the reconciler in-process (operator_lite) but adopts
the CR shape: `GraphSpec.from_manifest` parses a DynamoGraphDeployment
manifest (deploy/k8s/crd-dynamographdeployment.yaml defines the CRD;
example-graphdeployment.yaml is a working CR), renders per-service k8s
Deployments for the kubectl backend, or drives local subprocess pools
for tests/single-host serving.
"""

from __future__ import annotations

import asyncio
import logging
import subprocess
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger("dynamo_tpu.deploy.graph")

API_VERSION = "dynamo.tpu/v1alpha1"
KIND = "DynamoGraphDeployment"


@dataclass
class ServiceSpec:
    """One service of the graph (reference: spec.services map entry)."""

    name: str
    module: str  # python -m <module>
    replicas: int = 1
    role: Optional[str] = None  # prefill | decode | None (role-less)
    args: List[str] = field(default_factory=list)

    @property
    def deployment_name(self) -> str:
        return self.name.lower().replace("_", "-")

    def command(self) -> List[str]:
        return ["python", "-m", self.module, *self.args]


@dataclass
class GraphSpec:
    name: str
    namespace: str
    image: str
    services: List[ServiceSpec]

    @classmethod
    def from_manifest(cls, doc: dict) -> "GraphSpec":
        if doc.get("apiVersion") != API_VERSION or doc.get("kind") != KIND:
            raise ValueError(
                f"not a {KIND} ({API_VERSION}): "
                f"{doc.get('apiVersion')}/{doc.get('kind')}"
            )
        meta = doc.get("metadata") or {}
        spec = doc.get("spec") or {}
        raw = spec.get("services") or {}
        if not raw:
            raise ValueError("spec.services is empty")
        services = []
        for name, s in raw.items():
            if "module" not in s:
                raise ValueError(f"service {name!r} has no module")
            role = s.get("role")
            if role not in (None, "prefill", "decode"):
                raise ValueError(f"service {name!r}: unknown role {role!r}")
            services.append(
                ServiceSpec(
                    name=name,
                    module=s["module"],
                    replicas=int(s.get("replicas", 1)),
                    role=role,
                    args=[str(a) for a in (s.get("args") or [])],
                )
            )
        return cls(
            name=meta.get("name", "dynamo-graph"),
            namespace=meta.get("namespace", "default"),
            image=spec.get("image", "dynamo-tpu:latest"),
            services=services,
        )

    def with_planner_overlay(
        self, num_prefill: Optional[int], num_decode: Optional[int]
    ) -> "GraphSpec":
        """The planner's decision overrides replica counts of role-tagged
        services (reference: the planner patches the CRD's worker
        replicas; role-less services keep their declared counts)."""
        out = []
        for s in self.services:
            replicas = s.replicas
            if s.role == "prefill" and num_prefill is not None:
                replicas = num_prefill
            elif s.role == "decode" and num_decode is not None:
                replicas = num_decode
            out.append(ServiceSpec(s.name, s.module, replicas, s.role, list(s.args)))
        return GraphSpec(self.name, self.namespace, self.image, out)

    def render_deployments(self) -> List[dict]:
        """k8s Deployment docs, one per service — what the kubectl backend
        applies. Matches the label scheme of deploy/k8s/ manifests."""
        docs = []
        for s in self.services:
            full = f"{self.name}-{s.deployment_name}"
            docs.append(
                {
                    "apiVersion": "apps/v1",
                    "kind": "Deployment",
                    "metadata": {
                        "name": full,
                        "namespace": self.namespace,
                        "labels": {
                            "app": full,
                            "dynamo.tpu/graph": self.name,
                            "dynamo.tpu/service": s.name,
                        },
                    },
                    "spec": {
                        "replicas": s.replicas,
                        "selector": {"matchLabels": {"app": full}},
                        "template": {
                            "metadata": {"labels": {"app": full}},
                            "spec": {
                                "containers": [
                                    {
                                        "name": s.deployment_name,
                                        "image": self.image,
                                        "command": s.command(),
                                    }
                                ]
                            },
                        },
                    },
                }
            )
        return docs


class LocalGraphBackend:
    """Reconcile every service to N local subprocesses (tests and
    single-host serving; the graph analogue of LocalProcessConnector)."""

    def __init__(self, env: Optional[dict] = None, python: Optional[str] = None):
        self._procs: Dict[str, List[subprocess.Popen]] = {}
        self._cmds: Dict[str, tuple] = {}
        self._reap: List[subprocess.Popen] = []  # terminated, await wait()
        self.env = env
        self.python = python or sys.executable

    def _reap_terminated(self):
        """Collect exited replicas we previously terminate()d (zombie
        prevention); survivors stay queued for shutdown()'s escalation."""
        still = []
        for p in self._reap:
            if p.poll() is None:
                still.append(p)
        self._reap = still

    def _spawn(self, svc: ServiceSpec) -> subprocess.Popen:
        cmd = [self.python, "-m", svc.module, *svc.args]
        # DEVNULL stdin: services must not share (or die on EOF of) the
        # operator's stdin
        return subprocess.Popen(cmd, env=self.env, stdin=subprocess.DEVNULL)

    async def apply(self, graph: GraphSpec) -> None:
        for svc in graph.services:
            # rollout: a TEMPLATE change (module/args), not just a replica
            # change, replaces every running replica — the subprocess
            # analogue of a Deployment pod-template rollout
            cmd = tuple(svc.command())
            if self._cmds.get(svc.name) not in (None, cmd):
                stale = self._procs.pop(svc.name, [])
                for p in stale:
                    if p.poll() is None:
                        p.terminate()
                        self._reap.append(p)
                logger.info(
                    "graph %s: rolling %s (%d stale replicas terminated)",
                    graph.name, svc.name, len(stale),
                )
            self._cmds[svc.name] = cmd
            pool = [p for p in self._procs.get(svc.name, []) if p.poll() is None]
            while len(pool) < svc.replicas:
                pool.append(self._spawn(svc))
                logger.info("graph %s: started %s replica (%d/%d)",
                            graph.name, svc.name, len(pool), svc.replicas)
            while len(pool) > svc.replicas:
                p = pool.pop()
                p.terminate()
                self._reap.append(p)
                logger.info("graph %s: stopped %s replica (%d/%d)",
                            graph.name, svc.name, len(pool), svc.replicas)
            self._procs[svc.name] = pool
        self._reap_terminated()

    def replica_counts(self) -> Dict[str, int]:
        return {
            name: sum(1 for p in pool if p.poll() is None)
            for name, pool in self._procs.items()
        }

    def shutdown(self) -> None:
        pools = list(self._procs.values()) + [self._reap]
        for pool in pools:
            for p in pool:
                if p.poll() is None:
                    p.terminate()
        for pool in pools:
            for p in pool:
                try:
                    p.wait(timeout=3)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=3)
        self._procs.clear()
        self._reap = []


class GraphController:
    """Controller semantics over a graph backend — the part of the
    reference operator the round-4 review flagged as missing
    (dynamographdeployment_controller.go): status conditions with
    transitions, observedGeneration writeback, rollout on template
    change (delegated to the backend's apply), and exponential failure
    backoff instead of hot-looping a broken spec.

    `status()` returns the CR-status-shaped dict; backends exposing
    `patch_status` (kubectl) get it written back after every reconcile.
    """

    BACKOFF_BASE_S = 2.0
    BACKOFF_MAX_S = 60.0

    def __init__(self, backend, now=None):
        import time as _time

        self.backend = backend
        self.now = now or _time.monotonic
        self._conditions: Dict[str, dict] = {}
        self._observed_generation = 0
        self._failures = 0
        self._retry_at = 0.0
        self._last_graph: Optional[GraphSpec] = None

    # -- conditions ----------------------------------------------------- #

    def _set_condition(self, ctype: str, status: str, reason: str,
                       message: str = ""):
        import time as _time

        cur = self._conditions.get(ctype)
        if cur and cur["status"] == status and cur["reason"] == reason:
            cur["message"] = message
            return
        self._conditions[ctype] = {
            "type": ctype,
            "status": status,
            "reason": reason,
            "message": message,
            # k8s-conventional RFC3339 (self.now drives only the backoff
            # clock and may be monotonic/fake)
            "lastTransitionTime": _time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", _time.gmtime()
            ),
        }

    def condition(self, ctype: str) -> Optional[dict]:
        return self._conditions.get(ctype)

    def status(self) -> dict:
        st = {
            "observedGeneration": self._observed_generation,
            "conditions": sorted(
                self._conditions.values(), key=lambda c: c["type"]
            ),
        }
        counts = getattr(self.backend, "replica_counts", None)
        if counts is not None:
            st["services"] = counts()
        return st

    # -- reconcile ------------------------------------------------------ #

    @property
    def backoff_remaining(self) -> float:
        return max(0.0, self._retry_at - self.now())

    @property
    def needs_retry(self) -> bool:
        """True while the last apply failed — whether the backoff window
        is still open (reconcile() will no-op) or has expired (reconcile()
        will actually retry)."""
        return self._failures > 0

    async def reconcile(self, graph: GraphSpec, generation: int) -> bool:
        """One reconcile pass. Returns True when the spec was applied,
        False when skipped (failure backoff window). Raises nothing:
        apply errors become the Degraded condition + backoff."""
        if self._failures and self.now() < self._retry_at:
            return False
        self._set_condition(
            "Progressing", "True", "Reconciling",
            f"applying generation {generation}",
        )
        try:
            await self.backend.apply(graph)
        except Exception as e:  # noqa: BLE001 — apply errors become status
            self._failures += 1
            delay = min(
                self.BACKOFF_BASE_S * (2 ** (self._failures - 1)),
                self.BACKOFF_MAX_S,
            )
            self._retry_at = self.now() + delay
            self._set_condition(
                "Degraded", "True", "ApplyFailed",
                f"{type(e).__name__}: {e} (retry in {delay:.0f}s)",
            )
            self._set_condition("Ready", "False", "ApplyFailed", str(e))
            logger.warning("graph %s apply failed (%d consecutive): %s",
                           graph.name, self._failures, e)
            await self._write_status(graph)
            return False
        self._failures = 0
        self._retry_at = 0.0
        self._observed_generation = generation
        self._last_graph = graph
        self._set_condition("Degraded", "False", "ApplyOk")
        self._set_condition(
            "Progressing", "False", "ReconcileComplete",
            f"generation {generation} applied",
        )
        ready, detail = self._readiness(graph)
        self._set_condition(
            "Ready", "True" if ready else "False",
            "AllReplicasUp" if ready else "ReplicasPending", detail,
        )
        await self._write_status(graph)
        return True

    def _readiness(self, graph: GraphSpec):
        counts_fn = getattr(self.backend, "replica_counts", None)
        if counts_fn is None:
            # backend can't observe replicas (plain kubectl apply):
            # readiness is ownership of the applied spec
            return True, "spec applied (backend does not report replicas)"
        counts = counts_fn()
        missing = {
            s.name: (counts.get(s.name, 0), s.replicas)
            for s in graph.services
            if counts.get(s.name, 0) < s.replicas
        }
        if missing:
            return False, f"pending: {missing}"
        return True, f"{len(graph.services)} services at declared replicas"

    async def _write_status(self, graph: GraphSpec):
        patch = getattr(self.backend, "patch_status", None)
        if patch is None:
            return
        try:
            await patch(graph, self.status())
        except Exception as e:  # noqa: BLE001 — status writeback best-effort
            logger.warning("status writeback failed: %s", e)


class KubectlGraphBackend:
    """Apply the rendered Deployments with `kubectl apply` (idempotent:
    replica changes ride the same apply)."""

    def __init__(self, kubectl: str = "kubectl"):
        self.kubectl = kubectl

    async def apply(self, graph: GraphSpec) -> None:
        import json as _json

        manifest = _json.dumps(
            {"apiVersion": "v1", "kind": "List",
             "items": graph.render_deployments()}
        )
        proc = await asyncio.create_subprocess_exec(
            self.kubectl, "-n", graph.namespace, "apply", "-f", "-",
            stdin=asyncio.subprocess.PIPE,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        out, err = await proc.communicate(manifest.encode())
        if proc.returncode != 0:
            raise RuntimeError(
                f"kubectl apply failed rc={proc.returncode}: {err.decode()!r}"
            )
        logger.info("applied graph %s: %s", graph.name, out.decode().strip())

    async def patch_status(self, graph: GraphSpec, status: dict) -> None:
        """Write the controller status back onto the CR's status
        subresource (reference: controller-runtime Status().Update())."""
        import json as _json

        proc = await asyncio.create_subprocess_exec(
            self.kubectl, "-n", graph.namespace, "patch",
            f"dynamographdeployment/{graph.name}",
            "--type=merge", "--subresource=status",
            "-p", _json.dumps({"status": status}),
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        out, err = await proc.communicate()
        if proc.returncode != 0:
            raise RuntimeError(
                f"kubectl patch status failed rc={proc.returncode}: "
                f"{err.decode()!r}"
            )
