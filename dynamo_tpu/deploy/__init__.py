"""Deploy layer (reference deploy/cloud/operator, helm, recipes/):
operator-lite reconciler + k8s manifests + per-config recipes."""
