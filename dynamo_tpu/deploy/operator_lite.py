"""Operator-lite: the reconciler that makes planner decisions real.

The reference ships an 18k-LoC Go operator whose controller reconciles
DynamoGraphDeployment CRDs (deploy/cloud/operator/internal/controller/
dynamocomponentdeployment_controller.go); the SLA planner patches the CRD
and the controller scales worker Deployments. The TPU-build equivalent is
deliberately small and CRD-free:

  * the planner publishes {num_prefill_workers, num_decode_workers,
    revision} to the discovery KV (planner/connector.py VirtualConnector,
    key v1/planner/decision);
  * THIS process watches that key and reconciles the actual replica
    counts through a backend:
      - kubectl: `kubectl scale deployment/<name> --replicas=N`
        against the manifests in deploy/k8s/ (TPU slice pods);
      - local:   worker subprocesses on this host
        (planner/connector.py LocalProcessConnector — the e2e/test
        orchestrator).

Run: python -m dynamo_tpu.deploy.operator_lite --backend kubectl \
        --prefill-deployment dynamo-prefill --decode-deployment dynamo-decode

GRAPH MODE (--graph <manifest.yaml>): reconcile a whole
DynamoGraphDeployment CR (deploy/graph.py) instead of two fixed
deployment names — every declared service converges to its replica
count, and planner decisions overlay the prefill/decode roles
(reference CRD semantics, dynamographdeployment_types.go).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
from typing import Optional, Sequence

from dynamo_tpu.planner.connector import PLANNER_DECISION_KEY

logger = logging.getLogger("dynamo_tpu.operator_lite")


class KubectlScaler:
    """Scale k8s Deployments via kubectl (no python k8s client in the
    image; kubectl is the stable, auditable interface)."""

    def __init__(self, prefill_deployment: str, decode_deployment: str,
                 namespace: str = "default", kubectl: str = "kubectl",
                 frontend_deployment: Optional[str] = None):
        self.prefill_deployment = prefill_deployment
        self.decode_deployment = decode_deployment
        # frontend role (docs/frontend_scaleout.md): None = the planner's
        # num_frontends is ignored (frontend tier managed elsewhere)
        self.frontend_deployment = frontend_deployment
        self.namespace = namespace
        self.kubectl = kubectl

    async def _scale(self, deployment: str, replicas: int) -> None:
        cmd = [
            self.kubectl, "-n", self.namespace, "scale",
            f"deployment/{deployment}", f"--replicas={replicas}",
        ]
        proc = await asyncio.create_subprocess_exec(
            *cmd,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        out, err = await proc.communicate()
        if proc.returncode != 0:
            raise RuntimeError(
                f"kubectl scale failed rc={proc.returncode}: {err.decode()!r}"
            )
        logger.info("scaled %s to %d: %s", deployment, replicas,
                    out.decode().strip())

    async def set_replicas(self, prefill: int, decode: int,
                           frontend: Optional[int] = None) -> None:
        await self._scale(self.prefill_deployment, prefill)
        await self._scale(self.decode_deployment, decode)
        if frontend is not None and self.frontend_deployment:
            await self._scale(self.frontend_deployment, frontend)


def _parse_decision(raw) -> Optional[tuple]:
    """(revision, num_prefill, num_decode, num_frontends|None) from the
    planner's published decision, or None when absent/malformed."""
    if not raw:
        return None
    try:
        doc = json.loads(raw)
        frontends = doc.get("num_frontends")
        return (
            int(doc["revision"]),
            int(doc["num_prefill_workers"]),
            int(doc["num_decode_workers"]),
            int(frontends) if frontends is not None else None,
        )
    except (KeyError, ValueError, TypeError, json.JSONDecodeError):
        logger.warning("malformed planner decision: %r", raw[:200])
        return None


class _PollLoop:
    """Shared reconcile-forever loop: poll, survive errors, stoppable."""

    poll_s: float = 2.0

    def __init__(self):
        self._stop = asyncio.Event()

    async def reconcile_once(self) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError

    async def run(self) -> None:
        logger.info("%s watching %s", type(self).__name__, PLANNER_DECISION_KEY)
        while not self._stop.is_set():
            try:
                await self.reconcile_once()
            except Exception:  # noqa: BLE001 — a bad scale must not kill the loop
                logger.exception("reconcile failed; retrying")
            try:
                await asyncio.wait_for(self._stop.wait(), self.poll_s)
            except asyncio.TimeoutError:
                pass

    def stop(self) -> None:
        self._stop.set()


class GraphReconciler(_PollLoop):
    """Reconcile a DynamoGraphDeployment: converge every service, overlay
    the planner's prefill/decode decision (revision-gated like
    OperatorLite)."""

    def __init__(self, discovery_client, graph, backend, poll_s: float = 2.0):
        from dynamo_tpu.deploy.graph import GraphController

        super().__init__()
        self.client = discovery_client
        self.graph = graph
        self.backend = backend
        self.controller = GraphController(backend)
        self.poll_s = poll_s
        self.applied_revision: Optional[int] = None
        self._applied_base = False
        self._last_overlay = None  # (num_prefill, num_decode) last applied
        self.generation = 0  # bumps on every spec change (base or overlay)
        self.reconciles = 0

    def _overlaid(self, graph):
        if self._last_overlay is None:
            return graph
        return graph.with_planner_overlay(*self._last_overlay)

    def set_graph(self, graph) -> None:
        """Spec change (edited manifest): triggers a rollout on the next
        reconcile (the backend replaces replicas whose template changed)."""
        self.graph = graph
        self._applied_base = False  # dynolint: disable=race-guarded-state -- the one sanctioned external trigger: a sync one-shot flag flip the poll task picks up next pass

    async def reconcile_once(self) -> bool:
        raw = await self.client.get(PLANNER_DECISION_KEY) if self.client else None
        decision = _parse_decision(raw)
        fresh = decision is not None and (
            self.applied_revision is None or decision[0] > self.applied_revision
        )
        if self._applied_base and not fresh:
            if self.controller.needs_retry:
                # a previously failed apply retries once its backoff
                # expires, even with no new spec/decision (reconcile()
                # itself no-ops while the window is still open)
                return await self.controller.reconcile(
                    self._overlaid(self.graph), self.generation
                )
            return False
        target = self.graph
        if fresh:
            target = self.graph.with_planner_overlay(decision[1], decision[2])
            self._last_overlay = (decision[1], decision[2])
        else:
            # spec change (set_graph) with no NEW decision: the planner's
            # last applied replica counts remain the desired state — a
            # manifest edit must not scale the fleet back to base counts
            target = self._overlaid(target)
        self.generation += 1
        ok = await self.controller.reconcile(target, self.generation)
        if not ok:
            self.generation -= 1  # not observed; retry keeps the number
            return False
        if fresh:
            self.applied_revision = decision[0]
        self._applied_base = True
        self.reconciles += 1
        logger.info(
            "reconciled graph %s gen=%d (rev=%s): %s",
            target.name, self.generation,
            decision[0] if fresh else None,
            {s.name: s.replicas for s in target.services},
        )
        return True


class OperatorLite(_PollLoop):
    """Watch the planner's published decision; reconcile through a scaler
    (KubectlScaler or planner.connector.LocalProcessConnector)."""

    def __init__(self, discovery_client, scaler, poll_s: float = 2.0):
        super().__init__()
        self.client = discovery_client
        self.scaler = scaler
        self.poll_s = poll_s
        self.applied_revision: Optional[int] = None
        self.reconciles = 0

    async def reconcile_once(self) -> bool:
        """Apply the latest decision if its revision is new; returns True
        when a scale was performed."""
        decision = _parse_decision(await self.client.get(PLANNER_DECISION_KEY))
        if decision is None:
            return False
        rev, prefill, decode, frontend = decision
        if self.applied_revision is not None and rev <= self.applied_revision:
            return False
        if frontend is not None:
            await self.scaler.set_replicas(prefill, decode, frontend=frontend)
        else:
            # decisions without a frontend count keep working against
            # scalers that predate the role
            await self.scaler.set_replicas(prefill, decode)
        self.applied_revision = rev
        self.reconciles += 1
        logger.info("reconciled rev=%d -> prefill=%d decode=%d frontend=%s",
                    rev, prefill, decode, frontend)
        return True


def _build_local_scaler(args) -> "object":
    from dynamo_tpu.planner.connector import LocalProcessConnector

    base = [
        "python", "-m", "dynamo_tpu.jax_worker", "--model", args.model,
        "--discovery", args.discovery or "",
    ]
    return LocalProcessConnector(
        prefill_cmd=base + ["--role", "prefill"],
        decode_cmd=base + ["--role", "decode"],
    )


async def main(argv: Optional[Sequence[str]] = None) -> None:
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig, init_logging

    init_logging()
    ap = argparse.ArgumentParser(description="dynamo-tpu operator-lite")
    ap.add_argument("--backend", choices=["kubectl", "local"], default="kubectl")
    ap.add_argument("--discovery", default=None)
    ap.add_argument("--namespace", default=None,
                    help="k8s namespace (default: the graph manifest's "
                    "metadata.namespace in --graph mode, else 'default')")
    ap.add_argument("--prefill-deployment", default="dynamo-prefill")
    ap.add_argument("--decode-deployment", default="dynamo-decode")
    ap.add_argument("--frontend-deployment", default=None,
                    help="deployment scaled to the planner's num_frontends "
                    "(docs/frontend_scaleout.md); unset = frontend tier "
                    "not operator-managed")
    ap.add_argument("--model", default="llama3-8b", help="local backend model")
    ap.add_argument("--graph", default=None,
                    help="DynamoGraphDeployment manifest: reconcile the "
                    "whole graph (deploy/k8s/example-graphdeployment.yaml)")
    ap.add_argument("--poll-s", type=float, default=2.0)
    args = ap.parse_args(argv)

    cfg = RuntimeConfig.from_settings()
    if args.discovery:
        cfg.discovery_endpoint = args.discovery
    drt = await DistributedRuntime.create(cfg)
    if args.graph:
        import dataclasses

        import yaml

        from .graph import GraphSpec, KubectlGraphBackend, LocalGraphBackend

        with open(args.graph) as f:
            graph = GraphSpec.from_manifest(yaml.safe_load(f))
        if args.namespace:
            graph = dataclasses.replace(graph, namespace=args.namespace)
        backend = (
            KubectlGraphBackend() if args.backend == "kubectl"
            else LocalGraphBackend()
        )
        await GraphReconciler(
            drt.discovery, graph, backend, poll_s=args.poll_s
        ).run()
        return
    if args.backend == "kubectl":
        scaler = KubectlScaler(
            args.prefill_deployment, args.decode_deployment,
            args.namespace or "default",
            frontend_deployment=args.frontend_deployment,
        )
    else:
        scaler = _build_local_scaler(args)
    op = OperatorLite(drt.discovery, scaler, poll_s=args.poll_s)
    await op.run()


if __name__ == "__main__":
    asyncio.run(main())
