"""Pipeline parallelism: GPipe-style microbatch pipelining over the ``pp``
mesh axis.

The reference passes PP flags through to its engines (SURVEY.md §2.5 row
"Pipeline parallel (PP)" — delegated, engine_configs/); here it is native:
layer stages are sharded over ``pp`` (leading stage axis on the stacked
params), microbatches stream through under ``shard_map``, and activations
hop stage→stage via ``ppermute`` each tick. The whole schedule compiles to
one XLA while-loop; bubble overhead is (S-1)/(M+S-1) for S stages and M
microbatches.

``pipeline_apply`` is the generic scheduler: it takes a per-stage function
``stage_fn(stage_params, x) -> x`` and works for any pytree-of-stacked
params whose leaves carry a leading stage axis.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import PP_AXIS


def stack_stages(params_layers: Any, num_stages: int) -> Any:
    """Re-stack a layer-stacked param pytree [L, ...] into [S, L/S, ...] so
    axis 0 can be sharded over ``pp``."""

    def restack(x):
        L = x.shape[0]
        if L % num_stages:
            raise ValueError(f"{L} layers not divisible by {num_stages} stages")
        return x.reshape(num_stages, L // num_stages, *x.shape[1:])

    return jax.tree.map(restack, params_layers)


def _pipeline_local(
    stage_params: Any,  # leaves [1, L/S, ...] — this device's stage
    x_mb: jax.Array,  # [M, mb, ...] all microbatches (replicated)
    *,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    num_stages: int,
    axis_name: str,
) -> jax.Array:
    rank = jax.lax.axis_index(axis_name)
    local = jax.tree.map(lambda p: p[0], stage_params)
    M = x_mb.shape[0]
    mb_shape = x_mb.shape[1:]
    ticks = M + num_stages - 1
    fwd = [(i, i + 1) for i in range(num_stages - 1)]

    def tick(carry, t):
        recv, out_buf = carry
        # stage 0 feeds itself from the microbatch queue; others from the wire
        feed_idx = jnp.clip(t, 0, M - 1)
        feed = jax.lax.dynamic_index_in_dim(x_mb, feed_idx, 0, keepdims=False)
        cur = jnp.where(rank == 0, feed, recv)
        out = stage_fn(local, cur)
        # last stage owns microbatch t-(S-1) at tick t
        done_idx = jnp.clip(t - (num_stages - 1), 0, M - 1)
        take = (rank == num_stages - 1) & (t >= num_stages - 1)
        slot = jax.lax.dynamic_index_in_dim(out_buf, done_idx, 0, keepdims=False)
        out_buf = jax.lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(take, out, slot), done_idx, 0
        )
        # dynolint: disable=shard-collective-symmetry -- GPipe forward edge: the last stage deliberately sends to nobody (stage i -> i+1 only)
        recv = jax.lax.ppermute(out, axis_name, fwd) if fwd else out
        return (recv, out_buf), None

    recv0 = jnp.zeros(mb_shape, x_mb.dtype)
    out_buf0 = jnp.zeros((M, *mb_shape), x_mb.dtype)
    (recv, out_buf), _ = jax.lax.scan(
        tick, (recv0, out_buf0), jnp.arange(ticks)
    )
    # only the last stage's buffer is real; broadcast it around the ring so
    # the result is replicated over pp (one psum, off the per-tick path)
    mask = (rank == num_stages - 1).astype(out_buf.dtype)
    return jax.lax.psum(out_buf * mask, axis_name)


def _pipeline_local_stateful(
    stage_params: Any,  # leaves [1, L/S, ...]
    stage_state: Any,  # leaves [1, ...] — this device's mutable state (KV)
    x_mb: jax.Array,  # [M, mb, ...] microbatched hidden states (replicated)
    aux_mb: Any,  # pytree, leaves [M, ...] — per-microbatch metadata
    *,
    stage_fn,
    num_stages: int,
    axis_name: str,
):
    rank = jax.lax.axis_index(axis_name)
    local_p = jax.tree.map(lambda p: p[0], stage_params)
    local_s = jax.tree.map(lambda s: s[0], stage_state)
    M = x_mb.shape[0]
    mb_shape = x_mb.shape[1:]
    ticks = M + num_stages - 1
    fwd = [(i, i + 1) for i in range(num_stages - 1)]

    def tick(carry, t):
        recv, out_buf, st = carry
        feed_idx = jnp.clip(t, 0, M - 1)
        feed = jax.lax.dynamic_index_in_dim(x_mb, feed_idx, 0, keepdims=False)
        cur = jnp.where(rank == 0, feed, recv)
        # at tick t, stage `rank` holds microbatch t - rank (when in range);
        # out-of-range ticks compute with valid=False so state writes mask
        # to the scratch page
        mb_idx = jnp.clip(t - rank, 0, M - 1)
        aux = jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(a, mb_idx, 0, keepdims=False),
            aux_mb,
        )
        valid = (t >= rank) & (t - rank <= M - 1)
        out, st = stage_fn(local_p, st, cur, aux, valid)
        done_idx = jnp.clip(t - (num_stages - 1), 0, M - 1)
        take = (rank == num_stages - 1) & (t >= num_stages - 1)
        slot = jax.lax.dynamic_index_in_dim(out_buf, done_idx, 0, keepdims=False)
        out_buf = jax.lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(take, out, slot), done_idx, 0
        )
        # dynolint: disable=shard-collective-symmetry -- GPipe forward edge: the last stage deliberately sends to nobody (stage i -> i+1 only)
        recv = jax.lax.ppermute(out, axis_name, fwd) if fwd else out
        return (recv, out_buf, st), None

    recv0 = jnp.zeros(mb_shape, x_mb.dtype)
    out_buf0 = jnp.zeros((M, *mb_shape), x_mb.dtype)
    (recv, out_buf, local_s), _ = jax.lax.scan(
        tick, (recv0, out_buf0, local_s), jnp.arange(ticks)
    )
    mask = (rank == num_stages - 1).astype(out_buf.dtype)
    out = jax.lax.psum(out_buf * mask, axis_name)
    return out, jax.tree.map(lambda s: s[None], local_s)


def pipeline_apply_stateful(
    stage_params: Any,  # pytree, leaves [S, L/S, ...] (see stack_stages)
    stage_state: Any,  # pytree, leaves [S, ...] — per-stage KV, sharded pp
    x_mb: jax.Array,  # [M, mb, ...] microbatched hidden input
    aux_mb: Any,  # pytree, leaves [M, ...] — per-microbatch metadata (page
    # table rows, positions, seq lens — replicated)
    stage_fn: Callable,  # (local_params, local_state, x, aux, valid) ->
    # (x, local_state)
    mesh: Mesh,
    axis_name: str = PP_AXIS,
):
    """GPipe schedule that also threads PER-STAGE STATE through the ticks —
    the piece a paged-KV engine needs: each stage owns the KV pool of ITS
    layers (state sharded over pp), writes it as microbatches stream
    through, and the updated pool comes back out. Returns
    (out [M, mb, ...] replicated, new_stage_state [S, ...] pp-sharded).

    The reference only passes PP flags through to engines (SURVEY.md §2.5
    PP row); this is the native TPU schedule: one XLA while-loop,
    activations hop stage->stage via ppermute, bubble (S-1)/(M+S-1)."""
    num_stages = mesh.shape[axis_name]
    param_specs = jax.tree.map(
        lambda x: P(axis_name, *([None] * (x.ndim - 1))), stage_params
    )
    state_specs = jax.tree.map(
        lambda x: P(axis_name, *([None] * (x.ndim - 1))), stage_state
    )
    fn = jax.shard_map(
        partial(
            _pipeline_local_stateful,
            stage_fn=stage_fn,
            num_stages=num_stages,
            axis_name=axis_name,
        ),
        mesh=mesh,
        in_specs=(param_specs, state_specs, P(), P()),
        out_specs=(P(), state_specs),
        check_vma=False,
    )
    return fn(stage_params, stage_state, x_mb, aux_mb)


def pipeline_apply(
    stage_params: Any,  # pytree, leaves [S, L/S, ...] (see stack_stages)
    x_mb: jax.Array,  # [M, mb, ...] microbatched input
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: Mesh,
    axis_name: str = PP_AXIS,
) -> jax.Array:
    """Run M microbatches through S pipeline stages; returns [M, mb, ...]
    outputs (replicated over pp)."""
    num_stages = mesh.shape[axis_name]
    param_specs = jax.tree.map(
        lambda x: P(axis_name, *([None] * (x.ndim - 1))), stage_params
    )
    fn = jax.shard_map(
        partial(
            _pipeline_local,
            stage_fn=stage_fn,
            num_stages=num_stages,
            axis_name=axis_name,
        ),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )
    return fn(stage_params, x_mb)
