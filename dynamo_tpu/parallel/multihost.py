"""Multi-host SPMD support: leader-driven step replication.

The reference scales a worker across nodes through the engine's own
launcher (vLLM node orchestration, components/backends/vllm/src/dynamo/
vllm/main.py:64-296: node rank 0 registers the endpoint, other ranks join
the engine's distributed group). The TPU-native equivalent (SURVEY.md §7
hard part (d)):

  * every host of a slice runs the SAME process image and calls
    `jax.distributed.initialize` — jax sees one global device set, and
    every jitted program over mesh-sharded arrays must be entered by ALL
    hosts in the SAME order (SPMD).
  * ONLY host 0 talks to the control plane: discovery registration,
    request endpoint, KV events, metrics (per-host KV-event ownership =
    host 0).
  * host 0 runs the real engine scheduler; every device dispatch it makes
    is first broadcast as a compact STEP DESCRIPTOR (tag + numpy args)
    over a TCP fan-out; follower hosts replay the identical dispatch
    sequence against their engine replica. Host-side scheduling stays in
    exactly one place, so there is no cross-host nondeterminism to keep
    in lockstep — the only contract is "followers apply descriptors in
    order", which a single TCP stream per follower gives for free.

Tested without TPU hardware by a 2-process CPU run (gloo collectives):
tests/test_multihost.py.
"""

from __future__ import annotations

import asyncio
import logging
import struct
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import msgpack
import numpy as np

logger = logging.getLogger(__name__)

_MAGIC = 0xD7A0517E


@dataclass
class MultihostInfo:
    process_index: int
    num_processes: int

    @property
    def is_primary(self) -> bool:
        return self.process_index == 0


def init_multihost(
    coordinator: str,
    num_processes: int,
    process_id: int,
    local_device_ids: Optional[List[int]] = None,
) -> MultihostInfo:
    """`jax.distributed.initialize` wrapper (idempotent for tests)."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    return MultihostInfo(process_index=process_id, num_processes=num_processes)


def _pack_step(tag: str, arrays: Dict[str, np.ndarray]) -> bytes:
    payload = {
        "tag": tag,
        "arrays": {
            k: {
                "dtype": str(v.dtype),
                "shape": list(v.shape),
                "data": np.ascontiguousarray(v).tobytes(),
            }
            for k, v in arrays.items()
        },
    }
    body = msgpack.packb(payload, use_bin_type=True)
    return struct.pack("<II", _MAGIC, len(body)) + body


def _unpack_step(body: bytes) -> Tuple[str, Dict[str, np.ndarray]]:
    payload = msgpack.unpackb(body, raw=False)
    arrays = {
        k: np.frombuffer(v["data"], dtype=np.dtype(v["dtype"])).reshape(v["shape"])
        for k, v in payload["arrays"].items()
    }
    return payload["tag"], arrays


ACK_EVERY = 64  # follower acks every N frames
MAX_BUFFER = 256 << 20  # per-follower write buffer cap before declaring death
MAX_LAG = 4096  # frames a live follower may trail before declaring death


@dataclass
class _Follower:
    host_id: int
    data_plane_addr: str
    writer: asyncio.StreamWriter
    acked: int = 0  # highest frame seq the follower confirmed


class StepBroadcaster:
    """Host-0 side: accepts follower connections, fans out step descriptors
    in dispatch order. `wait_for_followers` gates serving until the whole
    slice is connected.

    Hardening (round-2 weak #5): each follower sends a HELLO frame (host id
    + its KV data plane address — the per-host shard rendezvous) and then
    ACKs every ACK_EVERY frames on the same socket. A follower whose socket
    resets, whose write buffer exceeds MAX_BUFFER, or whose ack lag exceeds
    MAX_LAG is declared dead: `on_follower_lost` fires so the engine can
    fail in-flight work instead of wedging inside the next collective."""

    def __init__(self, host: str, port: int, expected_followers: int,
                 on_follower_lost=None):
        self.host = host
        self.port = port
        self.expected = expected_followers
        self._on_follower_lost = on_follower_lost
        self._lost_pending: List[tuple] = []  # losses before a callback exists
        self._followers: List[_Follower] = []
        self._server: Optional[asyncio.AbstractServer] = None
        self._connected = asyncio.Event()
        self._seq = 0
        self._reader_tasks: List[asyncio.Task] = []
        if expected_followers == 0:
            self._connected.set()

    @property
    def on_follower_lost(self):
        return self._on_follower_lost

    @on_follower_lost.setter
    def on_follower_lost(self, cb):
        """Losses during startup (between HELLO and the engine wiring the
        callback) must not vanish: they are queued and replayed here —
        otherwise the leader's first collective wedges with the watchdog
        never armed."""
        self._on_follower_lost = cb
        if cb is not None:
            pending, self._lost_pending = self._lost_pending, []
            for host_id, why in pending:
                try:
                    cb(host_id, why)
                except Exception:  # noqa: BLE001
                    logger.exception("on_follower_lost callback failed")

    @property
    def follower_data_planes(self) -> Dict[int, str]:
        """host_id -> advertised KV data plane address (from hello)."""
        return {f.host_id: f.data_plane_addr for f in self._followers}

    async def start(self):
        self._server = await asyncio.start_server(
            self._on_connect, self.host, self.port
        )

    async def _on_connect(self, reader, writer):
        try:
            header = await asyncio.wait_for(reader.readexactly(8), 30.0)
            magic, length = struct.unpack("<II", header)
            if magic != _MAGIC or length > 4096:
                raise RuntimeError("bad hello frame")
            hello = msgpack.unpackb(await reader.readexactly(length), raw=False)
        except Exception:  # noqa: BLE001 — a garbage peer must not wedge startup
            logger.warning("rejecting malformed follower hello", exc_info=True)
            writer.close()
            return
        f = _Follower(
            host_id=int(hello.get("host_id", len(self._followers) + 1)),
            data_plane_addr=str(hello.get("data_plane_addr", "")),
            writer=writer,
        )
        self._followers.append(f)
        self._reader_tasks.append(asyncio.create_task(self._read_acks(f, reader)))
        logger.info(
            "follower host %d connected (%d/%d), data plane %s",
            f.host_id, len(self._followers), self.expected, f.data_plane_addr or "-",
        )
        if len(self._followers) >= self.expected:
            self._connected.set()

    async def _read_acks(self, f: _Follower, reader: asyncio.StreamReader):
        try:
            while True:
                header = await reader.readexactly(8)
                magic, length = struct.unpack("<II", header)
                if magic != _MAGIC:
                    raise RuntimeError("bad ack frame")
                body = msgpack.unpackb(await reader.readexactly(length), raw=False)
                f.acked = int(body.get("seq", f.acked))
        except (asyncio.IncompleteReadError, ConnectionError, RuntimeError) as e:
            self._lose(f, f"step stream closed ({type(e).__name__})")
        # cancellation (leader close()) propagates: the task must record
        # itself cancelled, not finished, so drain accounting stays honest

    def _lose(self, f: _Follower, why: str):
        if f not in self._followers:
            return
        self._followers.remove(f)
        logger.error("follower host %d lost: %s", f.host_id, why)
        f.writer.close()
        if self._on_follower_lost is not None:
            try:
                self._on_follower_lost(f.host_id, why)
            except Exception:  # noqa: BLE001
                logger.exception("on_follower_lost callback failed")
        else:
            self._lost_pending.append((f.host_id, why))

    async def wait_for_followers(self, timeout: float = 120.0):
        await asyncio.wait_for(self._connected.wait(), timeout)

    def send(self, tag: str, arrays: Dict[str, np.ndarray]):
        """Non-blocking ordered fan-out (called before the local dispatch).
        Backpressure is fail-fast: a follower too far behind is dead weight
        that will wedge the next collective anyway — cut it loose early."""
        if not self._followers:
            return
        self._seq += 1
        frame = _pack_step(tag, arrays)
        for f in list(self._followers):
            w = f.writer
            if w.is_closing():
                self._lose(f, "writer closed")
                continue
            if w.transport.get_write_buffer_size() > MAX_BUFFER:
                self._lose(f, "write buffer overflow (slow consumer)")
                continue
            if self._seq - f.acked > MAX_LAG:
                self._lose(f, f"ack lag {self._seq - f.acked} frames")
                continue
            w.write(frame)

    async def drain(self):
        # snapshot: a slow follower's drain() suspends, and _lose/_on_connect
        # mutate the follower list from other tasks mid-iteration
        for f in list(self._followers):
            if not f.writer.is_closing():
                await f.writer.drain()

    async def close(self):
        self.send("stop", {})
        await self.drain()
        for t in self._reader_tasks:
            t.cancel()
        for f in self._followers:
            f.writer.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


class StepReceiver:
    """Follower side: ordered step descriptor stream from host 0. Sends a
    hello (host id + local KV data plane address) at connect and acks every
    ACK_EVERY frames so the leader can detect death/lag."""

    def __init__(self, host: str, port: int, host_id: int = -1,
                 data_plane_addr: str = ""):
        self.host = host
        self.port = port
        self.host_id = host_id
        self.data_plane_addr = data_plane_addr
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._recved = 0

    async def connect(self, retries: int = 60, delay: float = 0.5):
        for attempt in range(retries):
            try:
                self._reader, self._writer = await asyncio.open_connection(
                    self.host, self.port
                )
                break
            except OSError:
                if attempt == retries - 1:
                    raise
                await asyncio.sleep(delay)
        hello = msgpack.packb(
            {"host_id": self.host_id, "data_plane_addr": self.data_plane_addr},
            use_bin_type=True,
        )
        self._writer.write(struct.pack("<II", _MAGIC, len(hello)) + hello)
        await self._writer.drain()

    async def recv(self) -> Tuple[str, Dict[str, np.ndarray]]:
        header = await self._reader.readexactly(8)
        magic, length = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise RuntimeError(f"bad step frame magic {magic:#x}")
        body = await self._reader.readexactly(length)
        self._recved += 1
        if self._recved % ACK_EVERY == 0 and not self._writer.is_closing():
            ack = msgpack.packb({"seq": self._recved}, use_bin_type=True)
            self._writer.write(struct.pack("<II", _MAGIC, len(ack)) + ack)
        return _unpack_step(body)

    async def close(self):
        if self._writer is not None:
            self._writer.close()
