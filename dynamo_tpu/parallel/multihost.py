"""Multi-host SPMD support: leader-driven step replication.

The reference scales a worker across nodes through the engine's own
launcher (vLLM node orchestration, components/backends/vllm/src/dynamo/
vllm/main.py:64-296: node rank 0 registers the endpoint, other ranks join
the engine's distributed group). The TPU-native equivalent (SURVEY.md §7
hard part (d)):

  * every host of a slice runs the SAME process image and calls
    `jax.distributed.initialize` — jax sees one global device set, and
    every jitted program over mesh-sharded arrays must be entered by ALL
    hosts in the SAME order (SPMD).
  * ONLY host 0 talks to the control plane: discovery registration,
    request endpoint, KV events, metrics (per-host KV-event ownership =
    host 0).
  * host 0 runs the real engine scheduler; every device dispatch it makes
    is first broadcast as a compact STEP DESCRIPTOR (tag + numpy args)
    over a TCP fan-out; follower hosts replay the identical dispatch
    sequence against their engine replica. Host-side scheduling stays in
    exactly one place, so there is no cross-host nondeterminism to keep
    in lockstep — the only contract is "followers apply descriptors in
    order", which a single TCP stream per follower gives for free.

Tested without TPU hardware by a 2-process CPU run (gloo collectives):
tests/test_multihost.py.
"""

from __future__ import annotations

import asyncio
import logging
import struct
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import msgpack
import numpy as np

logger = logging.getLogger(__name__)

_MAGIC = 0xD7A0517E


@dataclass
class MultihostInfo:
    process_index: int
    num_processes: int

    @property
    def is_primary(self) -> bool:
        return self.process_index == 0


def init_multihost(
    coordinator: str,
    num_processes: int,
    process_id: int,
    local_device_ids: Optional[List[int]] = None,
) -> MultihostInfo:
    """`jax.distributed.initialize` wrapper (idempotent for tests)."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    return MultihostInfo(process_index=process_id, num_processes=num_processes)


def _pack_step(tag: str, arrays: Dict[str, np.ndarray]) -> bytes:
    payload = {
        "tag": tag,
        "arrays": {
            k: {
                "dtype": str(v.dtype),
                "shape": list(v.shape),
                "data": np.ascontiguousarray(v).tobytes(),
            }
            for k, v in arrays.items()
        },
    }
    body = msgpack.packb(payload, use_bin_type=True)
    return struct.pack("<II", _MAGIC, len(body)) + body


def _unpack_step(body: bytes) -> Tuple[str, Dict[str, np.ndarray]]:
    payload = msgpack.unpackb(body, raw=False)
    arrays = {
        k: np.frombuffer(v["data"], dtype=np.dtype(v["dtype"])).reshape(v["shape"])
        for k, v in payload["arrays"].items()
    }
    return payload["tag"], arrays


class StepBroadcaster:
    """Host-0 side: accepts follower connections, fans out step descriptors
    in dispatch order. `wait_for_followers` gates serving until the whole
    slice is connected."""

    def __init__(self, host: str, port: int, expected_followers: int):
        self.host = host
        self.port = port
        self.expected = expected_followers
        self._writers: List[asyncio.StreamWriter] = []
        self._server: Optional[asyncio.AbstractServer] = None
        self._connected = asyncio.Event()
        if expected_followers == 0:
            self._connected.set()

    async def start(self):
        self._server = await asyncio.start_server(
            self._on_connect, self.host, self.port
        )

    async def _on_connect(self, reader, writer):
        self._writers.append(writer)
        logger.info(
            "follower connected (%d/%d)", len(self._writers), self.expected
        )
        if len(self._writers) >= self.expected:
            self._connected.set()

    async def wait_for_followers(self, timeout: float = 120.0):
        await asyncio.wait_for(self._connected.wait(), timeout)

    def send(self, tag: str, arrays: Dict[str, np.ndarray]):
        """Non-blocking ordered fan-out (called before the local dispatch)."""
        if not self._writers:
            return
        frame = _pack_step(tag, arrays)
        for w in self._writers:
            if not w.is_closing():
                w.write(frame)

    async def drain(self):
        for w in self._writers:
            if not w.is_closing():
                await w.drain()

    async def close(self):
        self.send("stop", {})
        await self.drain()
        for w in self._writers:
            w.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


class StepReceiver:
    """Follower side: ordered step descriptor stream from host 0."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None

    async def connect(self, retries: int = 60, delay: float = 0.5):
        for attempt in range(retries):
            try:
                self._reader, self._writer = await asyncio.open_connection(
                    self.host, self.port
                )
                return
            except OSError:
                if attempt == retries - 1:
                    raise
                await asyncio.sleep(delay)

    async def recv(self) -> Tuple[str, Dict[str, np.ndarray]]:
        header = await self._reader.readexactly(8)
        magic, length = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise RuntimeError(f"bad step frame magic {magic:#x}")
        body = await self._reader.readexactly(length)
        return _unpack_step(body)

    async def close(self):
        if self._writer is not None:
            self._writer.close()
