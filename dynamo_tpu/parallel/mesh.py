"""Device mesh construction + named shardings for the engine.

The TPU-native replacement for the reference's engine-delegated TP/PP/EP
flags (SURVEY.md §2.5): a `jax.sharding.Mesh` with axes

    dp — data parallel (replica) axis
    tp — tensor parallel axis (attention heads / MLP hidden / vocab)
    ep — expert parallel axis for MoE (aliases tp by default)

Params and KV cache carry NamedShardings; jit'd steps run under GSPMD and
XLA inserts all-reduces over ICI (scaling-book recipe). No manual
collectives on the inference path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# --------------------------------------------------------------------- #
# Mesh-axis registry — the single source of truth for axis names.
#
# Every collective (`psum`/`ppermute`/`all_gather`/`axis_index`), every
# `PartitionSpec`, and every `mesh.shape[...]` lookup in the package must
# reference one of these names; the `shard-axis-registry` dynolint rule
# (dynamo_tpu/analysis/shard/) resolves axis arguments through call chains
# and fails CI on anything not registered here. Modules import the
# constants instead of repeating the string literals, so a typo is an
# ImportError rather than a silent wrong-axis collective.
# --------------------------------------------------------------------- #

DP_AXIS = "dp"
PP_AXIS = "pp"
SP_AXIS = "sp"
EP_AXIS = "ep"
TP_AXIS = "tp"

#: axis name -> role. Parsed (as AST, never imported) by the shard
#: analysis pack; keep values one-line human-readable.
KNOWN_AXES = {
    DP_AXIS: "data-parallel replica axis",
    PP_AXIS: "pipeline-stage axis (layers sharded across stages)",
    SP_AXIS: "sequence-parallel (ring-attention) axis",
    EP_AXIS: "expert-parallel axis for MoE dispatch",
    TP_AXIS: "tensor-parallel axis (heads / MLP hidden / vocab)",
}

#: outer→inner device-grid order; tp innermost so its all-reduces ride
#: the fastest ICI dimension (scaling-book layout recipe)
MESH_AXIS_ORDER = (DP_AXIS, PP_AXIS, SP_AXIS, EP_AXIS, TP_AXIS)


@dataclass(frozen=True)
class ParallelConfig:
    """Mesh axis sizes. Axis order (outer→inner) is dp, pp, sp, ep, tp —
    tp innermost so its all-reduces ride the fastest ICI dimension
    (scaling-book layout recipe)."""

    tp_size: int = 1
    dp_size: int = 1
    pp_size: int = 1  # pipeline stages
    sp_size: int = 1  # sequence (ring-attention) axis
    ep_size: int = 1  # expert axis for MoE

    @property
    def world(self) -> int:
        return self.tp_size * self.dp_size * self.pp_size * self.sp_size * self.ep_size


def build_mesh(parallel: ParallelConfig, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    n = parallel.world
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    p = parallel
    grid = np.asarray(devices[:n]).reshape(
        p.dp_size, p.pp_size, p.sp_size, p.ep_size, p.tp_size
    )
    return Mesh(grid, axis_names=MESH_AXIS_ORDER)


@dataclass(frozen=True)
class LlamaShardings:
    """PartitionSpecs for the llama param tree + KV cache + activations.

    Megatron-style TP: column-parallel wq/wk/wv/w_gate/w_up (output dim over
    tp), row-parallel wo/w_down (input dim over tp) — one all-reduce per
    block, inserted by XLA from these specs.
    """

    mesh: Mesh

    @property
    def _pp(self):
        """Layer axis: sharded over pp when pipeline stages are configured
        (parallel/pipeline.py reshapes [L, ...] -> [S, L/S, ...] in-program;
        a leading-'pp' layout on L is the same placement)."""
        return PP_AXIS if self.mesh.shape.get(PP_AXIS, 1) > 1 else None

    def param_specs(self) -> dict:
        pp = self._pp
        return {
            "embed": P(None, TP_AXIS),  # hidden sharded
            "layers": {
                "attn_norm": P(pp),
                "wq": P(pp, None, TP_AXIS),  # [L, H, q_dim/tp]
                "wk": P(pp, None, TP_AXIS),
                "wv": P(pp, None, TP_AXIS),
                "wo": P(pp, TP_AXIS, None),  # row-parallel
                "mlp_norm": P(pp),
                "w_gate": P(pp, None, TP_AXIS),
                "w_up": P(pp, None, TP_AXIS),
                "w_down": P(pp, TP_AXIS, None),
            },
            "final_norm": P(None),
            "lm_head": P(None, TP_AXIS),  # vocab sharded on output
        }

    def param_shardings(self) -> dict:
        return jax.tree.map(
            lambda spec: NamedSharding(self.mesh, spec),
            self.param_specs(),
            is_leaf=lambda x: isinstance(x, P),
        )

    def kv_sharding(self) -> NamedSharding:
        # [layers, pages, page_size, kv_heads, head_dim]: kv heads over tp;
        # layers over pp when pipelining (each stage owns its layers' pool)
        return NamedSharding(self.mesh, P(self._pp, None, None, TP_AXIS, None))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


@dataclass(frozen=True)
class MoeShardings(LlamaShardings):
    """LlamaShardings with the MLP rows replaced by expert weights sharded
    over the ``ep`` axis (wide-EP, SURVEY.md §2.5 row "Expert parallel");
    models/moe.py constrains the dispatched [E, C, H] token tensor to
    P("ep") so GSPMD inserts the all-to-all over ICI."""

    def param_specs(self) -> dict:
        specs = super().param_specs()
        pp = self._pp
        layers = dict(specs["layers"])
        layers.update(
            {
                "router": P(pp, None, None),  # [L, H, E]
                "w_gate": P(pp, EP_AXIS, None, TP_AXIS),  # [L, E, H, I/tp]
                "w_up": P(pp, EP_AXIS, None, TP_AXIS),
                "w_down": P(pp, EP_AXIS, TP_AXIS, None),
            }
        )
        specs["layers"] = layers
        return specs


@dataclass(frozen=True)
class DpAttentionShardings(MoeShardings):
    """DeepSeek-style wide-EP serving layout (reference recipe:
    recipes/deepseek-r1/sglang-wideep/tep16p-dep16d-disagg.yaml
    `--enable-dp-attention --ep-size 16`): experts are ep-sharded as in
    MoeShardings, but the KV cache is DATA-parallel over the ep axis — the
    page pool is sharded over ``ep`` so attention state is partitioned
    across the expert group instead of replicated on every rank (the KV
    memory blow-up dp-attention exists to avoid). GSPMD partitions the
    page gathers/writes across the ep group from this one spec; expert
    dispatch keeps its all-to-all over the same axis."""

    def kv_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(self._pp, EP_AXIS, None, TP_AXIS, None))


def shard_params(params: dict, shardings) -> dict:
    """Place a param pytree onto the mesh (works for freshly-initialized,
    loaded, or int8-quantized params — a quantized leaf's scale gets the
    leaf's sharding with singleton axes unsharded)."""
    from ..models.quant import is_quant, scale_sharding

    shard_tree = shardings.param_shardings()

    def place(x, s):
        if x is None:
            return None
        if is_quant(x):
            return {
                "q": jax.device_put(x["q"], s),
                "s": jax.device_put(x["s"], scale_sharding(s, x["s"].shape)),
            }
        return jax.device_put(x, s)

    return jax.tree.map(
        place, params, shard_tree, is_leaf=lambda x: x is None or is_quant(x)
    )
