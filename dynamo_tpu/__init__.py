"""dynamo-tpu: a TPU-native distributed LLM inference serving framework.

A ground-up rebuild of the capabilities of NVIDIA Dynamo (reference:
/root/reference) designed for TPU hardware: the compute path is JAX/XLA/Pallas
over `jax.sharding.Mesh`, the serving runtime is asyncio + a built-in TCP
control/request plane, and KV movement rides XLA collectives / host DMA
instead of NIXL.

Layer map (mirrors reference SURVEY.md §1):
  runtime/   — distributed runtime: discovery, component model, request plane
  llm/       — serving pipeline: protocols, preprocessor, HTTP frontend,
               KV router, block manager, mocker engine
  engine/    — the JAX inference engine: continuous batching, paged KV
  models/    — model zoo (functional JAX, param pytrees)
  ops/       — Pallas TPU kernels. Kernel map (each with an XLA reference
               fallback + the shared `_pallas_eligible` dispatch gate in
               ops/paged_attention.py):
                 pallas_paged_attention.py   — decode (T=1) flash over paged
                                               KV, + fused pool+local variant
                 pallas_prefill_attention.py — batched chunked-prefill flash
                 pallas_ragged_attention.py  — ragged UNIFIED mixed
                                               prefill+decode (one flat
                                               buffer, one dispatch;
                                               docs/ragged_attention.md)
                 ring_attention.py           — sequence-parallel ring prefill
  parallel/  — mesh construction, shardings (tp/dp/pp/ep/sp)
  planner/   — SLA planner: load prediction, perf interpolation, autoscale
  frontend/  — `python -m dynamo_tpu.frontend` OpenAI entrypoint
"""

__version__ = "0.1.0"
