"""Guided decoding: structured output compiled to token-level FSMs.

Covers the reference's guided-decoding request surface — OpenAI
`response_format` (json_object / json_schema) plus the nvext extension
fields `guided_choice` / `guided_regex` / `guided_json`
(reference lib/llm/src/protocols/openai/nvext.rs:73-88). The reference
delegates enforcement to its engines (vLLM / TRT-LLM run xgrammar on the
GPU worker); here the native JAX engine owns it:

  host side   regex / JSON-schema  →  char DFA  →  token-level mask,
              one FSM state per request lane, advanced as tokens are
              emitted;
  device side the per-lane vocab bitmask rides the guided decode /
              prefill dispatch variants and is applied to the logits
              inside the jitted sampler (ops stay on the MXU; no logits
              transfer to host).

The mask for step t+1 depends on the token emitted at step t, so guided
lanes force the engine into single-step, non-pipelined decode dispatches
while any guided request is in flight (engine/engine.py _dispatch_decode).
Throughput of concurrent unguided traffic degrades for that window; this
is the documented trade for airtight constraint enforcement.

JSON-schema support is the practical subset (type string/integer/number/
boolean/null, const, enum, object properties — all treated as required,
in declaration order — arrays with bounded item counts, bounded nesting
depth). `json_object` mode accepts any JSON value to a bounded depth.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

# --------------------------------------------------------------------- #
# regex AST + parser (the subset the schema compiler emits, plus user
# guided_regex patterns: literals, escapes, classes, quantifiers,
# groups, alternation; fullmatch semantics, no anchors/backrefs)
# --------------------------------------------------------------------- #

_DIGITS = frozenset("0123456789")
_WORD = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"
)
_SPACE = frozenset(" \t\n\r\f\v")

_META = set(r"\.[](){}*+?|^$")
MAX_REPEAT = 4096  # cap on {m,n} expansion (DoS guard; see _repeat)


@dataclass(frozen=True)
class CharSet:
    """A character class: `negated=False` matches chars ∈ `chars`;
    `negated=True` matches chars ∉ `chars` (the dot is `chars={'\\n'},
    negated=True`)."""

    chars: FrozenSet[str]
    negated: bool = False

    def matches(self, ch: str) -> bool:
        return (ch in self.chars) != self.negated


def _esc_literal(text: str) -> str:
    """Escape regex metacharacters so `text` matches itself."""
    return "".join("\\" + c if c in _META else c for c in text)


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def next(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            raise ValueError(f"unexpected {self.p[self.i]!r} at {self.i}")
        return node

    def _alt(self):
        branches = [self._concat()]
        while self.peek() == "|":
            self.next()
            branches.append(self._concat())
        return ("alt", branches) if len(branches) > 1 else branches[0]

    def _concat(self):
        items = []
        while self.peek() is not None and self.peek() not in "|)":
            items.append(self._repeat())
        if not items:
            return ("cat", [])  # empty branch (matches "")
        return ("cat", items) if len(items) > 1 else items[0]

    def _repeat(self):
        node = self._atom()
        ch = self.peek()
        if ch == "*":
            self.next()
            return ("star", node)
        if ch == "+":
            self.next()
            return ("cat", [node, ("star", node)])
        if ch == "?":
            self.next()
            return ("alt", [node, ("cat", [])])
        if ch == "{":
            save = self.i
            self.next()
            spec = ""
            while self.peek() is not None and self.peek() != "}":
                spec += self.next()
            if self.peek() != "}" or not spec or not spec.replace(",", "").isdigit():
                # not a quantifier (e.g. a literal '{' in a schema string
                # would have been escaped; treat malformed as error)
                self.i = save
                raise ValueError(f"bad quantifier at {save} in {self.p!r}")
            self.next()
            if "," in spec:
                lo_s, hi_s = spec.split(",", 1)
                lo = int(lo_s)
                hi = int(hi_s) if hi_s else None
            else:
                lo = hi = int(spec)
            if lo > MAX_REPEAT or (hi or 0) > MAX_REPEAT:
                # quantifiers expand to lo+hi AST nodes BEFORE the DFA
                # max_states guard can fire: a {0,300000} would pin the
                # compile thread / OOM long before subset construction
                raise ValueError(
                    f"repetition bound exceeds {MAX_REPEAT}"
                )
            parts: list = [node] * lo
            if hi is None:
                parts.append(("star", node))
            else:
                if hi < lo:
                    raise ValueError(f"bad range {{{spec}}}")
                opt = ("alt", [node, ("cat", [])])
                parts.extend([opt] * (hi - lo))
            return ("cat", parts)
        return node

    def _atom(self):
        ch = self.next()
        if ch == "(":
            if self.peek() == "?":  # (?: non-capturing — same thing here
                self.next()
                if self.peek() == ":":
                    self.next()
                else:
                    raise ValueError("only (?: groups supported")
            node = self._alt()
            if self.peek() != ")":
                raise ValueError("unbalanced (")
            self.next()
            return node
        if ch == "[":
            return ("lit", self._char_class())
        if ch == ".":
            return ("lit", CharSet(frozenset("\n"), negated=True))
        if ch == "\\":
            return ("lit", self._escape(self.next()))
        if ch in _META:
            raise ValueError(f"unexpected {ch!r} at {self.i - 1}")
        return ("lit", CharSet(frozenset(ch)))

    def _escape(self, ch: str) -> CharSet:
        table = {
            "d": CharSet(_DIGITS),
            "D": CharSet(_DIGITS, negated=True),
            "w": CharSet(_WORD),
            "W": CharSet(_WORD, negated=True),
            "s": CharSet(_SPACE),
            "S": CharSet(_SPACE, negated=True),
            "n": CharSet(frozenset("\n")),
            "t": CharSet(frozenset("\t")),
            "r": CharSet(frozenset("\r")),
        }
        if ch in table:
            return table[ch]
        if ch == "x":  # \xNN hex escape (schema compiler: control chars)
            hx = self.next() + self.next()
            return CharSet(frozenset(chr(int(hx, 16))))
        return CharSet(frozenset(ch))  # \. \\ \[ etc: the literal char

    def _char_class(self) -> CharSet:
        negated = False
        if self.peek() == "^":
            self.next()
            negated = True
        chars: set = set()
        prev: Optional[str] = None
        while True:
            ch = self.peek()
            if ch is None:
                raise ValueError("unbalanced [")
            if ch == "]":
                self.next()
                break
            self.next()
            if ch == "\\":
                sub = self._escape(self.next())
                if sub.negated:
                    raise ValueError("negated escape inside class unsupported")
                chars |= sub.chars
                # single-char escapes (\xNN, \-, \]) can anchor a range
                prev = next(iter(sub.chars)) if len(sub.chars) == 1 else None
                continue
            if ch == "-" and prev is not None and self.peek() not in (None, "]"):
                end = self.next()
                if end == "\\":
                    endset = self._escape(self.next())
                    if len(endset.chars) != 1:
                        raise ValueError("bad range end in class")
                    end = next(iter(endset.chars))
                for o in range(ord(prev), ord(end) + 1):
                    chars.add(chr(o))
                prev = None
                continue
            chars.add(ch)
            prev = ch
        return CharSet(frozenset(chars), negated=negated)


# --------------------------------------------------------------------- #
# NFA (Thompson) → DFA (subset construction)
# --------------------------------------------------------------------- #


class _Nfa:
    def __init__(self):
        self.eps: List[List[int]] = []
        self.edges: List[List[Tuple[CharSet, int]]] = []

    def state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    def build(self, node, start: int) -> int:
        """Wire `node` from `start`, return its accepting state."""
        kind = node[0]
        if kind == "lit":
            end = self.state()
            self.edges[start].append((node[1], end))
            return end
        if kind == "cat":
            cur = start
            for child in node[1]:
                cur = self.build(child, cur)
            return cur
        if kind == "alt":
            end = self.state()
            for child in node[1]:
                mid = self.state()
                self.eps[start].append(mid)
                sub_end = self.build(child, mid)
                self.eps[sub_end].append(end)
            return end
        if kind == "star":
            loop = self.state()
            end = self.state()
            self.eps[start].append(loop)
            self.eps[start].append(end)
            sub_end = self.build(node[1], loop)
            self.eps[sub_end].append(loop)
            self.eps[sub_end].append(end)
            return end
        raise AssertionError(kind)


@dataclass
class Dfa:
    """Char-level DFA. `trans[s]` holds targets for explicit-alphabet chars
    (absent ⇒ dead); chars outside `sigma` route via `other[s]` (-1 =
    dead) — that's how negated classes/dot admit the unbounded rest of
    unicode without enumerating it."""

    trans: List[Dict[str, int]]
    other: List[int]
    accept: List[bool]
    sigma: FrozenSet[str]

    def step(self, state: int, ch: str) -> int:
        if state < 0:
            return -1
        t = self.trans[state]
        if ch in t:
            return t[ch]
        if ch in self.sigma:
            return -1
        return self.other[state]

    def walk(self, state: int, text: str) -> int:
        for ch in text:
            state = self.step(state, ch)
            if state < 0:
                return -1
        return state

    def fullmatch(self, text: str) -> bool:
        s = self.walk(0, text)
        return s >= 0 and self.accept[s]


def compile_regex(pattern: str, max_states: int = 20000) -> Dfa:
    ast = _Parser(pattern).parse()
    nfa = _Nfa()
    start = nfa.state()
    end = nfa.build(ast, start)

    def eclose(states: FrozenSet[int]) -> FrozenSet[int]:
        seen = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for t in nfa.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    sigma = set()
    for edges in nfa.edges:
        for cs, _ in edges:
            sigma |= cs.chars
    sigma = frozenset(sigma)

    start_set = eclose(frozenset([start]))
    ids: Dict[FrozenSet[int], int] = {start_set: 0}
    order = [start_set]
    trans: List[Dict[str, int]] = []
    other: List[int] = []
    accept: List[bool] = []
    i = 0
    while i < len(order):
        cur = order[i]
        i += 1
        row: Dict[str, int] = {}
        # explicit chars
        for ch in sigma:
            nxt = set()
            for s in cur:
                for cs, t in nfa.edges[s]:
                    if cs.matches(ch):
                        nxt.add(t)
            if nxt:
                tgt = eclose(frozenset(nxt))
                if tgt not in ids:
                    ids[tgt] = len(order)
                    order.append(tgt)
                    if len(order) > max_states:
                        raise ValueError("pattern too complex (DFA blowup)")
                row[ch] = ids[tgt]
        # the OTHER symbol: any char ∉ sigma (matches only negated sets)
        nxt = set()
        for s in cur:
            for cs, t in nfa.edges[s]:
                if cs.negated:
                    nxt.add(t)
        o = -1
        if nxt:
            tgt = eclose(frozenset(nxt))
            if tgt not in ids:
                ids[tgt] = len(order)
                order.append(tgt)
            o = ids[tgt]
        trans.append(row)
        other.append(o)
        accept.append(end in cur)
    return Dfa(trans=trans, other=other, accept=accept, sigma=sigma)


# --------------------------------------------------------------------- #
# JSON schema / json_object → regex
# --------------------------------------------------------------------- #

_WS = "[ \t\n]*"
# JSON string: no raw control chars; only the legal JSON escapes
_STRING_CHAR = r'([^"\\\x00-\x1f]|\\(["\\/bfnrt]|u[0-9a-fA-F]{4}))'
_STRING = '"' + _STRING_CHAR + '*"'
_INT = "\\-?(0|[1-9][0-9]*)"
_NUM = _INT + "(\\.[0-9]+)?([eE][\\-+]?[0-9]+)?"
_SCALAR = f"({_STRING}|{_NUM}|true|false|null)"

DEFAULT_DEPTH = 4
DEFAULT_MAX_ITEMS = 8


def _free_value(depth: int) -> str:
    """Any JSON value, nesting bounded by `depth` (JSON is context-free;
    a regular approximation must bound the stack)."""
    if depth <= 0:
        return _SCALAR
    v = _free_value(depth - 1)
    obj = (
        "\\{" + _WS
        + f"({_STRING}{_WS}:{_WS}{v}({_WS},{_WS}{_STRING}{_WS}:{_WS}{v})*)?"
        + _WS + "\\}"
    )
    arr = "\\[" + _WS + f"({v}({_WS},{_WS}{v})*)?" + _WS + "\\]"
    return f"({_SCALAR}|{obj}|{arr})"


def schema_to_regex(schema: dict, depth: int = DEFAULT_DEPTH) -> str:
    if not isinstance(schema, dict):
        raise ValueError("schema must be an object")
    if "const" in schema:
        return _esc_literal(json.dumps(schema["const"]))
    if "enum" in schema:
        return (
            "(" + "|".join(_esc_literal(json.dumps(v)) for v in schema["enum"]) + ")"
        )
    for union_key in ("anyOf", "oneOf"):
        if union_key in schema:
            siblings = (
                {"type", "properties", "items", "enum", "const", "required",
                 "minLength", "maxLength", "pattern", "minItems", "maxItems"}
                & set(schema)
            )
            if siblings:
                # intersecting a union with sibling constraints is not
                # supported — enforcing only the union would be WEAKER
                # than the client asked for (silent-accept discipline)
                raise ValueError(
                    f"{union_key} cannot be combined with {sorted(siblings)}"
                )
            subs = schema[union_key]
            if not subs or not isinstance(subs, list):
                raise ValueError(f"{union_key} must be a non-empty list")
            if depth <= 0:
                raise ValueError("schema nesting exceeds supported depth")
            return (
                "("
                + "|".join(schema_to_regex(s, depth - 1) for s in subs)
                + ")"
            )
    t = schema.get("type")
    if isinstance(t, list):
        return (
            "("
            + "|".join(
                schema_to_regex({**schema, "type": x}, depth) for x in t
            )
            + ")"
        )
    if t == "string":
        if "pattern" in schema:
            # a raw pattern spliced between quotes can emit output that is
            # not valid JSON (embedded quotes/backslashes) — reject rather
            # than enforce a broken constraint
            raise ValueError(
                "string `pattern` is not supported in guided json_schema; "
                "use guided_regex for free-form patterns"
            )
        lo = schema.get("minLength")
        hi = schema.get("maxLength")
        if lo is not None or hi is not None:
            lo = int(lo or 0)
            quant = "{%d,%s}" % (lo, "" if hi is None else int(hi))
            return '"' + _STRING_CHAR + quant + '"'
        return _STRING
    if t == "integer":
        return _INT
    if t == "number":
        return _NUM
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t == "array":
        if depth <= 0:
            raise ValueError("schema nesting exceeds supported depth")
        item = schema_to_regex(schema.get("items", {}), depth - 1)
        lo = int(schema.get("minItems", 0))
        hi = int(schema.get("maxItems", max(lo, DEFAULT_MAX_ITEMS)))
        if hi < lo:
            raise ValueError("maxItems < minItems")
        if hi == 0:
            return "\\[" + _WS + "\\]"
        body = item + f"({_WS},{_WS}{item})" + "{%d,%d}" % (
            max(lo - 1, 0), hi - 1
        )
        if lo == 0:
            body = f"({body})?"
        return "\\[" + _WS + body + _WS + "\\]"
    if t == "object":
        props = schema.get("properties")
        if not props:
            return _free_value(max(depth, 1))
        if depth <= 0:
            raise ValueError("schema nesting exceeds supported depth")
        # `required` honored when present; absent = ALL required (stricter
        # than JSON Schema's none-required default, but the right default
        # for structured output — and the pre-round-5 behavior). Optional
        # properties keep declaration order; comma placement rides a
        # first-present-item alternation (an item can open the object only
        # if every earlier item is optional).
        required = (
            set(schema["required"]) if "required" in schema else set(props)
        )
        unknown = required - set(props)
        if unknown:
            raise ValueError(f"required names undeclared properties: {unknown}")
        items = [
            (
                _esc_literal(json.dumps(key))
                + _WS + ":" + _WS
                + schema_to_regex(sub, depth - 1),
                key in required,
            )
            for key, sub in props.items()
        ]
        sep = _WS + "," + _WS
        branches = []
        for i in range(len(items)):
            if any(req for _, req in items[:i]):
                break  # a required item before i cannot be skipped
            body = items[i][0]
            for re_j, req_j in items[i + 1:]:
                seg = sep + re_j
                body += seg if req_j else "(" + seg + ")?"
            branches.append(body)
        inner = "(" + "|".join(branches) + ")"
        if not any(req for _, req in items):
            inner += "?"
        return "\\{" + _WS + inner + _WS + "\\}"
    if t is None:
        return _free_value(depth)
    raise ValueError(f"unsupported schema type {t!r}")


def choice_to_regex(choices: Sequence[str]) -> str:
    if not choices:
        raise ValueError("guided_choice requires at least one option")
    return "(" + "|".join(_esc_literal(str(c)) for c in choices) + ")"


# --------------------------------------------------------------------- #
# token-level FSM
# --------------------------------------------------------------------- #


class _TrieNode:
    __slots__ = ("children", "token_ids")

    def __init__(self):
        self.children: Dict[str, _TrieNode] = {}
        self.token_ids: List[int] = []


def _build_trie(vocab: Sequence[str]) -> _TrieNode:
    root = _TrieNode()
    for tid, text in enumerate(vocab):
        if not text:
            continue  # empty decode (special tokens): never admissible
        node = root
        for ch in text:
            nxt = node.children.get(ch)
            if nxt is None:
                nxt = node.children[ch] = _TrieNode()
            node = nxt
        node.token_ids.append(tid)
    return root


class TokenFsm:
    """A char DFA lifted to the token vocabulary.

    `allowed(state)` → bool[V] mask of tokens whose FULL string keeps the
    DFA alive from `state` (computed by walking the shared vocab trie —
    tokens sharing prefixes share DFA work — and cached per state).
    EOS ids are admitted exactly in accepting states; if a state admits
    nothing (unsatisfiable pattern), EOS is admitted so generation can
    terminate instead of sampling from an all-masked row.
    """

    def __init__(self, dfa: Dfa, vocab: Sequence[str], eos_ids: Sequence[int]):
        self.dfa = dfa
        self.vocab_size = len(vocab)
        self.eos_ids = [e for e in eos_ids if 0 <= e < len(vocab)]
        self._trie = _build_trie(vocab)
        self._vocab = vocab
        self._masks: Dict[int, np.ndarray] = {}
        self._adv: Dict[Tuple[int, int], int] = {}

    @property
    def start_state(self) -> int:
        return 0

    def allowed(self, state: int) -> np.ndarray:
        cached = self._masks.get(state)
        if cached is not None:
            return cached
        mask = np.zeros((self.vocab_size,), bool)
        if state >= 0:
            stack = [(self._trie, state)]
            while stack:
                node, s = stack.pop()
                for tid in node.token_ids:
                    mask[tid] = True
                for ch, child in node.children.items():
                    ns = self.dfa.step(s, ch)
                    if ns >= 0:
                        stack.append((child, ns))
        if state >= 0 and self.dfa.accept[state]:
            mask[self.eos_ids] = True
        if not mask.any():
            mask[self.eos_ids] = True  # dead end: force termination
        self._masks[state] = mask
        return mask

    def advance(self, state: int, token_id: int) -> int:
        key = (state, token_id)
        cached = self._adv.get(key)
        if cached is not None:
            return cached
        s = self.dfa.walk(state, self._vocab[token_id]) if state >= 0 else -1
        self._adv[key] = s
        return s

    def is_accepting(self, state: int) -> bool:
        return state >= 0 and self.dfa.accept[state]


# --------------------------------------------------------------------- #
# request-surface extraction + compilation
# --------------------------------------------------------------------- #


def extract_guided_spec(response_format, nvext) -> Optional[dict]:
    """Normalize the request's structured-output asks into one guided spec
    dict ({"kind": ..., ...}) or None. Raises ValueError (→ HTTP 400) on
    unsupported or conflicting combinations — silent-accept is worse than
    absent (round-4 verdict weak #7)."""
    specs: List[dict] = []
    if response_format:
        rtype = response_format.get("type")
        if rtype in (None, "text"):
            pass
        elif rtype == "json_object":
            specs.append({"kind": "json_object"})
        elif rtype == "json_schema":
            js = response_format.get("json_schema") or {}
            schema = js.get("schema") if isinstance(js, dict) else None
            if not isinstance(schema, dict):
                raise ValueError(
                    "response_format.json_schema.schema must be an object"
                )
            specs.append({"kind": "json_schema", "schema": schema})
        else:
            raise ValueError(f"response_format type {rtype!r} not supported")
    if nvext is not None:
        if getattr(nvext, "guided_grammar", None):
            raise ValueError("guided_grammar (EBNF) is not supported")
        if getattr(nvext, "guided_choice", None):
            specs.append({"kind": "choice",
                          "choices": list(nvext.guided_choice)})
        if getattr(nvext, "guided_regex", None):
            specs.append({"kind": "regex", "regex": str(nvext.guided_regex)})
        gj = getattr(nvext, "guided_json", None)
        if gj:
            if isinstance(gj, str):
                try:
                    gj = json.loads(gj)
                except ValueError as e:
                    raise ValueError(f"guided_json is not valid JSON: {e}")
            if not isinstance(gj, dict):
                raise ValueError("guided_json must be a JSON schema object")
            specs.append({"kind": "json_schema", "schema": gj})
    if not specs:
        return None
    if len(specs) > 1:
        raise ValueError(
            "conflicting guided-decoding constraints: specify exactly one of "
            "response_format / guided_choice / guided_regex / guided_json"
        )
    return specs[0]


def spec_to_regex(spec: dict) -> str:
    kind = spec.get("kind")
    try:
        if kind == "regex":
            return spec["regex"]
        if kind == "choice":
            return choice_to_regex(spec["choices"])
        if kind == "json_schema":
            return schema_to_regex(spec["schema"])
    except ValueError:
        raise
    except Exception as e:  # noqa: BLE001 — malformed client schemas
        # (required: 5, minLength: [2], anyOf: 7, ...) raise TypeError/
        # KeyError deep in the compiler; the serving path maps ONLY
        # ValueError to a 400, so normalize here
        raise ValueError(f"malformed schema: {type(e).__name__}: {e}")
    if kind == "json_object":
        return _free_value(DEFAULT_DEPTH)
    raise ValueError(f"unknown guided kind {kind!r}")


import weakref

# weak-keyed: entries die with their tokenizer (an id()-keyed dict would
# both leak and serve stale vocab after CPython address reuse)
_VOCAB_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def vocab_strings(tokenizer) -> List[str]:
    """id → decoded string for the full vocab, cached per tokenizer
    object. Special tokens decode to "" (inadmissible in the FSM)."""
    try:
        cached = _VOCAB_CACHE.get(tokenizer)
    except TypeError:  # unhashable/non-weakref-able tokenizer: no cache
        cached = None
    if cached is not None:
        return cached
    V = tokenizer.vocab_size
    if callable(V):
        V = V()
    out = [tokenizer.decode([i]) for i in range(V)]
    try:
        _VOCAB_CACHE[tokenizer] = out
    except TypeError:
        pass
    return out


class GuidedCompiler:
    """Spec → TokenFsm with a bounded LRU cache (FSM compiles cost a
    vocab-trie walk; repeated requests with the same schema — the common
    serving pattern — hit the cache, while per-request-unique specs from
    a hostile/buggy client cannot grow it without bound: each TokenFsm
    lazily holds bool[V] masks per visited DFA state)."""

    MAX_ENTRIES = 32

    def __init__(self, tokenizer, max_entries: int = MAX_ENTRIES):
        import threading
        from collections import OrderedDict

        self.tokenizer = tokenizer
        self.max_entries = max_entries
        self._cache: "OrderedDict[str, TokenFsm]" = OrderedDict()
        # compile() runs on asyncio.to_thread workers (engine
        # _compile_guided_async): hit/evict must not race
        self._lock = threading.Lock()

    def compile(self, spec: dict) -> TokenFsm:
        key = json.dumps(spec, sort_keys=True)
        with self._lock:
            fsm = self._cache.get(key)
            if fsm is not None:
                self._cache.move_to_end(key)
                return fsm
        dfa = compile_regex(spec_to_regex(spec))
        eos = self.tokenizer.eos_token_ids
        if callable(eos):
            eos = eos()
        fsm = TokenFsm(dfa, vocab_strings(self.tokenizer), eos)
        with self._lock:
            cached = self._cache.get(key)
            if cached is not None:  # concurrent miss: first insert wins
                return cached
            self._cache[key] = fsm
            while len(self._cache) > self.max_entries:
                self._cache.popitem(last=False)
        return fsm
