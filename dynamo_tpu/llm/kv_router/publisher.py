"""Worker-side publishers: KV events + engine load metrics.

Mirrors reference lib/llm/src/kv_router/publisher.rs: `KvEventPublisher`
(:92) forwards engine block stored/removed events to the event plane, and
`WorkerMetricsPublisher` (:684) periodically publishes ForwardPassMetrics
(the reference scrapes via NATS $SRV.STATS; here both ride the discovery
pub/sub topics)."""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, List, Optional

from ...runtime import codec
from ...runtime.component import DistributedRuntime, Endpoint
from ..mocker.kv_manager import KvEvent
from .indexer import EVENT_TOPIC_FMT

logger = logging.getLogger(__name__)

METRICS_TOPIC_FMT = "kv_metrics/{namespace}/{component}"


class KvEventPublisher:
    """Batch + publish KV events for one worker (reference publisher.rs:92)."""

    def __init__(
        self,
        drt: DistributedRuntime,
        endpoint: Endpoint,
        worker_id: int,
        flush_interval: float = 0.01,
    ):
        self.drt = drt
        self.worker_id = worker_id
        self.topic = EVENT_TOPIC_FMT.format(
            namespace=endpoint.component.namespace, component=endpoint.component.name
        )
        self.flush_interval = flush_interval
        self._buffer: List[dict] = []
        self._task: Optional[asyncio.Task] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    async def start(self):
        self._loop = asyncio.get_running_loop()
        self._task = asyncio.create_task(self._flush_loop())

    def publish(self, event: KvEvent):
        """Queue an event (engine step-loop side, same event loop)."""
        self._buffer.append(event.to_dict())

    def publish_threadsafe(self, event: KvEvent):
        """Queue an event from a non-asyncio thread (JAX engine thread)."""
        if self._loop is None:
            self._buffer.append(event.to_dict())
        else:
            self._loop.call_soon_threadsafe(self._buffer.append, event.to_dict())

    async def _flush_loop(self):
        while True:
            await asyncio.sleep(self.flush_interval)
            if not self._buffer or self.drt.discovery is None:
                continue
            batch, self._buffer = self._buffer, []
            try:
                await self.drt.discovery.publish(
                    self.topic,
                    codec.pack({"worker_id": self.worker_id, "events": batch}),
                )
            except ConnectionError:
                logger.warning("kv event publish failed; dropping %d events", len(batch))

    async def close(self):
        if self._task:
            self._task.cancel()


class WorkerMetricsPublisher:
    """Publish engine load stats for the router's scheduler
    (reference WorkerMetricsPublisher publisher.rs:684)."""

    def __init__(
        self,
        drt: DistributedRuntime,
        endpoint: Endpoint,
        worker_id: int,
        stats_fn: Callable[[], dict],
        interval: float = 0.25,
    ):
        self.drt = drt
        self.worker_id = worker_id
        self.subject = endpoint.subject
        self.topic = METRICS_TOPIC_FMT.format(
            namespace=endpoint.component.namespace, component=endpoint.component.name
        )
        self.stats_fn = stats_fn
        self.interval = interval
        self._task: Optional[asyncio.Task] = None

    async def start(self):
        self._task = asyncio.create_task(self._loop())

    def _stats(self) -> dict:
        stats = dict(self.stats_fn() or {})
        # request-plane coalescing counters ride along: items/frames is the
        # worker-side tokens-per-frame signal the serving-gap bench and
        # hardware e2e rows read off this topic
        ep = self.drt.server.stats(self.subject)
        if ep is not None:
            stats.setdefault("frames_total", ep.frames_total)
            stats.setdefault("items_total", ep.items_total)
            # zero-copy token path visibility (docs/frontend_scaleout.md):
            # frames that rode the ENC_TOK binary payload
            stats.setdefault("frames_binary", ep.frames_binary)
        return stats

    async def _loop(self):
        while True:
            try:
                if self.drt.discovery is not None:
                    await self.drt.discovery.publish(
                        self.topic,
                        codec.pack(
                            {"worker_id": self.worker_id, "stats": self._stats()}
                        ),
                    )
            except ConnectionError:
                pass
            except Exception:  # noqa: BLE001
                logger.exception("metrics publish failed")
            await asyncio.sleep(self.interval)

    async def close(self):
        if self._task:
            self._task.cancel()
