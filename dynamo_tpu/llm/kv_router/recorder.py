"""KV-event recording and replay.

Role of the reference's KvRecorder (lib/llm/src/kv_router/recorder.rs +
lib/llm/src/recorder.rs): capture the router's KV-event stream to a JSONL
file with timestamps, and replay a capture later — into a live event topic
(load testing, router development without engines) or directly into an
indexer tree (state reconstruction), optionally time-scaled.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from pathlib import Path
from typing import List, Optional, Union

logger = logging.getLogger(__name__)


class KvRecorder:
    """Subscribe to a KV-event topic and append each message as a JSONL line
    {"ts": relative_seconds, "msg": {worker_id, events}}."""

    def __init__(self, drt, topic: str, path: Union[str, Path]):
        self.drt = drt
        self.topic = topic
        self.path = Path(path)
        self.events_recorded = 0
        self._task: Optional[asyncio.Task] = None
        self._sub = None
        self._t0: Optional[float] = None

    async def start(self):
        self._sub = await self.drt.discovery.subscribe(self.topic)
        self._task = asyncio.create_task(self._loop())

    async def _loop(self):
        from ...runtime import codec

        with self.path.open("a") as f:
            async for payload in self._sub:
                try:
                    msg = codec.unpack(payload)
                except Exception:  # noqa: BLE001
                    logger.exception("unreadable kv event; skipped")
                    continue
                now = time.monotonic()
                if self._t0 is None:
                    self._t0 = now
                f.write(json.dumps({"ts": now - self._t0, "msg": msg}) + "\n")
                f.flush()
                self.events_recorded += len(msg.get("events", []))

    async def close(self):
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._sub:
            await self._sub.cancel()


def load_recording(path: Union[str, Path]) -> List[dict]:
    """Read a JSONL capture; returns [{"ts": float, "msg": {...}}, ...]."""
    out = []
    with Path(path).open() as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def replay_into_tree(records: List[dict], tree) -> int:
    """Apply a capture directly to a radix tree; returns events applied."""
    n = 0
    for rec in records:
        msg = rec["msg"]
        worker_id = msg["worker_id"]
        for ev in msg.get("events", []):
            if ev.get("event_type") == "stored":
                tree.apply_stored(worker_id, ev["block_hashes"])
            elif ev.get("event_type") == "removed":
                tree.apply_removed(worker_id, ev["block_hashes"])
            elif ev.get("event_type") == "cleared":
                tree.clear_all_blocks(worker_id)
            n += 1
    return n


async def replay_to_topic(
    drt, topic: str, records: List[dict], timed: bool = False, speed: float = 1.0
) -> int:
    """Publish a capture back onto a live topic. With `timed`, inter-event
    gaps are reproduced (scaled by `speed`) — the reference's replay mode
    for exercising routers at recorded cadence."""
    from ...runtime import codec

    prev_ts = None
    n = 0
    for rec in records:
        if timed and prev_ts is not None:
            gap = (rec["ts"] - prev_ts) / speed
            if gap > 0:
                await asyncio.sleep(gap)
        prev_ts = rec["ts"]
        await drt.discovery.publish(topic, codec.pack(rec["msg"]))
        n += len(rec["msg"].get("events", []))
    return n
