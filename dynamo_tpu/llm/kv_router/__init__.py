"""KV-cache-aware routing (reference lib/llm/src/kv_router/).

`KvPushRouter` = the RouterMode::KV network hop: score workers by cached
prefix overlap (KvIndexer), pick via the scheduler cost + softmax
(KvScheduler), then send direct to the chosen instance
(reference KvRouter kv_router.rs:202, find_best_match :318).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, AsyncIterator, Dict, Optional

from ...runtime import codec
from ...runtime.component import Client, DistributedRuntime
from ...runtime.engine import Context
from ...runtime.request_plane import StreamLost
from ..model_card import ModelDeploymentCard
from ..tokens import compute_seq_hashes, salt_hash
from .indexer import (
    ApproxKvIndexer,
    KvIndexer,
    KvIndexerSharded,
    OverlapScores,
    RadixTree,
)
from .publisher import KvEventPublisher, WorkerMetricsPublisher, METRICS_TOPIC_FMT
from .scheduler import KvRouterConfig, KvScheduler, WorkerLoad, softmax_sample

logger = logging.getLogger(__name__)

__all__ = [
    "ApproxKvIndexer",
    "KvIndexerSharded",
    "KvEventPublisher",
    "KvIndexer",
    "KvPushRouter",
    "KvRouterConfig",
    "KvScheduler",
    "OverlapScores",
    "RadixTree",
    "WorkerLoad",
    "WorkerMetricsPublisher",
    "make_kv_router_factory",
    "softmax_sample",
]


class KvPushRouter:
    """The KV routing hop (reference KvPushRouter in bindings / KvRouter
    kv_router.rs:202)."""

    def __init__(
        self,
        drt: DistributedRuntime,
        client: Client,
        config: Optional[KvRouterConfig] = None,
        block_size: int = 64,
    ):
        self.drt = drt
        self.client = client
        self.config = config or KvRouterConfig(block_size=block_size)
        # the model card's kv block size is authoritative: hashes must match
        # what the worker's engine emits (SURVEY.md hard part (c))
        self.block_size = block_size
        self.config.block_size = block_size
        ns = client.endpoint.component.namespace
        comp = client.endpoint.component.name
        if self.config.use_kv_events:
            self.indexer = KvIndexer(drt, ns, comp, self.block_size)
        else:
            self.indexer = ApproxKvIndexer(self.block_size)
        # event mode: a short-TTL overlay of ROUTED prefixes, merged into
        # the event-based scores. Engine KV events take seconds to land;
        # without the overlay, same-prefix requests arriving inside that
        # window score overlap 0 everywhere and spread across workers —
        # exactly the requests the KV router exists to co-locate.
        self._inflight_overlay = (
            ApproxKvIndexer(self.block_size, ttl=self.config.inflight_prefix_ttl_s)
            if self.config.use_kv_events and self.config.inflight_prefix_ttl_s > 0
            else None
        )
        self.scheduler = KvScheduler(self.config)
        self._metrics_sub = None
        self._metrics_task: Optional[asyncio.Task] = None
        self._known_workers: set[int] = set()
        # replica sync (reference kv_router/subscriber.rs): multiple KV-mode
        # frontends mirror each other's routing decisions so their
        # active-block accounting (and approx indexers) don't drift
        import secrets as _secrets

        self._sync_id = _secrets.token_hex(4)
        self._sync_sub = None
        self._sync_task: Optional[asyncio.Task] = None
        self._bg: set = set()
        # fast corpse cleanup (docs/fault_tolerance.md): a worker whose
        # stream just died is SUSPECT until this deadline — its radix/
        # overlay/scheduler state is forgotten immediately (stale prefix
        # scores must not pin retries to the corpse, and the holder hint
        # must never name it) and new streams skip it while its lease
        # lingers. A live worker re-earns entries through its own events.
        self._suspect: Dict[int, float] = {}

    @property
    def _sync_topic(self) -> str:
        ns = self.client.endpoint.component.namespace
        comp = self.client.endpoint.component.name
        return f"kv_router_sync/{ns}/{comp}"

    async def start(self):
        if isinstance(self.indexer, KvIndexer):
            await self.indexer.start()
        ns = self.client.endpoint.component.namespace
        comp = self.client.endpoint.component.name
        if self.drt.discovery is not None:
            self._metrics_sub = await self.drt.discovery.subscribe(
                METRICS_TOPIC_FMT.format(namespace=ns, component=comp)
            )
            self._metrics_task = asyncio.create_task(self._metrics_loop())
            if self.config.replica_sync:
                self._sync_sub = await self.drt.discovery.subscribe(self._sync_topic)
                self._sync_task = asyncio.create_task(self._sync_loop())

    def _publish_sync(self, msg: dict):
        if self._sync_sub is None or self.drt.discovery is None:
            return
        msg["router"] = self._sync_id

        async def _pub():
            try:
                await self.drt.discovery.publish(self._sync_topic, codec.pack(msg))
            except Exception:  # noqa: BLE001 — sync is best-effort
                logger.debug("replica sync publish failed", exc_info=True)

        t = asyncio.create_task(_pub())
        self._bg.add(t)
        t.add_done_callback(self._bg.discard)

    async def _sync_loop(self):
        async for payload in self._sync_sub:
            try:
                msg = codec.unpack(payload)
                if msg.get("router") == self._sync_id:
                    continue  # our own event
                if msg["op"] == "route":
                    # mirrored=True: no local stream ends this entry, so the
                    # scheduler TTL-prunes it if the peer's 'free' never
                    # arrives (peer crash / dropped best-effort publish)
                    self.scheduler.add_request(
                        msg["request_id"], msg["worker"], msg["blocks"],
                        mirrored=True,
                    )
                    hashes = msg.get("prefix_hashes") or []
                    if not hashes and msg.get("token_ids"):
                        # older peers shipped raw token ids
                        hashes = compute_seq_hashes(
                            msg["token_ids"], self.block_size
                        )
                    if hashes:
                        if isinstance(self.indexer, ApproxKvIndexer):
                            self.indexer.apply_routed_hashes(hashes, msg["worker"])
                        if self._inflight_overlay is not None:
                            self._inflight_overlay.apply_routed_hashes(
                                hashes, msg["worker"]
                            )
                elif msg["op"] == "free":
                    self.scheduler.mark_free(msg["request_id"])
            except Exception:  # noqa: BLE001
                logger.exception("bad replica sync message")

    async def _metrics_loop(self):
        async for payload in self._metrics_sub:
            try:
                msg = codec.unpack(payload)
                self.scheduler.update_load(msg["worker_id"], msg.get("stats", {}))
            except Exception:  # noqa: BLE001
                logger.exception("bad metrics message")

    def _prune_dead_workers(self, live: list[int]):
        live_set = set(live)
        dead = self._known_workers - live_set
        for w in dead:
            self.indexer.remove_worker(w)
            if self._inflight_overlay is not None:
                self._inflight_overlay.remove_worker(w)
            self.scheduler.remove_worker(w)
            self._suspect.pop(w, None)  # lease authority took over
        self._known_workers = live_set

    def note_stream_lost(self, worker: int, ttl_s: float = 15.0):
        """A stream on `worker` died mid-flight: treat the worker as a
        corpse ahead of lease expiry — forget its radix/overlay/scheduler
        state NOW (stale overlap scores and holder hints must not pin
        retries to it) and keep it out of new-stream candidate sets for
        `ttl_s`. If the worker is actually alive (transient blip), it
        re-earns index entries from its own KV events and load reports —
        degraded routing for a moment, never a wrong dial."""
        self._suspect[int(worker)] = time.monotonic() + ttl_s
        self.indexer.remove_worker(int(worker))
        if self._inflight_overlay is not None:
            self._inflight_overlay.remove_worker(int(worker))
        self.scheduler.remove_worker(int(worker))

    def _live_suspects(self) -> set:
        now = time.monotonic()
        for w, dl in list(self._suspect.items()):
            if now >= dl:
                del self._suspect[w]
        return set(self._suspect)

    def find_best_match(
        self,
        token_ids: list[int],
        router_override: Optional[dict] = None,
        seq_hashes: Optional[list[int]] = None,
        return_scores: bool = False,
        exclude: Optional[set] = None,
    ) -> tuple:
        """Returns (worker_id, overlap_blocks) — reference find_best_match
        kv_router.rs:318. `seq_hashes`: precomputed block hashes (generate()
        hashes the prompt ONCE and reuses them here, for the overlay record
        and for the sync publish). `return_scores=True` appends the full
        per-worker overlap map (the cluster-KV-fabric holder hint reads
        the best-overlap worker from it). `exclude`: instances a migration
        retry named dead — never scheduled, never the holder hint."""
        live = self.client.instance_ids()
        # NEW streams schedule only onto ready instances: a `draining`
        # worker (scale-down in progress) would reject the stream anyway —
        # same invariant as PushRouter._pick. It stays in `live` though:
        # its index/overlay state is pruned by the lease-revoke delete,
        # not by the drain mark.
        ready = self.client.ready_instance_ids()
        hard = set(exclude or ())  # named dead by a migration retry
        avoid = hard | self._live_suspects()
        if avoid:
            # corpse-free candidate set; when ONLY suspects remain, fall
            # back to them (a suspect may be a transient blip — serving
            # beats refusing), but hard exclusions never come back: the
            # retry KNOWS that worker lost its stream
            filtered = [i for i in ready if i not in avoid]
            ready = filtered or [i for i in ready if i not in hard]
        if not ready:
            raise StreamLost(f"no instances for {self.client.endpoint.subject}")
        self._prune_dead_workers(live)
        pruned = self.scheduler.prune_mirrored()
        if pruned:
            logger.info("pruned %d stale mirrored sync entries", pruned)
        if seq_hashes is None:
            seq_hashes = compute_seq_hashes(token_ids, self.block_size)
        scores = self.indexer.find_matches_for_hashes(seq_hashes)
        if self._inflight_overlay is not None:
            inflight = self._inflight_overlay.find_matches_for_hashes(seq_hashes)
            for w, ov in inflight.scores.items():
                scores.scores[w] = max(scores.scores.get(w, 0), ov)
        request_blocks = len(token_ids) // self.block_size
        cfg = self.config
        if router_override:
            cfg = KvRouterConfig(
                overlap_score_weight=router_override.get(
                    "overlap_score_weight", cfg.overlap_score_weight
                ),
                router_temperature=router_override.get(
                    "router_temperature", cfg.router_temperature
                ),
                block_size=cfg.block_size,
            )
        saved = self.scheduler.config
        self.scheduler.config = cfg
        try:
            worker = self.scheduler.schedule(request_blocks, scores.scores, ready)
        finally:
            self.scheduler.config = saved
        if return_scores:
            return worker, scores.scores.get(worker, 0), dict(scores.scores)
        return worker, scores.scores.get(worker, 0)

    async def generate(
        self, request: Dict[str, Any], context: Optional[Context] = None
    ) -> AsyncIterator[Any]:
        token_ids = request.get("token_ids", [])
        request_id = request.get("request_id") or ""
        # LoRA adapters salt the hash chain exactly like the engine's
        # prefix cache (tokens.py; reference protocols.rs lora_id): the
        # router only co-locates same-adapter prefixes
        salt = (
            salt_hash(request["lora_name"].encode())
            if request.get("lora_name") else 0
        )
        seq_hashes = compute_seq_hashes(token_ids, self.block_size, salt)
        pinned = request.get("router", {}).get("backend_instance_id")
        from ...runtime.push_router import request_excluded_instances

        excluded = set(request_excluded_instances(request))
        holder = None
        if pinned is not None and int(pinned) in excluded:
            # a pin naming an excluded (dead) instance must not bypass
            # the corpse-exclusion contract — route as if unpinned
            pinned = None
        if pinned is not None:
            worker, overlap = int(pinned), 0
        else:
            worker, overlap, overlap_scores = self.find_best_match(
                token_ids, request.get("router") or None,
                seq_hashes=seq_hashes, return_scores=True,
                exclude=excluded,
            )
            # cluster KV fabric (docs/kvbm.md): the index already knows
            # which OTHER worker holds the longest cached prefix — ship
            # (holder, matched_blocks) with the request so the chosen
            # worker can pull those blocks from the holder's tiers instead
            # of recomputing them. Only a strictly-better holder is worth
            # a hint; the worker's own announcement mesh covers the rest.
            # A dead/suspect worker must never be the hint: a stale holder
            # would pin the resumed stream's onboard to the corpse.
            avoid_holder = excluded | self._live_suspects()
            best_holder = max(
                (w for w in overlap_scores
                 if w != worker and w not in avoid_holder),
                key=lambda w: overlap_scores[w], default=None,
            )
            if best_holder is not None and overlap_scores[best_holder] > overlap:
                holder = {
                    "instance": int(best_holder),
                    "blocks": int(overlap_scores[best_holder]),
                }
        request = dict(request)
        request["estimated_prefix_hit_num_blocks"] = overlap
        if holder is not None:
            request["kv_holder"] = holder
        blocks = max(len(token_ids) // self.block_size, 1)
        self.scheduler.add_request(request_id, worker, blocks)
        if isinstance(self.indexer, ApproxKvIndexer):
            self.indexer.apply_routed_hashes(seq_hashes, worker)
        if self._inflight_overlay is not None:
            self._inflight_overlay.apply_routed_hashes(seq_hashes, worker)
        self._publish_sync(
            {
                "op": "route", "request_id": request_id, "worker": worker,
                "blocks": blocks,
                # peers mirror prefix state (approx indexer / in-flight
                # overlay) from the block HASHES — block_size x smaller
                # than the token list and pre-hashed for the receiver
                "prefix_hashes": list(seq_hashes)
                if isinstance(self.indexer, ApproxKvIndexer)
                or self._inflight_overlay is not None else [],
            }
        )
        try:
            inner = await self.client.direct(request, worker, context)
        except StreamLost:
            self.scheduler.mark_free(request_id)
            # replicas mirrored the route: they must see the free too, or
            # they leak the active request forever (no TTL pruning)
            self._publish_sync({"op": "free", "request_id": request_id})
            self.note_stream_lost(worker)
            raise
        return self._wrap(inner, request_id, worker)

    async def _wrap(self, stream: AsyncIterator[Any], request_id: str,
                    worker: int):
        try:
            async for item in stream:
                yield item
        except StreamLost:
            # mid-stream death: forget the corpse NOW (fast corpse
            # cleanup) so the migration retry's re-route and holder hint
            # never land back on it while its lease lingers
            self.note_stream_lost(worker)
            raise
        finally:
            self.scheduler.mark_free(request_id)
            self._publish_sync({"op": "free", "request_id": request_id})

    async def close(self):
        # in-flight best-effort sync publishes die with the router
        for t in list(self._bg):
            t.cancel()
        if self._metrics_task:
            self._metrics_task.cancel()
        if self._metrics_sub:
            await self._metrics_sub.cancel()
        if self._sync_task:
            self._sync_task.cancel()
        if self._sync_sub:
            await self._sync_sub.cancel()
        if isinstance(self.indexer, KvIndexer):
            await self.indexer.close()


def make_kv_router_factory(config: KvRouterConfig):
    """Factory used by the ModelWatcher when --router-mode kv."""

    async def factory(drt: DistributedRuntime, card: ModelDeploymentCard, client: Client):
        import dataclasses

        per_model = dataclasses.replace(config, block_size=card.kv_cache_block_size)
        router = KvPushRouter(
            drt, client, per_model, block_size=card.kv_cache_block_size
        )
        await router.start()
        return router

    return factory
