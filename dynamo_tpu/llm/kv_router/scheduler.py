"""KV-aware worker selection.

Mirrors reference lib/llm/src/kv_router/scheduler.rs: cost =
`overlap_weight * potential_prefill_blocks + potential_decode_blocks`
(:505-538) and softmax/temperature sampling over negated costs
(softmax_sample :389). "Potential" blocks include sequences this router has
scheduled but the worker hasn't reported yet (reference sequence.rs
ActiveSequences), so rapid-fire requests don't all pile onto one worker.
"""

from __future__ import annotations

import logging
import math
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ...runtime.metrics import KV_ACTIVE_BLOCKS, KV_TOTAL_BLOCKS, NUM_WAITING_REQS

logger = logging.getLogger(__name__)


@dataclass
class KvRouterConfig:
    """Reference KvRouterConfig kv_router.rs:85."""

    overlap_score_weight: float = 1.0
    router_temperature: float = 0.0
    use_kv_events: bool = True  # False -> ApproxKvIndexer
    replica_sync: bool = False
    block_size: int = 64
    # mirrored replica-sync entries have no local stream whose end frees
    # them — if the publishing frontend dies (or its best-effort 'free' is
    # dropped) they would skew active-block scoring forever; prune at a
    # max-request-lifetime TTL instead
    sync_entry_ttl_s: float = 600.0
    # event mode only: assume a routed prefix is cached on its worker for
    # this long, so same-prefix requests arriving BEFORE the engine's KV
    # events co-locate instead of spreading (0 disables the overlay)
    inflight_prefix_ttl_s: float = 30.0


@dataclass
class _ActiveSeq:
    worker_id: int
    blocks: int
    started: float = field(default_factory=time.monotonic)
    mirrored: bool = False  # came from replica sync, not a local stream


@dataclass
class WorkerLoad:
    """Last reported engine stats (ForwardPassMetrics role)."""

    kv_active_blocks: int = 0
    kv_total_blocks: int = 1
    num_waiting_reqs: int = 0
    updated: float = 0.0


def softmax_sample(costs: Dict[int, float], temperature: float) -> int:
    """Sample a worker by softmax over negated costs; temperature 0 =
    argmin with random tie-break (reference softmax_sample scheduler.rs:389)."""
    if not costs:
        raise ValueError("no workers to sample")
    if temperature <= 0.0:
        best = min(costs.values())
        candidates = [w for w, c in costs.items() if c == best]
        return random.choice(candidates)
    # normalize for stability
    vals = list(costs.values())
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    logits = {w: -((c - lo) / span) / temperature for w, c in costs.items()}
    mx = max(logits.values())
    exps = {w: math.exp(v - mx) for w, v in logits.items()}
    total = sum(exps.values())
    r = random.random() * total
    acc = 0.0
    for w, e in exps.items():
        acc += e
        if r <= acc:
            return w
    return w  # numerical tail


class KvScheduler:
    """Pick the best worker for a request (reference KvScheduler
    scheduler.rs:297)."""

    def __init__(self, config: Optional[KvRouterConfig] = None):
        self.config = config or KvRouterConfig()
        self.loads: Dict[int, WorkerLoad] = {}
        self._active: Dict[str, _ActiveSeq] = {}  # request_id -> seq
        self._potential_blocks: Dict[int, int] = {}  # worker -> unreported blocks

    # -- state updates ------------------------------------------------------ #

    def update_load(self, worker_id: int, stats: dict):
        load = self.loads.setdefault(worker_id, WorkerLoad())
        load.kv_active_blocks = int(stats.get(KV_ACTIVE_BLOCKS, 0))
        load.kv_total_blocks = max(int(stats.get(KV_TOTAL_BLOCKS, 1)), 1)
        load.num_waiting_reqs = int(stats.get(NUM_WAITING_REQS, 0))
        load.updated = time.monotonic()

    def add_request(
        self, request_id: str, worker_id: int, blocks: int, mirrored: bool = False
    ):
        # re-adding an id (e.g. duplicate sync delivery) must not leak the
        # old entry's potential blocks
        if request_id in self._active:
            self.mark_free(request_id)
        self._active[request_id] = _ActiveSeq(worker_id, blocks, mirrored=mirrored)
        self._potential_blocks[worker_id] = (
            self._potential_blocks.get(worker_id, 0) + blocks
        )

    def mark_free(self, request_id: str):
        seq = self._active.pop(request_id, None)
        if seq is not None:
            w = seq.worker_id
            self._potential_blocks[w] = max(
                0, self._potential_blocks.get(w, 0) - seq.blocks
            )

    def prune_mirrored(self, now: Optional[float] = None) -> int:
        """Drop mirrored entries older than sync_entry_ttl_s (reference
        subscriber.rs keeps replicas converged via resync; here sync is
        best-effort pub/sub, so staleness is bounded by TTL instead).
        Returns how many entries were pruned."""
        now = time.monotonic() if now is None else now
        ttl = self.config.sync_entry_ttl_s
        stale = [
            rid for rid, s in self._active.items()
            if s.mirrored and now - s.started > ttl
        ]
        for rid in stale:
            self.mark_free(rid)
        return len(stale)

    def remove_worker(self, worker_id: int):
        self.loads.pop(worker_id, None)
        self._potential_blocks.pop(worker_id, None)
        for rid in [r for r, s in self._active.items() if s.worker_id == worker_id]:
            self._active.pop(rid, None)

    # -- the decision ------------------------------------------------------- #

    def schedule(
        self,
        request_blocks: int,
        overlap_scores: Dict[int, int],
        live_workers: List[int],
    ) -> int:
        """Reference cost function scheduler.rs:505-538."""
        if not live_workers:
            raise RuntimeError("no live workers")
        costs: Dict[int, float] = {}
        for w in live_workers:
            overlap = overlap_scores.get(w, 0)
            potential_prefill = max(request_blocks - overlap, 0)
            load = self.loads.get(w)
            decode_blocks = (load.kv_active_blocks if load else 0) + self._potential_blocks.get(w, 0)
            costs[w] = (
                self.config.overlap_score_weight * potential_prefill + decode_blocks
            )
        choice = softmax_sample(costs, self.config.router_temperature)
        logger.debug(
            "kv schedule: blocks=%d overlaps=%s costs=%s -> %x",
            request_blocks,
            overlap_scores,
            costs,
            choice,
        )
        return choice
