"""KV block index: which worker holds which cached blocks.

Mirrors reference lib/llm/src/kv_router/indexer.rs (RadixTree :224,
find_matches :276, apply_event :336). Because block hashes are CHAINED
sequence hashes (tokens.py), a hash is globally unique to its exact prefix —
so the radix tree collapses to a flat hash→workers map, with per-worker
continuity enforced during the match walk (a worker that evicted an early
block stops matching at the gap). This is O(1) per block with no tree
rebalancing — cheaper than the reference's pointer tree for the same
semantics.
"""

from __future__ import annotations

import asyncio
import json
import logging
from collections import defaultdict
from typing import Dict, List, Optional, Set

from ...runtime.component import DistributedRuntime
from ..tokens import compute_seq_hashes

logger = logging.getLogger(__name__)


class OverlapScores:
    """Per-worker count of matched prefix blocks (reference indexer.rs
    OverlapScores)."""

    def __init__(self):
        self.scores: Dict[int, int] = {}
        self.frequencies: List[int] = []  # workers matching at each depth

    def __repr__(self):
        return f"OverlapScores({self.scores})"


class RadixTree:
    """Flat chained-hash index with match-walk semantics
    (reference RadixTree indexer.rs:224)."""

    def __init__(self):
        self._blocks: Dict[int, Set[int]] = defaultdict(set)  # hash -> workers
        self._worker_blocks: Dict[int, Set[int]] = defaultdict(set)  # worker -> hashes

    def apply_stored(self, worker_id: int, block_hashes: List[int]):
        for h in block_hashes:
            self._blocks[h].add(worker_id)
            self._worker_blocks[worker_id].add(h)

    def apply_removed(self, worker_id: int, block_hashes: List[int]):
        for h in block_hashes:
            workers = self._blocks.get(h)
            if workers:
                workers.discard(worker_id)
                if not workers:
                    self._blocks.pop(h, None)
            self._worker_blocks[worker_id].discard(h)

    def remove_worker(self, worker_id: int):
        """Worker died: drop all its blocks (reference remove_worker)."""
        for h in self._worker_blocks.pop(worker_id, set()):
            workers = self._blocks.get(h)
            if workers:
                workers.discard(worker_id)
                if not workers:
                    self._blocks.pop(h, None)

    def clear_all_blocks(self, worker_id: int):
        self.remove_worker(worker_id)

    def find_matches(self, seq_hashes: List[int], early_exit: bool = False) -> OverlapScores:
        """Walk the prefix; a worker scores i+1 if it holds blocks 0..i
        contiguously (reference find_matches indexer.rs:276)."""
        result = OverlapScores()
        active: Optional[Set[int]] = None
        for depth, h in enumerate(seq_hashes):
            holders = self._blocks.get(h)
            if not holders:
                break
            active = set(holders) if active is None else (active & holders)
            if not active:
                break
            result.frequencies.append(len(active))
            for w in active:
                result.scores[w] = depth + 1
            if early_exit and len(active) == 1:
                break
        return result

    @property
    def num_blocks(self) -> int:
        return len(self._blocks)

    def worker_block_count(self, worker_id: int) -> int:
        return len(self._worker_blocks.get(worker_id, ()))

    def workers(self) -> List[int]:
        return list(self._worker_blocks.keys())

    def dump(self) -> dict:
        """Snapshot for replica sync / persistence (reference snapshots to
        the object store)."""
        return {
            str(w): sorted(hs) for w, hs in self._worker_blocks.items() if hs
        }

    def load(self, snapshot: dict):
        for w_str, hashes in snapshot.items():
            self.apply_stored(int(w_str), list(hashes))


EVENT_TOPIC_FMT = "kv_events/{namespace}/{component}"


ROUTER_SNAPSHOT_KEY_FMT = "v1/router_snapshots/{namespace}/{component}"


class KvIndexer:
    """Event-driven index: subscribes to the component's KV-event topic and
    applies stored/removed events to the RadixTree
    (reference KvIndexer indexer.rs + subscriber.rs).

    With `snapshot_threshold` set, the tree is persisted to the discovery
    KV (the reference's NATS object-store role, kv_cache_routing.md
    --router-snapshot-threshold) every N applied events and restored on
    start, so a restarted/added router replica syncs without replaying the
    whole event history. `reset_states` drops any stored snapshot instead
    (--router-reset-states)."""

    def __init__(
        self,
        drt: DistributedRuntime,
        namespace: str,
        component: str,
        block_size: int = 64,
        snapshot_threshold: Optional[int] = None,
        reset_states: bool = False,
    ):
        from ...native import make_radix_tree

        self.drt = drt
        self.block_size = block_size
        self.topic = EVENT_TOPIC_FMT.format(namespace=namespace, component=component)
        self.snapshot_key = ROUTER_SNAPSHOT_KEY_FMT.format(
            namespace=namespace, component=component
        )
        self.snapshot_threshold = snapshot_threshold
        self.reset_states = reset_states
        self.tree = make_radix_tree()  # C++ index when built, else RadixTree
        self._task: Optional[asyncio.Task] = None
        self._sub = None
        self.events_applied = 0
        self._events_at_snapshot = 0
        self._persist_task: Optional[asyncio.Task] = None

    async def start(self):
        assert self.drt.discovery is not None
        # subscribe BEFORE restoring: events arriving during the restore are
        # buffered in the subscription, not lost (load is additive)
        self._sub = await self.drt.discovery.subscribe(self.topic)
        if self.reset_states:
            await self.drt.discovery.delete(self.snapshot_key)
        elif self.snapshot_threshold is not None:
            await self._restore_snapshot()
        self._task = asyncio.create_task(self._loop())

    async def _restore_snapshot(self):
        raw = await self.drt.discovery.get(self.snapshot_key)
        if not raw:
            return
        try:
            self.tree.load(json.loads(raw))
            logger.info("restored router snapshot (%d blocks)", self.tree.num_blocks)
        except Exception:  # noqa: BLE001 — corrupt snapshot: start cold
            logger.exception("router snapshot restore failed; starting cold")

    def _start_persist_snapshot(self):
        """Dump the tree inline (consistent point-in-time view), then encode
        and upload off the event-apply hot path."""
        if self._persist_task is not None and not self._persist_task.done():
            return  # previous upload still in flight; next threshold retries
        snapshot = self.tree.dump()
        self._events_at_snapshot = self.events_applied

        async def upload():
            try:
                loop = asyncio.get_running_loop()
                raw = await loop.run_in_executor(
                    None, lambda: json.dumps(snapshot).encode()
                )
                await self.drt.discovery.put(self.snapshot_key, raw)
            except Exception:  # noqa: BLE001
                logger.exception("router snapshot persist failed")

        self._persist_task = asyncio.create_task(upload())

    async def _loop(self):
        from ...runtime import codec

        async for payload in self._sub:
            try:
                msg = codec.unpack(payload)
                worker_id = msg["worker_id"]
                for ev in msg.get("events", []):
                    if ev.get("event_type") == "stored":
                        self.tree.apply_stored(worker_id, ev["block_hashes"])
                    elif ev.get("event_type") == "removed":
                        self.tree.apply_removed(worker_id, ev["block_hashes"])
                    elif ev.get("event_type") == "cleared":
                        self.tree.clear_all_blocks(worker_id)
                    self.events_applied += 1
                if (
                    self.snapshot_threshold is not None
                    and self.events_applied - self._events_at_snapshot
                    >= self.snapshot_threshold
                ):
                    self._start_persist_snapshot()
            except Exception:  # noqa: BLE001 — indexer must survive bad events
                logger.exception("bad kv event")

    def find_matches_for_tokens(self, token_ids: List[int]) -> OverlapScores:
        return self.find_matches_for_hashes(
            compute_seq_hashes(token_ids, self.block_size)
        )

    def find_matches_for_hashes(self, hashes: List[int]) -> OverlapScores:
        return self.tree.find_matches(hashes)

    def remove_worker(self, worker_id: int):
        self.tree.remove_worker(worker_id)

    async def close(self):
        if self._task:
            self._task.cancel()
        if self._persist_task is not None and not self._persist_task.done():
            try:
                await self._persist_task
            except Exception:  # noqa: BLE001
                pass
        if self._sub:
            await self._sub.cancel()


class KvIndexerSharded:
    """N independent trees, workers assigned by worker_id modulo shards;
    lookups fan out and merge (reference KvIndexerSharded indexer.rs:992 —
    bounds per-trie size and contention for large fleets)."""

    def __init__(self, num_shards: int = 4, block_size: int = 64):
        from ...native import make_radix_tree

        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.block_size = block_size
        self.shards = [make_radix_tree() for _ in range(num_shards)]

    def _shard(self, worker_id: int):
        return self.shards[worker_id % len(self.shards)]

    def apply_stored(self, worker_id: int, block_hashes: List[int]):
        self._shard(worker_id).apply_stored(worker_id, block_hashes)

    def apply_removed(self, worker_id: int, block_hashes: List[int]):
        self._shard(worker_id).apply_removed(worker_id, block_hashes)

    def clear_all_blocks(self, worker_id: int):
        self._shard(worker_id).clear_all_blocks(worker_id)

    def remove_worker(self, worker_id: int):
        self._shard(worker_id).remove_worker(worker_id)

    def find_matches(self, seq_hashes: List[int], early_exit: bool = False) -> OverlapScores:
        merged = OverlapScores()
        for shard in self.shards:
            r = shard.find_matches(seq_hashes, early_exit=early_exit)
            merged.scores.update(r.scores)
            # frequencies[d] counts workers matching at depth d; shards hold
            # disjoint workers, so merge is an element-wise sum
            if len(r.frequencies) > len(merged.frequencies):
                merged.frequencies.extend(
                    [0] * (len(r.frequencies) - len(merged.frequencies))
                )
            for d, f in enumerate(r.frequencies):
                merged.frequencies[d] += f
        return merged

    def find_matches_for_tokens(self, token_ids: List[int]) -> OverlapScores:
        return self.find_matches(compute_seq_hashes(token_ids, self.block_size))

    @property
    def num_blocks(self) -> int:
        return sum(s.num_blocks for s in self.shards)

    def workers(self) -> List[int]:
        out: List[int] = []
        for s in self.shards:
            out.extend(s.workers())
        return out

    def dump(self) -> dict:
        merged: dict = {}
        for s in self.shards:
            merged.update(s.dump())
        return merged

    def load(self, snapshot: dict):
        for w_str, hashes in snapshot.items():
            self.apply_stored(int(w_str), list(hashes))


class ApproxKvIndexer:
    """Indexer that needs no engine events: assumes a routed request's prefix
    becomes cached on the chosen worker for a TTL
    (reference ApproxKvIndexer approx.rs)."""

    def __init__(self, block_size: int = 64, ttl: float = 120.0):
        from ...native import make_radix_tree

        self.block_size = block_size
        self.ttl = ttl
        self.tree = make_radix_tree()
        self._expiry: List[tuple] = []  # (deadline, worker_id, hashes)
        # refcount per (worker, hash): a hot prefix re-routed inside the
        # TTL appends a SECOND expiry entry — without counts, the OLDER
        # entry's expiry would erase the still-valid refresh
        self._refs: dict = {}

    def process_routing_decision_for_request(self, token_ids: List[int], worker_id: int):
        self.apply_routed_hashes(
            compute_seq_hashes(token_ids, self.block_size), worker_id
        )

    def apply_routed_hashes(self, hashes: List[int], worker_id: int):
        import time

        self.tree.apply_stored(worker_id, hashes)
        for h in hashes:
            key = (worker_id, h)
            self._refs[key] = self._refs.get(key, 0) + 1
        self._expiry.append((time.monotonic() + self.ttl, worker_id, hashes))
        self._expire()

    def _expire(self):
        import time

        now = time.monotonic()
        while self._expiry and self._expiry[0][0] < now:
            _, worker_id, hashes = self._expiry.pop(0)
            dead = []
            for h in hashes:
                key = (worker_id, h)
                n = self._refs.get(key, 1) - 1
                if n <= 0:
                    self._refs.pop(key, None)
                    dead.append(h)
                else:
                    self._refs[key] = n
            if dead:
                self.tree.apply_removed(worker_id, dead)

    def find_matches_for_tokens(self, token_ids: List[int]) -> OverlapScores:
        return self.find_matches_for_hashes(
            compute_seq_hashes(token_ids, self.block_size)
        )

    def find_matches_for_hashes(self, hashes: List[int]) -> OverlapScores:
        self._expire()
        return self.tree.find_matches(hashes)

    def remove_worker(self, worker_id: int):
        self.tree.remove_worker(worker_id)
        self._refs = {k: v for k, v in self._refs.items() if k[0] != worker_id}
        self._expiry = [e for e in self._expiry if e[1] != worker_id]
