"""KV block index: which worker holds which cached blocks.

Mirrors reference lib/llm/src/kv_router/indexer.rs (RadixTree :224,
find_matches :276, apply_event :336). Because block hashes are CHAINED
sequence hashes (tokens.py), a hash is globally unique to its exact prefix —
so the radix tree collapses to a flat hash→workers map, with per-worker
continuity enforced during the match walk (a worker that evicted an early
block stops matching at the gap). This is O(1) per block with no tree
rebalancing — cheaper than the reference's pointer tree for the same
semantics.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
from collections import OrderedDict, defaultdict
from typing import Dict, List, Optional, Set

from ...runtime.component import DistributedRuntime
from ..tokens import compute_seq_hashes

logger = logging.getLogger(__name__)


def _index_cap_from_env() -> Optional[int]:
    """DYN_ROUTER_INDEX_MAX_BLOCKS: block-count cap per router index
    (0/unset = unbounded, the seed behavior). At a million sessions the
    event stream grows the index without bound — the cap turns that into
    leaf-first eviction instead of a frontend OOM."""
    raw = os.environ.get("DYN_ROUTER_INDEX_MAX_BLOCKS")
    try:
        cap = int(raw) if raw else 0
    except ValueError:
        logger.warning("DYN_ROUTER_INDEX_MAX_BLOCKS=%r invalid; unbounded", raw)
        cap = 0
    return cap if cap > 0 else None


class OverlapScores:
    """Per-worker count of matched prefix blocks (reference indexer.rs
    OverlapScores)."""

    def __init__(self):
        self.scores: Dict[int, int] = {}
        self.frequencies: List[int] = []  # workers matching at each depth

    def __repr__(self):
        return f"OverlapScores({self.scores})"


#: stats() memory-estimate coefficients: rough CPython cost of one hash
#: entry (two dict slots + OrderedDict node + parent/children bookkeeping)
#: and one (hash, worker) set membership, measured-once constants, not
#: precise accounting — the point is that the estimate SCALES with the
#: index so an operator can alarm on it.
_BYTES_PER_BLOCK = 240
_BYTES_PER_MAPPING = 100


class RadixTree:
    """Flat chained-hash index with match-walk semantics
    (reference RadixTree indexer.rs:224).

    `max_blocks` bounds the index (docs/kv_cache_routing.md): when the
    cap is exceeded, LEAVES are evicted first in least-recently-matched
    order. Because hashes are chained, an interior block is exactly as
    useful as the deepest chain through it — evicting leaf-first means a
    capped index degrades from the deep (cold, most specific) end of each
    prefix chain while shared roots survive, and a match walk over the
    survivors still returns correct (merely shallower) overlap scores.
    Eviction drops the hash for ALL workers: it is routing metadata, not
    cache state — the worker still holds the block; the router just stops
    scoring it."""

    def __init__(self, max_blocks: Optional[int] = None):
        self._blocks: Dict[int, Set[int]] = defaultdict(set)  # hash -> workers
        self._worker_blocks: Dict[int, Set[int]] = defaultdict(set)  # worker -> hashes
        self.max_blocks = max_blocks if max_blocks and max_blocks > 0 else None
        # chain bookkeeping for leaf-first eviction: parent link per hash,
        # in-index children per hash, and leaves in least-recently-matched
        # order (OrderedDict as an O(1) recency list)
        self._parent: Dict[int, int] = {}
        self._children: Dict[int, Set[int]] = {}
        self._leaf_order: "OrderedDict[int, None]" = OrderedDict()
        self._mappings = 0  # live (hash, worker) pairs, for the mem estimate
        self.evicted_blocks = 0

    def apply_stored(self, worker_id: int, block_hashes: List[int],
                     chained: bool = True, parent: Optional[int] = None):
        """`chained=True` (live stored events): consecutive hashes are a
        contiguous chain, so each records the previous as its parent, and
        `parent` (the stored event's `parent_hash`) links the FIRST block
        to the chain it extends — without it, per-block stored events
        (one per generated block) would leave every block a root/leaf and
        leaf-first eviction would take the roots first.
        `chained=False` (snapshot restore via load(): dump() sorts hash
        sets, destroying chain order): no parent links are fabricated —
        restored blocks are all roots/leaves until live events re-chain
        them, degrading eviction quality, never correctness."""
        bounded = self.max_blocks is not None
        prev: Optional[int] = parent if chained else None
        for h in block_hashes:
            workers = self._blocks[h]
            if worker_id not in workers:
                workers.add(worker_id)
                self._worker_blocks[worker_id].add(h)
                self._mappings += 1
            if not bounded:
                continue  # chain/leaf bookkeeping only feeds eviction —
                # an uncapped tree skips its ~2x per-block overhead
            if h not in self._leaf_order and not self._children.get(h):
                self._leaf_order[h] = None
            if chained and prev is not None and h not in self._parent:
                self._parent[h] = prev
                self._children.setdefault(prev, set()).add(h)
                self._leaf_order.pop(prev, None)  # prev now interior
            prev = h
        self._maybe_evict()

    def _unlink(self, h: int):
        """Chain bookkeeping for a hash that left the index entirely:
        drop its leaf/parent entries, and re-leaf the parent (at the MRU
        end — it just proved useful by having had descendants) when `h`
        was its last in-index child."""
        self._leaf_order.pop(h, None)
        parent = self._parent.pop(h, None)
        if parent is not None:
            kids = self._children.get(parent)
            if kids is not None:
                kids.discard(h)
                if not kids:
                    del self._children[parent]
                    if parent in self._blocks:
                        self._leaf_order[parent] = None

    def _drop_hash(self, h: int):
        """Remove `h` for every holder + all chain bookkeeping."""
        workers = self._blocks.pop(h, None)
        if workers:
            for w in workers:
                wb = self._worker_blocks.get(w)
                if wb is not None:
                    wb.discard(h)
            self._mappings -= len(workers)
        self._unlink(h)

    def _maybe_evict(self):
        if self.max_blocks is None:
            return
        while len(self._blocks) > self.max_blocks:
            if self._leaf_order:
                victim = next(iter(self._leaf_order))
            else:
                # no known leaf (stale bookkeeping) — never wedge the cap
                victim = next(iter(self._blocks))
            self._drop_hash(victim)
            self.evicted_blocks += 1

    def _forget_for_worker(self, worker_id: int, h: int):
        workers = self._blocks.get(h)
        if workers and worker_id in workers:
            workers.discard(worker_id)
            self._mappings -= 1
            if not workers:
                self._blocks.pop(h, None)
                self._unlink(h)  # fully gone: same cleanup as an eviction

    def apply_removed(self, worker_id: int, block_hashes: List[int]):
        for h in block_hashes:
            self._forget_for_worker(worker_id, h)
            self._worker_blocks[worker_id].discard(h)

    def remove_worker(self, worker_id: int):
        """Worker died: drop all its blocks (reference remove_worker)."""
        for h in self._worker_blocks.pop(worker_id, set()):
            self._forget_for_worker(worker_id, h)

    def clear_all_blocks(self, worker_id: int):
        self.remove_worker(worker_id)

    def find_matches(self, seq_hashes: List[int], early_exit: bool = False) -> OverlapScores:
        """Walk the prefix; a worker scores i+1 if it holds blocks 0..i
        contiguously (reference find_matches indexer.rs:276)."""
        result = OverlapScores()
        active: Optional[Set[int]] = None
        for depth, h in enumerate(seq_hashes):
            holders = self._blocks.get(h)
            if not holders:
                break
            if self.max_blocks is not None and h in self._leaf_order:
                # matched leaves are hot: refresh their eviction recency
                self._leaf_order.move_to_end(h)
            active = set(holders) if active is None else (active & holders)
            if not active:
                break
            result.frequencies.append(len(active))
            for w in active:
                result.scores[w] = depth + 1
            if early_exit and len(active) == 1:
                break
        return result

    @property
    def num_blocks(self) -> int:
        return len(self._blocks)

    def memory_bytes_estimate(self) -> int:
        """Order-of-magnitude resident cost of the index (docs note in
        kv_cache_routing.md: an alarmable scale signal, not an accountant)."""
        return (
            _BYTES_PER_BLOCK * len(self._blocks)
            + _BYTES_PER_MAPPING * self._mappings
        )

    def stats(self) -> dict:
        return {
            "index_blocks": len(self._blocks),
            "index_max_blocks": self.max_blocks or 0,
            "index_evicted_blocks": self.evicted_blocks,
            "index_mappings": self._mappings,
            "index_memory_bytes_estimate": self.memory_bytes_estimate(),
        }

    def worker_block_count(self, worker_id: int) -> int:
        return len(self._worker_blocks.get(worker_id, ()))

    def workers(self) -> List[int]:
        return list(self._worker_blocks.keys())

    def dump(self) -> dict:
        """Snapshot for replica sync / persistence (reference snapshots to
        the object store)."""
        return {
            str(w): sorted(hs) for w, hs in self._worker_blocks.items() if hs
        }

    def load(self, snapshot: dict):
        # dump() sorts each worker's hash set — chain order is gone, so
        # restoring must NOT fabricate parent links (chained=False);
        # restored blocks are all leaves until live events re-chain them
        for w_str, hashes in snapshot.items():
            self.apply_stored(int(w_str), list(hashes), chained=False)


EVENT_TOPIC_FMT = "kv_events/{namespace}/{component}"


ROUTER_SNAPSHOT_KEY_FMT = "v1/router_snapshots/{namespace}/{component}"


class KvIndexer:
    """Event-driven index: subscribes to the component's KV-event topic and
    applies stored/removed events to the RadixTree
    (reference KvIndexer indexer.rs + subscriber.rs).

    With `snapshot_threshold` set, the tree is persisted to the discovery
    KV (the reference's NATS object-store role, kv_cache_routing.md
    --router-snapshot-threshold) every N applied events and restored on
    start, so a restarted/added router replica syncs without replaying the
    whole event history. `reset_states` drops any stored snapshot instead
    (--router-reset-states)."""

    def __init__(
        self,
        drt: DistributedRuntime,
        namespace: str,
        component: str,
        block_size: int = 64,
        snapshot_threshold: Optional[int] = None,
        reset_states: bool = False,
        max_blocks: Optional[int] = None,
    ):
        from ...native import make_radix_tree

        self.drt = drt
        self.block_size = block_size
        self.topic = EVENT_TOPIC_FMT.format(namespace=namespace, component=component)
        self.snapshot_key = ROUTER_SNAPSHOT_KEY_FMT.format(
            namespace=namespace, component=component
        )
        self.snapshot_threshold = snapshot_threshold
        self.reset_states = reset_states
        if max_blocks is None:
            max_blocks = _index_cap_from_env()
        self.max_blocks = max_blocks
        # C++ index when built AND unbounded, else RadixTree (the cap's
        # leaf-first bookkeeping lives in the Python tree)
        self.tree = make_radix_tree(max_blocks=max_blocks)
        self._task: Optional[asyncio.Task] = None
        self._sub = None
        self.events_applied = 0
        self._events_at_snapshot = 0
        self._persist_task: Optional[asyncio.Task] = None

    async def start(self):
        assert self.drt.discovery is not None
        # subscribe BEFORE restoring: events arriving during the restore are
        # buffered in the subscription, not lost (load is additive)
        self._sub = await self.drt.discovery.subscribe(self.topic)
        if self.reset_states:
            await self.drt.discovery.delete(self.snapshot_key)
        elif self.snapshot_threshold is not None:
            await self._restore_snapshot()
        self._task = asyncio.create_task(self._loop())

    async def _restore_snapshot(self):
        raw = await self.drt.discovery.get(self.snapshot_key)
        if not raw:
            return
        try:
            self.tree.load(json.loads(raw))
            logger.info("restored router snapshot (%d blocks)", self.tree.num_blocks)
        except Exception:  # noqa: BLE001 — corrupt snapshot: start cold
            logger.exception("router snapshot restore failed; starting cold")

    def _start_persist_snapshot(self):
        """Dump the tree inline (consistent point-in-time view), then encode
        and upload off the event-apply hot path."""
        if self._persist_task is not None and not self._persist_task.done():
            return  # previous upload still in flight; next threshold retries
        snapshot = self.tree.dump()
        self._events_at_snapshot = self.events_applied

        async def upload():
            try:
                loop = asyncio.get_running_loop()
                raw = await loop.run_in_executor(
                    None, lambda: json.dumps(snapshot).encode()
                )
                await self.drt.discovery.put(self.snapshot_key, raw)
            except Exception:  # noqa: BLE001
                logger.exception("router snapshot persist failed")

        self._persist_task = asyncio.create_task(upload())

    async def _loop(self):
        from ...runtime import codec

        async for payload in self._sub:
            try:
                msg = codec.unpack(payload)
                worker_id = msg["worker_id"]
                for ev in msg.get("events", []):
                    if ev.get("event_type") == "stored":
                        self.tree.apply_stored(
                            worker_id, ev["block_hashes"],
                            parent=ev.get("parent_hash"),
                        )
                    elif ev.get("event_type") == "removed":
                        self.tree.apply_removed(worker_id, ev["block_hashes"])
                    elif ev.get("event_type") == "cleared":
                        self.tree.clear_all_blocks(worker_id)
                    self.events_applied += 1
                if (
                    self.snapshot_threshold is not None
                    and self.events_applied - self._events_at_snapshot
                    >= self.snapshot_threshold
                ):
                    self._start_persist_snapshot()
            except Exception:  # noqa: BLE001 — indexer must survive bad events
                logger.exception("bad kv event")

    def find_matches_for_tokens(self, token_ids: List[int]) -> OverlapScores:
        return self.find_matches_for_hashes(
            compute_seq_hashes(token_ids, self.block_size)
        )

    def find_matches_for_hashes(self, hashes: List[int]) -> OverlapScores:
        return self.tree.find_matches(hashes)

    def remove_worker(self, worker_id: int):
        self.tree.remove_worker(worker_id)

    def stats(self) -> dict:
        out = {"events_applied": self.events_applied}
        tree_stats = getattr(self.tree, "stats", None)
        if tree_stats is not None:
            out.update(tree_stats())
        else:  # native tree: block count only
            out["index_blocks"] = self.tree.num_blocks
        return out

    async def close(self):
        if self._task:
            self._task.cancel()
        if self._persist_task is not None and not self._persist_task.done():
            try:
                await self._persist_task
            except Exception:  # noqa: BLE001
                pass
        if self._sub:
            await self._sub.cancel()


class KvIndexerSharded:
    """N independent trees, workers assigned by worker_id modulo shards;
    lookups fan out and merge (reference KvIndexerSharded indexer.rs:992 —
    bounds per-trie size and contention for large fleets)."""

    def __init__(self, num_shards: int = 4, block_size: int = 64,
                 max_blocks: Optional[int] = None):
        from ...native import make_radix_tree

        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.block_size = block_size
        if max_blocks is None:
            max_blocks = _index_cap_from_env()
        self.max_blocks = max_blocks
        # shards hold disjoint workers, so the global cap splits evenly
        # (ceil: the sum may exceed max_blocks by < num_shards)
        per_shard = (
            -(-max_blocks // num_shards) if max_blocks is not None else None
        )
        self.shards = [
            make_radix_tree(max_blocks=per_shard) for _ in range(num_shards)
        ]

    def _shard(self, worker_id: int):
        return self.shards[worker_id % len(self.shards)]

    def apply_stored(self, worker_id: int, block_hashes: List[int],
                     chained: bool = True, parent: Optional[int] = None):
        self._shard(worker_id).apply_stored(
            worker_id, block_hashes, chained=chained, parent=parent
        )

    def apply_removed(self, worker_id: int, block_hashes: List[int]):
        self._shard(worker_id).apply_removed(worker_id, block_hashes)

    def clear_all_blocks(self, worker_id: int):
        self._shard(worker_id).clear_all_blocks(worker_id)

    def remove_worker(self, worker_id: int):
        self._shard(worker_id).remove_worker(worker_id)

    def find_matches(self, seq_hashes: List[int], early_exit: bool = False) -> OverlapScores:
        merged = OverlapScores()
        for shard in self.shards:
            r = shard.find_matches(seq_hashes, early_exit=early_exit)
            merged.scores.update(r.scores)
            # frequencies[d] counts workers matching at depth d; shards hold
            # disjoint workers, so merge is an element-wise sum
            if len(r.frequencies) > len(merged.frequencies):
                merged.frequencies.extend(
                    [0] * (len(r.frequencies) - len(merged.frequencies))
                )
            for d, f in enumerate(r.frequencies):
                merged.frequencies[d] += f
        return merged

    def find_matches_for_tokens(self, token_ids: List[int]) -> OverlapScores:
        return self.find_matches(compute_seq_hashes(token_ids, self.block_size))

    @property
    def num_blocks(self) -> int:
        return sum(s.num_blocks for s in self.shards)

    def stats(self) -> dict:
        out: dict = {"index_blocks": 0, "index_max_blocks": self.max_blocks or 0}
        for s in self.shards:
            shard_stats = getattr(s, "stats", None)
            if shard_stats is None:
                out["index_blocks"] += s.num_blocks
                continue
            for k, v in shard_stats().items():
                if k == "index_max_blocks":
                    continue
                out[k] = out.get(k, 0) + v
        return out

    def workers(self) -> List[int]:
        out: List[int] = []
        for s in self.shards:
            out.extend(s.workers())
        return out

    def dump(self) -> dict:
        merged: dict = {}
        for s in self.shards:
            merged.update(s.dump())
        return merged

    def load(self, snapshot: dict):
        # route through each shard's own load: the sorted snapshot must
        # not be re-interpreted as chains (Python tree), and a native
        # shard's plain apply_stored is chain-free anyway
        for w_str, hashes in snapshot.items():
            self._shard(int(w_str)).load({w_str: list(hashes)})


class ApproxKvIndexer:
    """Indexer that needs no engine events: assumes a routed request's prefix
    becomes cached on the chosen worker for a TTL
    (reference ApproxKvIndexer approx.rs)."""

    def __init__(self, block_size: int = 64, ttl: float = 120.0):
        from ...native import make_radix_tree

        self.block_size = block_size
        self.ttl = ttl
        self.tree = make_radix_tree()
        self._expiry: List[tuple] = []  # (deadline, worker_id, hashes)
        # refcount per (worker, hash): a hot prefix re-routed inside the
        # TTL appends a SECOND expiry entry — without counts, the OLDER
        # entry's expiry would erase the still-valid refresh
        self._refs: dict = {}

    def process_routing_decision_for_request(self, token_ids: List[int], worker_id: int):
        self.apply_routed_hashes(
            compute_seq_hashes(token_ids, self.block_size), worker_id
        )

    def apply_routed_hashes(self, hashes: List[int], worker_id: int):
        import time

        self.tree.apply_stored(worker_id, hashes)
        for h in hashes:
            key = (worker_id, h)
            self._refs[key] = self._refs.get(key, 0) + 1
        self._expiry.append((time.monotonic() + self.ttl, worker_id, hashes))
        self._expire()

    def _expire(self):
        import time

        now = time.monotonic()
        while self._expiry and self._expiry[0][0] < now:
            _, worker_id, hashes = self._expiry.pop(0)
            dead = []
            for h in hashes:
                key = (worker_id, h)
                n = self._refs.get(key, 1) - 1
                if n <= 0:
                    self._refs.pop(key, None)
                    dead.append(h)
                else:
                    self._refs[key] = n
            if dead:
                self.tree.apply_removed(worker_id, dead)

    def find_matches_for_tokens(self, token_ids: List[int]) -> OverlapScores:
        return self.find_matches_for_hashes(
            compute_seq_hashes(token_ids, self.block_size)
        )

    def find_matches_for_hashes(self, hashes: List[int]) -> OverlapScores:
        self._expire()
        return self.tree.find_matches(hashes)

    def remove_worker(self, worker_id: int):
        self.tree.remove_worker(worker_id)
        self._refs = {k: v for k, v in self._refs.items() if k[0] != worker_id}
        self._expiry = [e for e in self._expiry if e[1] != worker_id]
