"""Token block hashing — the shared currency of KV reuse.

Mirrors reference lib/llm/src/tokens.rs: tokens are grouped into fixed-size
blocks; each block's hash chains the parent block's hash (xxh3, :21-44),
giving a `SequenceHash` that identifies the exact prefix ending at that
block. The router's radix index, the engine's prefix cache, and the KVBM
registry all key on these hashes, so the scheme must be identical everywhere
(SURVEY.md hard part (c)).

Hash: xxh3_64(le_bytes(tokens), seed=parent_hash) — parent of the first
block is the salt hash (xxh3_64 of salt bytes, seed=0).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Sequence

import xxhash

DEFAULT_BLOCK_SIZE = 64
NULL_PARENT = 0

# resolve the native C++ core ONCE at import (process startup): the first
# _load() may run `make`, which must never happen on the serving path
try:
    from ..native import native_available as _native_available
    from ..native import compute_seq_hashes as _native_seq_hashes

    _NATIVE = _native_available()
except Exception:  # noqa: BLE001 — any native failure falls back to Python
    _NATIVE = False


def salt_hash(salt: bytes = b"") -> int:
    """Per-model/per-tenant salt (reference SaltHash tokens.rs:30)."""
    return xxhash.xxh3_64_intdigest(salt)


def compute_block_hash(tokens: Sequence[int], parent_hash: int = NULL_PARENT) -> int:
    """Chained block hash (reference compute_hash_v2 tokens.rs:36)."""
    data = struct.pack(f"<{len(tokens)}I", *[t & 0xFFFFFFFF for t in tokens])
    return xxhash.xxh3_64_intdigest(data, seed=parent_hash & 0xFFFFFFFFFFFFFFFF)


def compute_seq_hashes(
    tokens: Sequence[int],
    block_size: int = DEFAULT_BLOCK_SIZE,
    salt: int = NULL_PARENT,
) -> List[int]:
    """Sequence hashes of every COMPLETE block of `tokens`.

    Dispatches to the native C++ core (csrc/dynamo_core.cpp) when built;
    the fallback below is the semantic definition (parity-tested)."""
    if _NATIVE and len(tokens) >= block_size:
        return _native_seq_hashes(tokens, block_size, salt)
    hashes: List[int] = []
    parent = salt
    for start in range(0, len(tokens) - block_size + 1, block_size):
        parent = compute_block_hash(tokens[start : start + block_size], parent)
        hashes.append(parent)
    return hashes


@dataclass
class TokenBlock:
    """One complete block with its chained hash (reference TokenBlock)."""

    tokens: List[int]
    block_hash: int
    parent_hash: int
    position: int  # block index in the sequence


class TokenBlockSequence:
    """Incrementally maintained blocked token sequence
    (reference TokenBlockSequence tokens.rs:388).

    Supports append (token-at-a-time or extend) while keeping complete-block
    hashes chained; used by engine-side KV bookkeeping and the mocker.
    """

    def __init__(
        self,
        tokens: Optional[Iterable[int]] = None,
        block_size: int = DEFAULT_BLOCK_SIZE,
        salt: int = NULL_PARENT,
    ):
        self.block_size = block_size
        self.salt = salt
        self.blocks: List[TokenBlock] = []
        self._partial: List[int] = []
        if tokens:
            self.extend(tokens)

    def __len__(self) -> int:
        return len(self.blocks) * self.block_size + len(self._partial)

    @property
    def tokens(self) -> List[int]:
        out: List[int] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self._partial)
        return out

    @property
    def partial_tokens(self) -> List[int]:
        return list(self._partial)

    def block_hashes(self) -> List[int]:
        return [b.block_hash for b in self.blocks]

    def last_hash(self) -> int:
        return self.blocks[-1].block_hash if self.blocks else self.salt

    def append(self, token: int):
        self._partial.append(token)
        if len(self._partial) == self.block_size:
            parent = self.last_hash()
            h = compute_block_hash(self._partial, parent)
            self.blocks.append(
                TokenBlock(self._partial, h, parent, len(self.blocks))
            )
            self._partial = []

    def extend(self, tokens: Iterable[int]):
        for t in tokens:
            self.append(t)

    def truncate(self, num_tokens: int):
        """Drop tokens beyond `num_tokens` (used on migration re-issue)."""
        if num_tokens >= len(self):
            return
        all_tokens = self.tokens[:num_tokens]
        self.blocks = []
        self._partial = []
        self.extend(all_tokens)
