"""Multimodal E/P/D support: the encode hop.

Reference flow (components/backends/trtllm/multimodal_epd.md +
multimodal_processor.py): an ENCODE worker turns image/audio content
parts into embedding tensors; the processor inserts placeholder tokens
into the prompt at each part's position; the prefill engine replaces the
placeholders' embedding rows with the encoder output
(engine._prefill_batch_mm); decode proceeds normally.

Two deliberate TPU-build choices:

  * Two encoders behind the same endpoint: ViTEncoder — a real JAX ViT
    (models/vit.py, HF-checkpoint loadable) with a LLaVA-style projector
    — and MockVisionEncoder, a deterministic content-hash projection the
    tests use (no weights to distribute). `encode_parts` takes either.
  * Placeholder token ids are CONTENT-DERIVED pseudo-tokens: two
    different images produce different placeholder ids, so KV block
    hashes (and with them the KV router's prefix scoring and the
    engine's prefix cache) distinguish images, while identical images
    still reuse cached KV. Constant placeholders would alias every
    image to the same prefix.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
from typing import Any, Dict, List, Tuple

import numpy as np

from ..runtime import StreamLost

logger = logging.getLogger(__name__)

__all__ = [
    "MockVisionEncoder",
    "ViTEncoder",
    "encode_parts",
    "part_content_key",
    "placeholder_tokens",
    "splice_placeholders",
]

DEFAULT_MM_TOKENS = 4


def part_content_key(part: Dict[str, Any]) -> bytes:
    """Stable identity of a content part (url or inline payload)."""
    ident = part.get("url") or part.get("data") or part.get("input_audio") or ""
    return hashlib.sha256(
        f"{part.get('type')}:{ident}".encode("utf-8", "replace")
    ).digest()


class MockVisionEncoder:
    """Deterministic stand-in encoder: content hash seeds a fixed random
    projection to [n_tokens, hidden]. Same image -> same embeddings on
    every host (no weights to distribute)."""

    def __init__(self, hidden_size: int, n_tokens: int = DEFAULT_MM_TOKENS):
        self.hidden_size = hidden_size
        self.n_tokens = n_tokens

    def encode(self, part: Dict[str, Any]) -> np.ndarray:
        seed = int.from_bytes(part_content_key(part)[:4], "little")
        rng = np.random.RandomState(seed)
        # small magnitude: comparable to embedding-table rows so the
        # splice doesn't blow out activation scales
        return (rng.randn(self.n_tokens, self.hidden_size) * 0.02).astype(
            np.float32
        )


class ViTEncoder:
    """Real vision encoder (models/vit.py): image content part → jitted
    JAX ViT → LLaVA-style projector → [n_patches, llm_hidden] rows for
    the engine splice. Accepts data: URLs, inline base64 payloads, or raw
    pixel arrays; plain http(s) URLs are rejected (zero-egress builds
    must not silently hang on fetches).

    Reference analogue: the HF vision tower the trtllm multimodal
    processor runs (components/backends/trtllm/src/dynamo/trtllm/
    multimodal_processor.py) — here on the TPU's MXU."""

    def __init__(self, config=None, params=None, llm_hidden: int = None,
                 checkpoint: str = None):
        import jax
        import jax.numpy as jnp

        from ..models import vit

        if config is None:
            config = vit.ViTConfig.tiny(
                out_hidden=llm_hidden or vit.ViTConfig.tiny().out_hidden
            )
        elif llm_hidden and config.out_hidden != llm_hidden:
            from dataclasses import replace

            config = replace(config, out_hidden=llm_hidden)
        self.config = config
        if params is None:
            if checkpoint:
                params = vit.load_vit_params(checkpoint, config)
            else:
                params = vit.init_params(config, jax.random.PRNGKey(0))
        self.params = params
        self.hidden_size = config.out_hidden
        self.n_tokens = config.n_patches
        self._fwd = jax.jit(
            lambda px: vit.encode_tokens(self.params, config, px)
        )
        self._jnp = jnp

    def _pixels(self, part: Dict[str, Any]) -> np.ndarray:
        """Content part → normalized [C, H, W] float32 (HF layout,
        mean/std 0.5 — the ViTImageProcessor default)."""
        c = self.config
        raw = part.get("pixels")
        if raw is not None:
            arr = np.asarray(raw, np.float32)
            if arr.shape != (c.num_channels, c.image_size, c.image_size):
                raise ValueError(
                    f"pixels shape {arr.shape} != "
                    f"[{c.num_channels}, {c.image_size}, {c.image_size}]"
                )
            return arr
        url = part.get("url") or ""
        data = part.get("data")
        if url.startswith("data:"):
            import base64

            b64 = url.split(",", 1)[1] if "," in url else ""
            data = base64.b64decode(b64)
        elif isinstance(data, str):
            import base64

            data = base64.b64decode(data)
        if not data:
            raise ValueError(
                "image part carries no decodable payload (data: URL, "
                "inline base64 `data`, or `pixels`); remote fetch is "
                "disabled on zero-egress deployments"
            )
        import io

        from PIL import Image

        img = Image.open(io.BytesIO(data)).convert("RGB")
        img = img.resize((c.image_size, c.image_size), Image.BILINEAR)
        arr = np.asarray(img, np.float32) / 255.0  # [H, W, C]
        arr = (arr - 0.5) / 0.5
        return arr.transpose(2, 0, 1)

    def encode(self, part: Dict[str, Any]) -> np.ndarray:
        px = self._jnp.asarray(self._pixels(part)[None])
        return np.asarray(self._fwd(px)[0], np.float32)


def placeholder_tokens(part: Dict[str, Any], n_tokens: int, vocab_size: int) -> List[int]:
    """Content-derived pseudo-token ids for one part (see module docstring).
    Ids land in [2, vocab) to dodge special tokens at 0/1."""
    key = part_content_key(part)
    stretched = hashlib.sha256(key + b"tokens").digest()
    span = max(vocab_size - 2, 1)
    return [
        2 + int.from_bytes(stretched[(2 * i) % 30 : (2 * i) % 30 + 2], "little") % span
        for i in range(n_tokens)
    ]


def splice_placeholders(
    token_ids: List[int],
    parts: List[Dict[str, Any]],
    n_tokens: int,
    vocab_size: int,
) -> Tuple[List[int], List[Dict[str, Any]]]:
    """Append each part's placeholder span to the prompt and record its
    position on the part (the chat template flattens text parts, so parts
    anchor after the rendered prompt, in request order — the reference
    anchors at the model's image-token markers instead)."""
    out = list(token_ids)
    stamped = []
    for part in parts:
        p = dict(part)
        p["position"] = len(out)
        p["n_tokens"] = n_tokens
        out.extend(placeholder_tokens(part, n_tokens, vocab_size))
        stamped.append(p)
    return out, stamped


def encode_parts(
    parts: List[Dict[str, Any]], encoder: MockVisionEncoder
) -> List[Dict[str, Any]]:
    """Worker-side: attach embeddings to each part (wire format: nested
    lists — msgpack-clean; the engine re-materializes np arrays)."""
    out = []
    for part in parts:
        p = dict(part)
        p["embedding"] = encoder.encode(part).tolist()
        p["n_tokens"] = encoder.n_tokens
        out.append(p)
    return out


class EncodeOperator:
    """Pipeline forward hop (runtime/pipeline.py Operator): the processor
    side of E/P/D. For requests carrying multimodal parts, calls the
    encode worker, then splices placeholder tokens + embeddings into the
    request BEFORE the router hop — so KV-aware routing and the engine
    prefix cache see the content-derived placeholder ids."""

    def __init__(self, router, vocab_size: int, max_attempts: int = 3,
                 retry_delay_s: float = 2.0):
        self.router = router  # PushRouter over the encode endpoint
        self.vocab_size = vocab_size
        self.max_attempts = max_attempts
        self.retry_delay_s = retry_delay_s

    @property
    def name(self) -> str:
        return "Encode"

    async def forward(self, request: Any, context) -> Any:
        is_dict = isinstance(request, dict)
        mm = request.get("multimodal") if is_dict else request.multimodal
        if not mm:
            return request
        if all(p.get("embedding") is not None and p.get("position") is not None
               for p in mm):
            return request  # already encoded (disagg/migration re-entry)
        encoded, n_tokens = None, DEFAULT_MM_TOKENS
        # the engine hop gets retries from the Migration operator; the
        # encode hop sits ABOVE it, so a restarting encode pool (brief
        # zero-instance window) must be ridden out here
        last_exc: Exception | None = None
        for attempt in range(self.max_attempts):
            if attempt:
                await asyncio.sleep(self.retry_delay_s)
                # cancelled/killed requests must not keep hammering a
                # recovering encode pool (mirrors migration.py's guard)
                if context is not None and (
                    context.is_stopped() or context.is_killed()
                ):
                    raise last_exc
            try:
                stream = await self.router.generate(
                    {"multimodal": list(mm)}, context
                )
                async for item in stream:
                    d = item.get("data") if isinstance(item, dict) else None
                    if d and "multimodal" in d:
                        encoded = d["multimodal"]
                        n_tokens = int(d.get("n_tokens") or n_tokens)
                last_exc = None
                break
            except StreamLost as e:
                last_exc = e
                logger.warning(
                    "encode hop attempt %d/%d failed: %s",
                    attempt + 1, self.max_attempts, e,
                )
        if last_exc is not None:
            raise last_exc
        if encoded is None:
            raise RuntimeError("encode worker returned no embeddings")
        token_ids = request["token_ids"] if is_dict else request.token_ids
        new_ids, stamped = splice_placeholders(
            token_ids, encoded, n_tokens, self.vocab_size
        )
        if is_dict:
            request = dict(request, token_ids=new_ids, multimodal=stamped)
        else:
            request.token_ids = new_ids
            request.multimodal = stamped
        return request

    # Operator protocol: pass-through backward, no around
    def backward(self, stream, request, context):
        return stream

    def around(self, next_engine, request, context):
        return None
