"""Block-level KV accounting for the mock engine.

Mirrors reference lib/llm/src/mocker/kv_manager.rs (KvManager :45): a fixed
pool of KV blocks with prefix caching (sequence-hash keyed), reference
counting, LRU eviction of unreferenced blocks at a watermark, and KV events
(stored/removed) emitted exactly like a real engine so the router's radix
index sees realistic traffic.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set


@dataclass
class KvEvent:
    """BlockStored/BlockRemoved event (reference kv_router/protocols.rs)."""

    event_type: str  # "stored" | "removed"
    block_hashes: List[int]
    parent_hash: Optional[int] = None
    token_blocks: Optional[List[List[int]]] = None  # stored only
    ts: float = field(default_factory=time.time)

    def to_dict(self) -> dict:
        d = {"event_type": self.event_type, "block_hashes": self.block_hashes}
        if self.parent_hash is not None:
            d["parent_hash"] = self.parent_hash
        if self.token_blocks is not None:
            d["token_blocks"] = self.token_blocks
        return d


def stored_event_runs(
    seq_hashes: List[int],
    new_hashes: Set[int],
    token_blocks: Optional[List[List[int]]] = None,
    parent_of_first: Optional[int] = None,
) -> List[KvEvent]:
    """Split the newly stored subset of a chained sequence into one
    `stored` event per CONTIGUOUS run, each carrying the run's true chain
    parent (the seq_hashes element just before it) and its aligned
    token_blocks slice. A commit can skip hashes a concurrent sequence
    already cached, and a single gapped event would make the router's
    bounded index fabricate parent links across the gap — this is the
    single spelling of the contract for BOTH producers (the engine's
    PageAllocator.commit_hashes and the mocker's KvManager.acquire)."""
    runs: List[dict] = []
    run: Optional[dict] = None
    prev = parent_of_first
    for i, h in enumerate(seq_hashes):
        if h in new_hashes:
            if run is None:
                run = {"parent": prev, "hashes": [], "tb": []}
                runs.append(run)
            run["hashes"].append(h)
            if token_blocks is not None and i < len(token_blocks):
                run["tb"].append(token_blocks[i])
        else:
            run = None
        prev = h
    return [
        KvEvent("stored", r["hashes"], parent_hash=r["parent"],
                token_blocks=r["tb"] or None)
        for r in runs
    ]


@dataclass
class _Block:
    seq_hash: int
    ref_count: int = 0


class KvManager:
    """Fixed-capacity block pool with prefix reuse (reference kv_manager.rs:45)."""

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        event_sink: Optional[Callable[[KvEvent], None]] = None,
        watermark: float = 0.01,
    ):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.event_sink = event_sink
        self.watermark_blocks = max(1, int(num_blocks * watermark))
        self._active: Dict[int, _Block] = {}  # seq_hash -> block (ref'd or cached)
        self._lru: OrderedDict[int, None] = OrderedDict()  # unreferenced, evictable
        self._used = 0

    # -- capacity ----------------------------------------------------------- #

    @property
    def used_blocks(self) -> int:
        return self._used

    @property
    def free_blocks(self) -> int:
        """Blocks allocatable right now (free + evictable)."""
        return self.num_blocks - self._used + len(self._lru)

    @property
    def active_blocks(self) -> int:
        return self._used - len(self._lru)

    def usage_perc(self) -> float:
        return self.active_blocks / self.num_blocks

    # -- queries ------------------------------------------------------------ #

    def cached_prefix_blocks(self, seq_hashes: List[int]) -> int:
        """How many leading blocks of this sequence are already resident."""
        n = 0
        for h in seq_hashes:
            if h in self._active:
                n += 1
            else:
                break
        return n

    def can_allocate(self, seq_hashes: List[int], extra_blocks: int = 0) -> bool:
        new_needed = sum(1 for h in seq_hashes if h not in self._active) + extra_blocks
        return new_needed <= self.num_blocks - self._used + len(self._lru) - self.watermark_blocks

    # -- allocation --------------------------------------------------------- #

    def acquire(
        self,
        seq_hashes: List[int],
        token_blocks: Optional[List[List[int]]] = None,
        parent_of_first: Optional[int] = None,
    ) -> bool:
        """Reference (and create if needed) blocks for the given sequence
        hashes. Emits `stored` events for newly created blocks."""
        new_hashes = [h for h in seq_hashes if h not in self._active]
        if len(new_hashes) > self.num_blocks - self._used + len(self._lru):
            return False
        # evict as needed
        while self._used + len(new_hashes) > self.num_blocks and self._lru:
            self._evict_one()
        created: Set[int] = set()
        for h in seq_hashes:
            blk = self._active.get(h)
            if blk is None:
                blk = _Block(seq_hash=h, ref_count=0)
                self._active[h] = blk
                self._used += 1
                created.add(h)
            if blk.ref_count == 0:
                self._lru.pop(h, None)
            blk.ref_count += 1
        if created and self.event_sink:
            for ev in stored_event_runs(
                seq_hashes, created, token_blocks, parent_of_first
            ):
                self.event_sink(ev)
        return True

    def release(self, seq_hashes: List[int]):
        """Drop references; unreferenced blocks go to the LRU (still cached
        for prefix reuse until evicted)."""
        for h in seq_hashes:
            blk = self._active.get(h)
            if blk is None:
                continue
            blk.ref_count -= 1
            if blk.ref_count <= 0:
                blk.ref_count = 0
                self._lru[h] = None
                self._lru.move_to_end(h)

    def _evict_one(self):
        h, _ = self._lru.popitem(last=False)
        self._active.pop(h, None)
        self._used -= 1
        if self.event_sink:
            self.event_sink(KvEvent("removed", [h]))

    def clear_cache(self) -> int:
        """Evict all unreferenced blocks (reference clear-kv-blocks route)."""
        n = 0
        while self._lru:
            self._evict_one()
            n += 1
        return n
