"""Mock engine: a fake TPU engine with real scheduling + KV accounting.

Mirrors reference lib/llm/src/mocker/: `MockVllmEngine` (engine.rs:48),
`Scheduler` (scheduler.rs:240) with continuous batching, chunked prefill,
prefix caching, and watermark eviction; `MockEngineArgs` (protocols.rs:67).

The mocker emits REAL KV events and realistic timing (scaled by
`speedup_ratio`), so the KV router, disaggregation flow, migration and
planner can all be exercised on CPU-only CI (SURVEY.md §4 takeaway).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Dict, List, Optional

from ...engine.scheduler.policy import (
    _TENANT_DECAY,
    _TENANT_MAX,
    _TENANT_TIE_QUANTUM_S,
)
from ...engine.scheduler.sla import SlaConfig
from ...runtime import faults
from ...runtime.engine import Context
from ...runtime.metrics import (
    KV_ACTIVE_BLOCKS,
    KV_TOTAL_BLOCKS,
    NUM_RUNNING_REQS,
    NUM_WAITING_REQS,
    SCHED_EST_DECODE_TOK_S,
    SCHED_EST_PREFILL_TOK_S,
    SCHED_EST_REQ_MS,
    SCHED_EST_TTFT_MS,
)
from ...runtime.request_plane import StreamSevered
from ..protocols import Annotated, LLMEngineOutput, PreprocessedRequest
from ..tokens import DEFAULT_BLOCK_SIZE, TokenBlockSequence, compute_seq_hashes
from .kv_manager import KvEvent, KvManager

logger = logging.getLogger(__name__)


@dataclass
class MockEngineArgs:
    """Reference MockEngineArgs protocols.rs:67."""

    model_name: str = "mock-model"
    num_gpu_blocks: int = 4096
    block_size: int = DEFAULT_BLOCK_SIZE
    max_num_seqs: int = 256
    max_num_batched_tokens: int = 8192
    enable_prefix_caching: bool = True
    enable_chunked_prefill: bool = True
    speedup_ratio: float = 1.0
    # synthetic timing model (seconds)
    prefill_time_per_token: float = 25e-6
    decode_time_per_step: float = 8e-3
    decode_time_per_seq: float = 60e-6
    vocab_size: int = 32000
    # SLA-aware scheduling (engine/scheduler/sla.py): None = resolve from
    # the DYN_SCHED_POLICY / DYN_SLA_TTFT_MS / DYN_SLA_ITL_MS env knobs.
    # "fifo" keeps the reference scheduler bit-for-bit; "sla" orders
    # admission+prefill by TTFT deadline (EDF) and caps the per-step
    # prefill budget so the synthetic decode cadence holds the ITL target
    # — the same policy the JaxEngine's StepPlanner applies, priced by
    # the mocker's own timing model instead of the EWMA cost model.
    sched_policy: Optional[str] = None
    ttft_target_ms: Optional[float] = None
    itl_target_ms: Optional[float] = None
    # serving role (docs/autoscaling.md "Role morphing"): which discovery
    # component this engine's worker registers under. "both" = colocated
    # (one worker serves prefill AND decode at low traffic). Flipped live
    # by MockEngine.morph().
    role: str = "decode"


@dataclass
class _MockRequest:
    request_id: str
    prompt: List[int]
    max_tokens: int
    eos_token_ids: List[int]
    ignore_eos: bool
    queue: asyncio.Queue
    context: Context
    seq: TokenBlockSequence = None  # type: ignore[assignment]
    prefill_pos: int = 0  # tokens prefilled so far
    generated: int = 0
    held_hashes: List[int] = field(default_factory=list)
    done: bool = False
    decode_only: bool = False  # disagg: KV assumed transferred in
    priority: int = 0
    sched_deadline: float = 0.0  # EDF key (monotonic s; sla policy only)
    tenant: str = ""  # dynogate fairness key (docs/overload.md)


class MockEngine:
    """Continuous-batching mock engine (reference MockVllmEngine engine.rs:48).

    `generate(request, context)` returns an async stream of Annotated
    LLMEngineOutput; a background step loop does prefill (chunked) and
    decode with synthetic timing.
    """

    def __init__(
        self,
        args: Optional[MockEngineArgs] = None,
        event_sink: Optional[Callable[[KvEvent], None]] = None,
    ):
        self.args = args or MockEngineArgs()
        self.kv = KvManager(
            self.args.num_gpu_blocks, self.args.block_size, event_sink=event_sink
        )
        self._waiting: List[_MockRequest] = []
        self._running: List[_MockRequest] = []
        self._step_task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self._closed = False
        self.num_requests = 0
        self.sla = SlaConfig.from_env(
            policy=self.args.sched_policy,
            ttft_target_ms=self.args.ttft_target_ms,
            itl_target_ms=self.args.itl_target_ms,
        )
        self.sched_deferred_steps = 0  # steps the ITL budget zeroed prefill
        self.sched_deadline_overrides = 0  # overdue requests that broke it
        # dynogate parity with StepPlanner (docs/overload.md): recent
        # prefill tokens per tenant — the EDF tiebreak prefers the
        # least-served tenant inside a ~100ms deadline bucket
        self._tenant_served: Dict[str, int] = {}
        # migration parity with JaxEngine stats (docs/fault_tolerance.md):
        # the mocker has no KVBM tiers, so every resume is a recompute —
        # but the soak/CI arms still assert the resume was COUNTED here
        self.migrations_resumed = 0
        self.resume_source_recompute = 0
        # live role morphing (docs/autoscaling.md "Role morphing"): the
        # serving role + state machine position, mutated only inside
        # morph() (GUARDED_STATE "MockEngine._role"/"._morph_state")
        self._role = self.args.role
        self._morph_state = "serving"
        self.morphs_completed = 0
        self.morphs_rolled_back = 0
        self.morph_drained_sessions = 0
        self.morph_last_duration_s = 0.0
        # fused-coverage parity with JaxEngine (docs/ragged_attention.md):
        # the mocker's step IS a fused prefill+decode step by
        # construction, so a step serving both kinds counts as mixed and
        # coverage is structurally 1.0 — gates reading any worker's
        # metrics see the same key set
        self.mixed_steps = 0
        self.split_steps = 0
        self.mixed_rows_plain = 0

    # -- lifecycle ---------------------------------------------------------- #

    def start(self):
        if self._step_task is None:
            self._step_task = asyncio.create_task(self._step_loop())

    async def close(self):
        self._closed = True
        self._wake.set()
        if self._step_task:
            self._step_task.cancel()

    async def warmup(self, extra_delay: float = 0.0) -> int:
        """Drive a few requests through the real step loop BEFORE the
        worker joins the control plane (the JaxEngine.warmup contract:
        first-iteration costs are paid pre-registration, never absorbed by
        live traffic). `extra_delay` simulates compile time so ordering
        tests can observe the not-yet-routable window."""
        n = 0
        for _ in range(2):
            req = PreprocessedRequest(
                token_ids=list(range(40, 56)),
                stop_conditions={"max_tokens": 4, "ignore_eos": True},
            ).to_dict()
            async for _ in self.generate(req, Context()):
                pass
            n += 1
        if extra_delay > 0:
            await asyncio.sleep(extra_delay)
        return n

    # -- public engine interface -------------------------------------------- #

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[dict]:
        if self._morph_state in ("draining-role", "flipped"):
            # mid-morph: refuse new streams the same way the drain cut the
            # in-flight ones — the server maps StreamSevered to a
            # `draining`-coded T_ERR, so the caller's migration machinery
            # re-routes instead of surfacing a terminal error. ("warm" is
            # admitted: the re-warm phase drives generate() itself.)
            raise StreamSevered(
                f"worker is morphing ({self._morph_state}); stream re-routed"
            )
        self.start()
        req = (
            request
            if isinstance(request, PreprocessedRequest)
            else PreprocessedRequest.from_dict(request)
        )
        stop = req.stop_conditions or {}
        disagg = req.disagg_params or {}
        mreq = _MockRequest(
            request_id=req.request_id or f"mock-{self.num_requests}",
            prompt=list(req.token_ids),
            max_tokens=int(stop.get("max_tokens") or 128),
            eos_token_ids=list(req.eos_token_ids or []),
            ignore_eos=bool(stop.get("ignore_eos")),
            queue=asyncio.Queue(),
            context=context,
            decode_only=bool(disagg.get("remote_prefill_done")),
        )
        mreq.seq = TokenBlockSequence(mreq.prompt, self.args.block_size)
        mreq.priority = int(req.priority or 0)
        mreq.tenant = req.tenant or ""
        if int(getattr(req, "migration", 0) or 0):
            self.migrations_resumed += 1
            self.resume_source_recompute += 1
        mreq.sched_deadline = self.sla.deadline(time.monotonic(), mreq.priority)
        self.num_requests += 1
        self._waiting.append(mreq)
        self._wake.set()
        try:
            while True:
                item = await mreq.queue.get()
                if item is None:
                    return
                if isinstance(item, Exception):
                    # _sever_all pushed a StreamSevered sentinel: raise it
                    # out of the handler so the request plane codes the
                    # T_ERR as `draining` and the caller migrates
                    raise item
                yield item
        finally:
            mreq.done = True
            self._wake.set()

    # -- stats (ForwardPassMetrics role) ------------------------------------ #

    def stats(self) -> dict:
        est_role = self.estimated_role_tok_s()
        return {
            NUM_WAITING_REQS: len(self._waiting),
            NUM_RUNNING_REQS: len(self._running),
            "gpu_cache_usage_perc": self.kv.usage_perc(),
            KV_ACTIVE_BLOCKS: self.kv.active_blocks,
            KV_TOTAL_BLOCKS: self.kv.num_blocks,
            "request_total_slots": self.args.max_num_seqs,
            "sched_policy": self.sla.policy,
            "sched_deferred_steps": self.sched_deferred_steps,
            "sched_deadline_overrides": self.sched_deadline_overrides,
            "migrations_resumed": self.migrations_resumed,
            "resume_source_recompute": self.resume_source_recompute,
            # dynogate signal parity with the JaxEngine (docs/overload.md):
            # the frontend admission gate projects TTFT from this gauge,
            # so the soak and CI smoke exercise the real gate without jax
            SCHED_EST_TTFT_MS: round(self.estimated_ttft_ms(), 1),
            # marginal cost of one MORE admitted request (the gate's
            # optimism-debt unit between 0.25s metric publishes — without
            # it a one-window burst floods past the published estimate)
            SCHED_EST_REQ_MS: round(self.estimated_req_ms(), 1),
            # role-morph telemetry (docs/autoscaling.md "Role morphing"):
            # per-role marginal throughput prices the planner's re-role
            # arm; the role/state gauges make a flip observable
            SCHED_EST_PREFILL_TOK_S: round(est_role["prefill"], 1),
            SCHED_EST_DECODE_TOK_S: round(est_role["decode"], 1),
            "engine_role": self._role,
            "morph_state": self._morph_state,
            "morphs_completed": self.morphs_completed,
            "morphs_rolled_back": self.morphs_rolled_back,
            "morph_drained_sessions": self.morph_drained_sessions,
            "morph_last_duration_s": round(self.morph_last_duration_s, 3),
            # fused-coverage parity (see __init__): structurally fused
            "mixed_steps": self.mixed_steps,
            "split_steps": self.split_steps,
            "mixed_rows_plain": self.mixed_rows_plain,
            "mixed_coverage_frac": 1.0,
        }

    def estimated_req_ms(self) -> float:
        """Marginal TTFT one more admitted request adds: with every slot
        busy, each queued admission adds one FULL request drain spread
        across the slots."""
        a = self.args
        occupied = len(self._running) + len(self._waiting)
        if occupied < a.max_num_seqs or not occupied:
            return 0.0  # truly free slots: an admission costs no queue wait
        speed = max(a.speedup_ratio, 1e-9)
        per_step = (
            a.decode_time_per_step
            + a.max_num_seqs * a.decode_time_per_seq
        ) / speed
        full = [max(r.max_tokens, 1) for r in [*self._running, *self._waiting]]
        mean_req_s = (sum(full) / len(full)) * per_step
        return mean_req_s / max(a.max_num_seqs, 1) * 1000.0

    def estimated_ttft_ms(self) -> float:
        """Projected TTFT for one more arriving request, priced by the
        mocker's own synthetic timing model (the mocker's spelling of
        JaxEngine.estimated_prefill_wait_ms): pending prefill tokens at
        the prefill rate, plus — when every slot is taken — the slot wait
        until the decode work AHEAD of the newcomer drains: the running
        requests' remaining steps plus every queued request's FULL
        service time, spread across the slots."""
        a = self.args
        speed = max(a.speedup_ratio, 1e-9)
        pending_tokens = sum(
            max(len(r.prompt) - r.prefill_pos, 0)
            for r in [*self._waiting, *self._running]
            if not r.done and not r.decode_only
        )
        est_s = pending_tokens * a.prefill_time_per_token / speed
        # slot wait: a waiting queue means every momentarily-free slot is
        # already spoken for — the term must not collapse to zero in the
        # instant between a finish and the next admission step (the gate
        # would read that publish as an idle fleet and flood)
        if self._waiting or len(self._running) >= a.max_num_seqs:
            per_step = (
                a.decode_time_per_step
                + a.max_num_seqs * a.decode_time_per_seq
            ) / speed
            ahead_steps = sum(
                max(r.max_tokens - r.generated, 1) for r in self._running
            ) + sum(
                max(r.max_tokens, 1)
                for r in self._waiting if not r.done
            )
            est_s += (ahead_steps / max(a.max_num_seqs, 1)) * per_step
        return est_s * 1000.0

    # -- scheduler ---------------------------------------------------------- #

    async def _step_loop(self):
        """One iteration = admit + chunked prefill + decode all running
        (reference Scheduler::step scheduler.rs:240)."""
        while not self._closed:
            if not self._waiting and not self._running:
                self._wake.clear()
                await self._wake.wait()
                continue
            t_step0 = time.monotonic()
            try:
                f = faults.FAULTS
                if f.enabled:
                    # dynochaos `mocker.step`: rides the same fail-all path
                    # a real scheduler bug would take
                    await f.on("mocker.step")
                prefill_tokens = self._do_admission_and_prefill()
                decoded = self._do_decode()
                if prefill_tokens and decoded:
                    self.mixed_steps += 1
                self.mixed_rows_plain += decoded
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — step loop must not die silently
                logger.exception("mock engine step failed; failing active requests")
                self._fail_all(f"mock engine step failed: {type(e).__name__}: {e}")
                await asyncio.sleep(0.05)
                continue
            # synthetic step latency
            a = self.args
            step_time = (
                prefill_tokens * a.prefill_time_per_token
                + (a.decode_time_per_step + decoded * a.decode_time_per_seq if decoded else 0.0)
            ) / max(a.speedup_ratio, 1e-9)
            elapsed = time.monotonic() - t_step0
            await asyncio.sleep(max(step_time - elapsed, 0.0001))

    def _itl_prefill_budget(self) -> int:
        """sla policy: prefill tokens this step may spend while the
        projected step latency (decode + prefill, the mocker's synthetic
        timing model) stays under the ITL target. Full throttle when no
        request is decode-active; a request past its TTFT deadline breaks
        a zero budget (one block) — TTFT attainment outranks decode
        smoothness, mirroring StepPlanner's deadline override."""
        a = self.args
        full = a.max_num_batched_tokens
        if self.sla.itl_target_ms <= 0:
            return full
        n_dec = sum(
            1 for r in self._running if r.prefill_pos >= len(r.prompt)
        )
        if not n_dec:
            return full
        speed = max(a.speedup_ratio, 1e-9)
        decode_s = (a.decode_time_per_step + n_dec * a.decode_time_per_seq) / speed
        per_tok = a.prefill_time_per_token / speed
        if per_tok <= 0:
            return full
        budget = int(max(self.sla.itl_target_ms / 1000.0 - decode_s, 0.0) / per_tok)
        budget = min(budget, full)
        if budget <= 0:
            pending = [
                r for r in [*self._waiting, *self._running]
                if r.prefill_pos < len(r.prompt) and not r.done
            ]
            if not pending:
                return 0  # nothing to defer: the counters must not move
            now = time.monotonic()
            if any(r.sched_deadline <= now for r in pending):
                self.sched_deadline_overrides += 1
                return a.block_size
            self.sched_deferred_steps += 1
            return 0
        return budget

    def _do_admission_and_prefill(self) -> int:
        """Admit waiting requests (prefix-cache aware) and advance chunked
        prefill; returns prefill tokens processed this step. Under the
        sla policy, admission and chunk order are EDF over TTFT deadlines
        and the budget is ITL-capped; under fifo (default) this is the
        reference scheduler bit-for-bit."""
        a = self.args
        budget = a.max_num_batched_tokens
        waiting = self._waiting
        if self.sla.policy == "sla":
            waiting = sorted(waiting, key=self._edf_key)
            budget = min(budget, self._itl_prefill_budget())
        processed = 0
        # admit
        still_waiting: List[_MockRequest] = []
        for req in waiting:
            if req.done or req.context.is_stopped():
                self._finish(req, "cancelled", emit=not req.done)
                continue
            if len(self._running) >= a.max_num_seqs:
                still_waiting.append(req)
                continue
            hashes = req.seq.block_hashes()
            if a.enable_prefix_caching:
                cached = self.kv.cached_prefix_blocks(hashes)
            else:
                cached = 0
            if not self.kv.can_allocate(hashes, extra_blocks=1):
                still_waiting.append(req)
                continue
            token_blocks = [b.tokens for b in req.seq.blocks]
            self.kv.acquire(hashes, token_blocks=token_blocks)
            req.held_hashes = list(hashes)
            req.prefill_pos = cached * a.block_size if not req.decode_only else len(req.prompt)
            self._running.append(req)
        self._waiting = still_waiting
        # chunked prefill over running requests (EDF order under sla;
        # taken AFTER admission so fresh admits prefill this same step,
        # exactly like the fifo path)
        prefill_order = (
            sorted(self._running, key=self._edf_key)
            if self.sla.policy == "sla" else self._running
        )
        for req in prefill_order:
            if req.prefill_pos >= len(req.prompt):
                continue
            remaining = len(req.prompt) - req.prefill_pos
            chunk = min(remaining, budget - processed) if a.enable_chunked_prefill else remaining
            if chunk <= 0:
                continue
            req.prefill_pos += chunk
            processed += chunk
            self._note_tenant(req.tenant, chunk)
        return processed

    def _edf_key(self, req: _MockRequest):
        """EDF with the dynogate tenant tiebreak (StepPlanner.order
        parity — same quantum/decay/cap constants, imported so the two
        paths cannot drift): within a deadline bucket the least-served
        tenant goes first."""
        return (int(req.sched_deadline / _TENANT_TIE_QUANTUM_S),
                self._tenant_served.get(req.tenant, 0), req.sched_deadline)

    def _note_tenant(self, tenant: str, granted: int) -> None:
        served = self._tenant_served.get(tenant, 0) + granted
        self._tenant_served[tenant] = served
        if served > _TENANT_DECAY:
            for t in list(self._tenant_served):
                self._tenant_served[t] //= 2
        if len(self._tenant_served) > _TENANT_MAX:  # client-controlled key
            keep = sorted(self._tenant_served.items(),
                          key=lambda kv: kv[1], reverse=True)
            self._tenant_served = dict(keep[: _TENANT_MAX // 2])

    def _do_decode(self) -> int:
        """One decode token for every prefilled running request."""
        a = self.args
        decoded = 0
        finished: List[_MockRequest] = []
        for req in self._running:
            if req.done or req.context.is_stopped():
                finished.append(req)
                continue
            if req.prefill_pos < len(req.prompt):
                continue  # still prefilling
            token = self._next_token(req)
            req.seq.append(token)
            req.generated += 1
            decoded += 1
            # block accounting for newly completed generation blocks
            hashes = req.seq.block_hashes()
            if len(hashes) > len(req.held_hashes):
                new = hashes[len(req.held_hashes) :]
                tokens_new = [b.tokens for b in req.seq.blocks[len(req.held_hashes) :]]
                self.kv.acquire(
                    new,
                    token_blocks=tokens_new,
                    parent_of_first=req.held_hashes[-1] if req.held_hashes else None,
                )
                req.held_hashes.extend(new)
            finish = None
            if not req.ignore_eos and token in req.eos_token_ids:
                finish = "eos"
            elif req.generated >= req.max_tokens:
                finish = "length"
            out = LLMEngineOutput(token_ids=[token], finish_reason=finish).to_dict()
            req.queue.put_nowait(Annotated(data=out).to_dict())
            if finish:
                finished.append(req)
        for req in finished:
            self._finish(req, None)
        return decoded

    def _next_token(self, req: _MockRequest) -> int:
        """Deterministic pseudo-token stream derived from the prompt. Tokens
        land in the byte-tokenizer's printable range (ids 35..126 ≈ ASCII)
        so mock responses detokenize to visible text."""
        h = hashlib.blake2b(
            f"{req.request_id}:{req.generated}".encode()
            + bytes(str(req.prompt[:8]), "ascii"),
            digest_size=4,
        ).digest()
        tok = 35 + int.from_bytes(h, "little") % 92
        while tok in req.eos_token_ids:
            tok = 35 + (tok + 1 - 35) % 92
        return tok

    def _fail_all(self, message: str):
        """A step raised: error every live request so callers see a clean
        typed terminal chunk and can retry/migrate, instead of hanging on
        queues a dead step loop will never fill (mirrors
        JaxEngine._fail_all)."""
        for req in [*self._running, *self._waiting]:
            if req.held_hashes:
                self.kv.release(req.held_hashes)
                req.held_hashes = []
            if not req.done:
                req.queue.put_nowait(Annotated.from_error(message).to_dict())
                req.queue.put_nowait(None)
                req.done = True
        self._running = []
        self._waiting = []

    def _sever_all(self, message: str) -> int:
        """Role-morph drain: deliberately cut every live stream with a
        StreamSevered sentinel (NOT a terminal error chunk — _fail_all's
        shape). The consumer loop raises it, the server codes the T_ERR
        as `draining`, and each caller's migration loop resumes the
        session on a peer from its durable checkpoint."""
        severed = 0
        for req in [*self._running, *self._waiting]:
            if req.held_hashes:
                self.kv.release(req.held_hashes)
                req.held_hashes = []
            # no trailing None: the consumer raises on the sentinel itself
            if not req.done:
                req.queue.put_nowait(StreamSevered(message))
                req.done = True
                severed += 1
        self._running = []
        self._waiting = []
        return severed

    # -- live role morphing (docs/autoscaling.md "Role morphing") ------------ #

    _ROLES = {
        "prefill": {"prefill"},
        "decode": {"decode"},
        "both": {"prefill", "decode"},
    }

    async def morph(
        self,
        target_role: str,
        *,
        on_flip: Optional[Callable[[], Any]] = None,
    ) -> dict:
        """Re-role this live engine: serving → draining-role → flipped →
        warm → serving. Streams of the OUTGOING role are severed so their
        sessions resume on peers from durable checkpoints (zero lost
        items, a tail of latency); `on_flip` is awaited between the role
        flip and re-warm so the worker harness can atomically move the
        discovery registration; re-warm drives the incoming role's
        compile surfaces before the worker takes traffic again.

        Failure semantics: any exception mid-morph rolls the engine back
        to its original role (drained sessions already resumed on peers —
        nothing to restore) EXCEPT faults.MorphCrash, which propagates so
        the harness tears the worker down crash-style."""
        if target_role not in self._ROLES:
            raise ValueError(f"unknown role {target_role!r}")
        if self._morph_state != "serving":
            raise RuntimeError(
                f"morph re-entered while {self._morph_state!r}"
            )
        old_role = self._role
        if target_role == old_role:
            return {"from": old_role, "to": target_role,
                    "drained": 0, "duration_s": 0.0}
        t0 = time.monotonic()
        self._morph_state = "draining-role"
        try:
            f = faults.FAULTS
            if f.enabled:
                # dynochaos `worker.morph` (mid-drain): `error` exercises
                # rollback, `crash` the corpse path
                act = await f.on("worker.morph")
                if act == "crash":
                    raise faults.MorphCrash("injected crash mid-drain")
            drained = 0
            # sever when ANY previously-served lane is going away; "both"
            # keeps every lane, so growing into it drains nothing
            if self._ROLES[old_role] - self._ROLES[target_role]:
                drained = self._sever_all(
                    f"worker morphing {old_role}->{target_role}; "
                    "stream re-routed"
                )
            self.morph_drained_sessions += drained
            self._morph_state = "flipped"
            if f.enabled:
                # dynochaos `worker.morph` (mid-flip): same actions, after
                # the drain — rollback here proves sessions already moved
                act = await f.on("worker.morph")
                if act == "crash":
                    raise faults.MorphCrash("injected crash mid-flip")
            self._role = target_role
            if on_flip is not None:
                await on_flip()
            self._morph_state = "warm"
            await self.warmup()
        except asyncio.CancelledError:
            raise
        except faults.MorphCrash:
            raise  # harness tears the worker down mid-morph, no rollback
        except Exception:
            self._role = old_role
            self._morph_state = "serving"
            self.morphs_rolled_back += 1
            raise
        self._morph_state = "serving"
        self.morphs_completed += 1
        self.morph_last_duration_s = time.monotonic() - t0
        return {"from": old_role, "to": target_role,
                "drained": drained,
                "duration_s": self.morph_last_duration_s}

    def estimated_role_tok_s(self) -> Dict[str, float]:
        """Marginal per-role throughput from the synthetic timing model —
        the mocker's spelling of the JaxEngine's cost-model-EWMA
        estimates that price the planner's morph-vs-spawn decision."""
        a = self.args
        speed = max(a.speedup_ratio, 1e-9)
        prefill = speed / max(a.prefill_time_per_token, 1e-12)
        b = max(a.max_num_seqs, 1)
        decode = b * speed / max(
            a.decode_time_per_step + b * a.decode_time_per_seq, 1e-12
        )
        return {"prefill": prefill, "decode": decode}

    def _finish(self, req: _MockRequest, reason: Optional[str], emit: bool = True):
        if req in self._running:
            self._running.remove(req)
        if req.held_hashes:
            self.kv.release(req.held_hashes)
            req.held_hashes = []
        if emit and reason and not req.done:
            out = LLMEngineOutput(token_ids=[], finish_reason=reason).to_dict()
            req.queue.put_nowait(Annotated(data=out).to_dict())
        if not req.done:
            req.queue.put_nowait(None)
