from .engine import MockEngine, MockEngineArgs
from .kv_manager import KvManager

__all__ = ["MockEngine", "MockEngineArgs", "KvManager"]
