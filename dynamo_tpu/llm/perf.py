"""Stream-level performance capture: TTFT / ITL from timestamped responses.

Role of the reference's perf module (lib/llm/src/perf.rs:84-340): wrap a
response stream so every emission is timestamped relative to request
start, then derive time-to-first-token, inter-token latencies, and token
throughput for benchmarking and the profiler. Works on any async iterator
of Annotated[LLMEngineOutput]-shaped items.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, List, Optional


@dataclass
class TimestampedResponse:
    t: float  # seconds since stream start
    data: Any
    num_tokens: int = 0


@dataclass
class StreamPerf:
    """Recorded stream timeline + derived latency stats."""

    responses: List[TimestampedResponse] = field(default_factory=list)

    def record(self, t: float, data: Any, num_tokens: int) -> None:
        self.responses.append(TimestampedResponse(t, data, num_tokens))

    # -- derived metrics ----------------------------------------------------
    def ttft(self) -> Optional[float]:
        for r in self.responses:
            if r.num_tokens > 0:
                return r.t
        return None

    def token_timestamps(self) -> List[float]:
        """One timestamp per token (a multi-token emission repeats its
        arrival time — tokens inside one step are indistinguishable)."""
        out: List[float] = []
        for r in self.responses:
            out.extend([r.t] * r.num_tokens)
        return out

    def inter_token_latencies(self) -> List[float]:
        ts = self.token_timestamps()
        return [b - a for a, b in zip(ts, ts[1:])]

    def mean_itl(self) -> Optional[float]:
        itls = self.inter_token_latencies()
        return sum(itls) / len(itls) if itls else None

    def total_tokens(self) -> int:
        return sum(r.num_tokens for r in self.responses)

    def token_frames(self) -> int:
        """Emissions that carried at least one token (delta batches)."""
        return sum(1 for r in self.responses if r.num_tokens > 0)

    def tokens_per_frame(self) -> Optional[float]:
        """Mean tokens per delta batch — the token-path batching signal:
        > 1 in steady decode means the serving plane is moving whole
        blocks, not singletons (ISSUE 4 serving-gap diagnostic)."""
        f = self.token_frames()
        return self.total_tokens() / f if f else None

    def duration(self) -> float:
        return self.responses[-1].t if self.responses else 0.0

    def tokens_per_second(self) -> Optional[float]:
        d = self.duration()
        n = self.total_tokens()
        return n / d if d > 0 and n else None

    def summary(self) -> dict:
        return {
            "ttft_s": self.ttft(),
            "mean_itl_s": self.mean_itl(),
            "total_tokens": self.total_tokens(),
            "duration_s": self.duration(),
            "tokens_per_second": self.tokens_per_second(),
            "tokens_per_frame": self.tokens_per_frame(),
        }


def _count_tokens(item: Any) -> int:
    data = getattr(item, "data", item)
    ids = getattr(data, "token_ids", None)
    if ids is None and isinstance(data, dict):
        ids = data.get("token_ids")
    return len(ids) if ids else 0


async def record_stream(
    stream: AsyncIterator[Any], perf: Optional[StreamPerf] = None
) -> AsyncIterator[Any]:
    """Pass-through wrapper that timestamps every emission into `perf`
    (reference perf.rs wrap-and-timestamp). Usage:

        perf = StreamPerf()
        async for item in record_stream(engine_stream, perf): ...
        print(perf.summary())
    """
    perf = perf if perf is not None else StreamPerf()
    t0 = time.monotonic()
    async for item in stream:
        perf.record(time.monotonic() - t0, item, _count_tokens(item))
        yield item
