"""KV-cache data plane: the NIXL replacement's fast path.

The reference moves prefill→decode KV via NIXL RDMA: the prefill side
registers memory and advertises descriptors, the decode side pulls with
`begin_read` while its engine keeps stepping
(lib/bindings/python/src/dynamo/nixl_connect/__init__.py:501-723,
lib/llm/src/block_manager/storage/nixl.rs). The TPU-native equivalent here
keeps the same *shape* — descriptor rendezvous + receiver-driven pull +
transfer/compute overlap — with transports that fit TPU hosts:

  * **staged pull over a dedicated TCP data plane**: the prefill worker
    runs a `KvDataPlaneServer` on its own port (NOT the request plane — a
    streaming KV payload must never head-of-line-block token traffic).
    Finishing a remote prefill *stages* the slot's pages and returns only a
    small descriptor on the response stream; the decode worker connects and
    pulls page CHUNKS, injecting each into its own cache while later chunks
    are still in flight. Frames carry raw page bytes (length-prefixed, no
    msgpack of the bulk) written straight from the array's memoryview.
  * **in-process device path**: when both engines share a process (one
    host serving both roles, or tests), the descriptor resolves through a
    process-local registry and chunks hand over as device arrays —
    extract→inject without host serialization. A multi-slice deployment
    whose prefill+decode meshes share one jax.distributed world can swap
    this transport for ppermute/DCN collectives behind the same interface.

Descriptors are also advertised under `v1/kv_data_plane/<instance>` in
discovery (the NIXL-metadata-in-etcd rendezvous, docs/architecture/
dynamo_flow.md S8/S10), so any worker can locate a peer's data plane
without a request-plane hop.
"""

from __future__ import annotations

import asyncio
import logging
import secrets
import struct
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Dict, Optional, Sequence, Tuple

import msgpack
import numpy as np

from ..runtime import faults

logger = logging.getLogger(__name__)


class KvTransferError(RuntimeError):
    """A KV data-plane transfer failed (peer unreachable, addr no longer
    resolving, severed stream, protocol violation). Typed so the onboard /
    disagg paths can convert it to a clean recompute/local-prefill fallback
    instead of letting a raw ConnectionError escape into the step loop."""


class KvFormatError(KvTransferError):
    """The two ends of a KV transfer run different page formats
    (DYN_KV_QUANT mixed-precision fleet, docs/kvbm.md mixed-fleet rules).
    Raised BEFORE any payload bytes are interpreted: a format mismatch
    must fail typed — countable, alertable — never silently reinterpret
    quantized bytes as fp pages (or vice versa)."""


_MAGIC = 0xD7A04B1D  # frame magic (full-stream pull handshake)
_MAGIC_RANGE = 0xD7A04B1E  # ranged pull handshake (multi-host shard chunks)
_HDR = struct.Struct("<II")  # magic, header length

DATA_PLANE_ROOT = "v1/kv_data_plane/"

# hard server-side cap on one checkpoint push's block payload; the
# checkpointer sizes its batches to half this (bytes, not block count —
# a large-KV config would otherwise build full batches no server accepts)
CHECKPOINT_MAX_PAYLOAD = 512 << 20

# process-local rendezvous: (addr, transfer_id) -> _Staged. The in-process
# device-direct path (co-located prefill/decode engines) resolves here and
# never touches the socket.
_LOCAL: Dict[Tuple[str, str], "_Staged"] = {}


def _np_bytes(a: np.ndarray) -> memoryview:
    """Zero-copy view of an array's bytes (contiguous arrays only)."""
    a = np.ascontiguousarray(a)
    return a.reshape(-1).view(np.uint8).data


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _set_nodelay(writer: asyncio.StreamWriter):
    """Disable Nagle on a KV data-plane socket: header+payload frames are
    written back-to-back and a coalescing delay on either end stalls the
    pull round-trip (admission-latency path)."""
    import socket

    try:
        sock = writer.get_extra_info("socket")
        if sock is not None:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass


def routable_host() -> str:
    """Best-effort routable address for descriptor advertisement. Binding to
    0.0.0.0 and advertising 127.0.0.1 silently defeats cross-host disagg
    (every pull connects to self and falls back to local prefill), so default
    to the interface a remote peer would reach us on."""
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        # no packets are sent; this just asks the kernel for the route
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


@dataclass
class KvTransferDescriptor:
    """What rides the response stream instead of the KV payload (the NIXL
    descriptor role)."""

    transfer_id: str
    addr: str  # host:port of the staging worker's data plane
    n_pages: int
    n_tokens: int
    page_size: int
    page_shape: list  # per-page block shape [L, page, KH, D]; a chunk of n
    # pages is layer-major [L, n, page, KH, D] (the engine's KV layout)
    dtype: str
    chunk_pages: int
    # multi-host shard rendezvous: host h of the PULLING worker fetches its
    # own shard's chunks (ranged pulls) from shards[h]["addr"] under the
    # shared transfer_id. page_shape is then the SHARD's per-page shape
    # (KH split across hosts). None => single staging endpoint (full pages).
    shards: Optional[list] = None  # [{"host_id": int, "addr": str}]
    # streamed staging: the producer is still prefilling when this
    # descriptor ships — chunks become pullable as pages commit, so the
    # puller must tolerate producer-paced gaps between chunks
    streamed: bool = False
    # quantized-KV page format ("none" | "int8" | "int4"): under quant,
    # page_shape is the PACKED host layout [L, PAGE_BYTES] uint8 (q bytes
    # + per-page-per-head scales, ops/kv_quant.py) and the puller must
    # run the same format — checked typed (KvFormatError) before pulling
    kv_format: str = "none"

    def to_dict(self) -> dict:
        return dict(self.__dict__)

    @classmethod
    def from_dict(cls, d: dict) -> "KvTransferDescriptor":
        import dataclasses as _dc

        known = {f.name for f in _dc.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


# extract(page_offset, n_pages, device) -> (k, v) with leading dim n_pages;
# may return jax arrays when device=True (in-process path)
ExtractFn = Callable[[int, int, bool], Awaitable[Tuple[Any, Any]]]


@dataclass
class _Staged:
    desc: KvTransferDescriptor
    extract: ExtractFn
    on_done: Callable[[bool], None]  # called exactly once; arg = pulled ok
    deadline: float
    max_transfer_time: float = 120.0  # per-chunk deadline extension budget
    started: bool = False
    finished: bool = False
    server: Optional["KvDataPlaneServer"] = None  # for serve accounting
    # streamed staging (disagg early handoff, docs/disagg_serving.md): the
    # transfer is staged while the producing prefill is STILL RUNNING.
    # `available` = pages valid so far (None = all pages, the non-streamed
    # default); the producer advances it as prefill chunks commit and the
    # serve loop waits on `avail_event` before extracting past it. `failed`
    # aborts waiting pullers (producer died / preempted mid-stream).
    available: Optional[int] = None
    failed: bool = False
    avail_event: Optional[asyncio.Event] = None

    def set_available(self, n_pages: int):
        if self.available is not None and n_pages > self.available:
            self.available = min(n_pages, self.desc.n_pages)
            # a progressing producer keeps the transfer alive
            self.deadline = time.monotonic() + self.max_transfer_time
            if self.avail_event is not None:
                self.avail_event.set()

    def fail_stream(self):
        self.failed = True
        if self.avail_event is not None:
            self.avail_event.set()

    async def wait_pages(self, upto: int):
        """Block until pages [0, upto) are valid (streamed staging); no-op
        for fully-staged transfers. Raises KvTransferError when the
        producer fails or the transfer is reaped mid-wait."""
        while True:
            if self.failed:
                raise KvTransferError("streamed kv transfer failed at source")
            if self.finished:
                raise KvTransferError("kv transfer reaped mid-stream")
            if self.available is None or self.available >= upto:
                return
            self.avail_event.clear()
            try:
                await asyncio.wait_for(
                    self.avail_event.wait(), self.max_transfer_time
                )
            except (TimeoutError, asyncio.TimeoutError) as e:
                raise KvTransferError(
                    "streamed kv transfer stalled (producer made no "
                    f"progress past page {self.available})"
                ) from e

    def count_serve(self, nbytes: int):
        """Account a served chunk (socket OR in-process) on the owning
        server's counters."""
        if self.server is not None:
            self.server.transfers_served += 1
            self.server.bytes_served += nbytes

    def finish(self, ok: bool):
        if not self.finished:
            self.finished = True
            try:
                self.on_done(ok)
            except Exception:  # noqa: BLE001 — release callbacks must not kill the server
                logger.exception("kv transfer on_done failed")


class KvDataPlaneServer:
    """Prefill-side staging server: holds pinned transfers, streams chunks
    to pulling peers, reaps abandoned transfers so their pages free."""

    def __init__(self, host: str = "0.0.0.0", advertise_host: Optional[str] = None,
                 port: int = 0, ttl: float = 30.0, max_transfer_time: float = 120.0,
                 chunk_timeout: float = 30.0):
        self._host = host
        self._advertise_host = advertise_host or (
            routable_host() if host in ("0.0.0.0", "") else host
        )
        self._port = port
        self.ttl = ttl
        # a pull that has *started* gets this long to finish before the
        # reaper unstages it (half-open peers must not pin pages forever)
        self.max_transfer_time = max_transfer_time
        self.chunk_timeout = chunk_timeout
        self._server: Optional[asyncio.AbstractServer] = None
        self._staged: Dict[str, _Staged] = {}
        self._reaper: Optional[asyncio.Task] = None
        # observability: exact evidence that THIS host's data plane moved
        # bytes (the disagg tests assert on these — a silent local-prefill
        # fallback must not be able to masquerade as a working data plane)
        self.transfers_served = 0
        self.bytes_served = 0
        # distributed KVBM (kvbm/distributed.py): when set, `{"blocks": [...]}`
        # handshakes resolve straight from the tier manager — peers onboard
        # blocks this worker offloaded (reference KvbmLeader/Worker role)
        self.kvbm_source = None
        # back-pointer to KvbmDistributed: the checkpoint-receive path
        # tags stored replicas + announces them on the mesh
        self.kvbm_distributed = None
        # session-checkpoint pushes accepted into our tiers
        self.checkpoint_pushes = 0
        self.checkpoint_blocks_received = 0

    @property
    def addr(self) -> str:
        return f"{self._advertise_host}:{self._port}"

    async def start(self):
        self._server = await asyncio.start_server(self._serve, self._host, self._port)
        self._port = self._server.sockets[0].getsockname()[1]
        self._reaper = asyncio.create_task(self._reap_loop())

    async def close(self):
        if self._reaper is not None:
            self._reaper.cancel()
        for t in list(self._staged.values()):
            self._unstage(t, ok=False)
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def register(self, drt):
        """Advertise this data plane in discovery (NIXL-metadata rendezvous)."""
        import json

        try:
            await drt.put_leased(
                f"{DATA_PLANE_ROOT}{drt.instance_id:x}",
                json.dumps({"addr": self.addr}).encode(),
            )
        except Exception:  # noqa: BLE001 — advertisement is best-effort
            logger.warning("could not advertise kv data plane", exc_info=True)

    def stage(
        self,
        *,
        n_pages: int,
        n_tokens: int,
        page_size: int,
        page_shape: list,
        dtype: str,
        extract: ExtractFn,
        on_done: Callable[[bool], None],
        chunk_pages: int = 0,
        ttl: Optional[float] = None,
        transfer_id: Optional[str] = None,
        streamed: bool = False,
        available_pages: int = 0,
        kv_format: str = "none",
    ) -> KvTransferDescriptor:
        """Pin a finished prefill's pages for pulling; returns the descriptor
        to send on the response stream. `on_done(ok)` fires exactly once —
        on successful pull, pull failure, or TTL expiry — and is where the
        engine releases the slot's pages. An explicit `transfer_id` lets
        every host of a multi-host worker stage its shard under ONE id (the
        leader picks the id and broadcasts it in the stage_shard step
        descriptor). `streamed=True` stages a transfer whose producer is
        still running: only `available_pages` are valid yet, the producer
        advances the watermark via `advance_streamed` as pages commit, and
        pullers wait at the watermark instead of reading garbage."""
        if chunk_pages <= 0:
            # ~4 MiB/chunk of K (plus V): small enough to overlap, large
            # enough that framing cost vanishes
            per_page = int(np.prod(page_shape)) * _np_dtype(dtype).itemsize
            chunk_pages = max(1, (4 << 20) // max(per_page, 1))
        transfer_id = transfer_id or secrets.token_hex(8)
        desc = KvTransferDescriptor(
            transfer_id=transfer_id,
            addr=self.addr,
            n_pages=n_pages,
            n_tokens=n_tokens,
            page_size=page_size,
            page_shape=list(page_shape),
            dtype=dtype,
            chunk_pages=chunk_pages,
            streamed=streamed,
            kv_format=kv_format,
        )
        staged = _Staged(
            desc=desc,
            extract=extract,
            on_done=on_done,
            deadline=time.monotonic() + (ttl if ttl is not None else self.ttl),
            max_transfer_time=self.max_transfer_time,
            server=self,
            available=min(max(available_pages, 0), n_pages) if streamed else None,
            avail_event=asyncio.Event() if streamed else None,
        )
        self._staged[transfer_id] = staged
        _LOCAL[(self.addr, transfer_id)] = staged
        return desc

    def advance_streamed(self, transfer_id: str, available_pages: int):
        """Producer-side watermark: pages [0, available_pages) are now
        valid. No-op for unknown/non-streamed transfers."""
        staged = self._staged.get(transfer_id)
        if staged is not None:
            staged.set_available(available_pages)

    def abort_streamed(self, transfer_id: str):
        """Producer died (preempt / engine failure) mid-stream: wake and
        fail any waiting puller, release the stage."""
        staged = self._staged.get(transfer_id)
        if staged is not None:
            staged.fail_stream()
            self._unstage(staged, ok=False)

    def _unstage(self, staged: _Staged, ok: bool):
        self._staged.pop(staged.desc.transfer_id, None)
        _LOCAL.pop((self.addr, staged.desc.transfer_id), None)
        staged.finish(ok)

    def unstage_by_id(self, transfer_id: str, ok: bool) -> None:
        """Explicit release (multi-host shard staging: the leader decides
        when a transfer is over and broadcasts unstage_shard to followers —
        ranged pulls have no single is-done connection)."""
        staged = self._staged.get(transfer_id)
        if staged is not None:
            self._unstage(staged, ok)

    async def _reap_loop(self):
        while True:
            await asyncio.sleep(1.0)
            now = time.monotonic()
            for t in list(self._staged.values()):
                if t.finished:
                    # in-process pulls finish without passing through _serve;
                    # drop the bookkeeping entry so _staged stays bounded
                    self._staged.pop(t.desc.transfer_id, None)
                elif now > t.deadline:
                    logger.warning(
                        "kv transfer %s %s; releasing",
                        t.desc.transfer_id,
                        "stalled mid-pull" if t.started else "never pulled",
                    )
                    self._unstage(t, ok=False)

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        _set_nodelay(writer)
        try:
            # ranged/kvbm requests are request-response and KEEP the
            # connection: a peer onboarding at admission rate would
            # otherwise pay a TCP connect per request (the client keeps a
            # small per-addr pool, _ConnPool). Idle connections die at the
            # chunk timeout; full-stream transfer pulls still close after
            # the one transfer.
            while True:
                try:
                    hdr = await asyncio.wait_for(
                        reader.readexactly(_HDR.size), self.chunk_timeout
                    )
                except (TimeoutError, asyncio.TimeoutError,
                        asyncio.IncompleteReadError):
                    return  # idle keep-alive or clean peer close
                magic, length = _HDR.unpack(hdr)
                if magic not in (_MAGIC, _MAGIC_RANGE):
                    raise RuntimeError(f"bad kv data plane magic {magic:#x}")
                # _MAGIC handshakes carry a 16-hex-char transfer id;
                # _MAGIC_RANGE handshakes may carry a {"blocks": [up to
                # 4096 x u64]} kvbm request (~9 B/hash => up to ~40 KiB)
                cap = 65536 if magic == _MAGIC_RANGE else 4096
                if length > cap:
                    raise RuntimeError(f"oversized kv handshake ({length} bytes)")
                body = await asyncio.wait_for(
                    reader.readexactly(length), self.chunk_timeout
                )
                if magic == _MAGIC_RANGE:
                    await self._serve_range(body, writer, reader)
                    continue
                await self._serve_transfer(body, writer)
                return
        except (asyncio.IncompleteReadError, ConnectionError):
            pass  # peer vanished; reaper/unstage already handled pages
        except Exception:  # noqa: BLE001 — one bad peer must not kill the server
            logger.exception("kv data plane connection failed")
        finally:
            writer.close()

    async def _serve_transfer(self, body: bytes, writer: asyncio.StreamWriter):
        """Full-stream transfer pull (one per connection; _serve closes
        after). Errors propagate to _serve's handler."""
        transfer_id = body.decode()
        staged = self._staged.get(transfer_id)
        if staged is None or staged.started:
            await self._send_header(writer, {"error": f"unknown transfer {transfer_id}"})
            return
        staged.started = True
        staged.deadline = time.monotonic() + self.max_transfer_time
        try:
            await self._stream(staged, writer)
        except (ConnectionError, asyncio.IncompleteReadError,
                TimeoutError, asyncio.TimeoutError,
                KvTransferError):  # asyncio.TimeoutError
            # is distinct from builtin TimeoutError before 3.11;
            # KvTransferError = streamed producer failed/stalled
            self._unstage(staged, ok=False)
            raise
        self.transfers_served += 1
        self._unstage(staged, ok=True)

    async def _serve_range(self, body: bytes, writer: asyncio.StreamWriter,
                           reader: Optional[asyncio.StreamReader] = None):
        """One ranged request -> one (k, v) frame. Ranged pulls are how a
        multi-host decode worker's host h fetches chunk (off, n) of ITS
        shard from the matching prefill host: many connections may read the
        same staged transfer, so completion is signalled out-of-band
        (unstage_by_id from the leader's unstage_shard broadcast), with the
        TTL/deadline reaper as backstop."""
        req = msgpack.unpackb(body, raw=False)
        if req.get("ckpt") is not None and reader is not None:
            await self._serve_checkpoint(req["ckpt"], reader, writer)
            return
        if req.get("blocks") is not None:
            await self._serve_kvbm_blocks(req, writer)
            return
        transfer_id = req.get("tid", "")
        staged = self._staged.get(transfer_id)
        if staged is None:
            await self._send_header(writer, {"error": f"unknown transfer {transfer_id}"})
            return
        if req.get("fin"):
            # puller-side completion signal: release now instead of at TTL
            # (a control message — not counted as a served transfer)
            self._unstage(staged, ok=True)
            await self._send_header(writer, {"ok": True})
            return
        off, n = int(req.get("off", 0)), int(req.get("n", 0))
        if not (0 <= off and 0 < n and off + n <= staged.desc.n_pages):
            await self._send_header(writer, {"error": f"range out of bounds ({off},{n})"})
            return
        if staged.available is not None and off + n > staged.available:
            # ranged pulls (multi-host shards) don't ride streamed staging:
            # refuse reads past the producer's watermark instead of
            # serving uncommitted pages
            await self._send_header(
                writer, {"error": f"range past stream watermark ({off},{n})"}
            )
            return
        # a transfer being actively range-pulled is alive: refresh its clock
        staged.deadline = time.monotonic() + self.max_transfer_time
        np_dtype = _np_dtype(staged.desc.dtype)
        k, v = await staged.extract(off, n, False)
        k = np.asarray(k, np_dtype)
        v = np.asarray(v, np_dtype)
        kb, vb = _np_bytes(k), _np_bytes(v)
        await self._send_header(
            writer, {"off": off, "n": n, "k_bytes": len(kb), "v_bytes": len(vb)}
        )
        writer.write(kb)
        writer.write(vb)
        await asyncio.wait_for(writer.drain(), self.chunk_timeout)
        staged.count_serve(len(kb) + len(vb))

    async def _serve_kvbm_blocks(self, req: dict, writer: asyncio.StreamWriter):
        """Serve tiered KV blocks by hash (distributed KVBM onboard path,
        kvbm/distributed.py). One request -> one stacked (k, v) frame."""
        if self.kvbm_source is None:
            await self._send_header(writer, {"error": "no kvbm tier here"})
            return
        hashes = [int(h) for h in req["blocks"]]
        if not hashes or len(hashes) > 4096:
            await self._send_header(writer, {"error": f"bad block count {len(hashes)}"})
            return
        my_fmt = str(getattr(self.kvbm_source, "kv_format", "none"))
        want_fmt = str(req.get("fmt", "none"))
        if want_fmt != my_fmt:
            # mixed-precision fleet: refuse TYPED before any block bytes
            # move — the puller raises KvFormatError, never misreads rows
            await self._send_header(
                writer,
                {"error": f"kv_format mismatch: serving {my_fmt}, "
                          f"peer wants {want_fmt}",
                 "fmt_mismatch": True, "fmt": my_fmt},
            )
            return
        try:
            # tier reads do host memcpy/disk IO: off the event loop —
            # EXCEPT small host-tier-only reads, where the executor
            # round-trip costs more than the memcpy it protects against
            # (admission-rate peer pulls of a few small blocks)
            src = self.kvbm_source
            small = (
                getattr(src, "disk", None) is None
                and getattr(src, "block_nbytes", 1 << 30) * len(hashes)
                <= (256 << 10)
            )
            if small:
                k, v = src.load_blocks(hashes)
            else:
                k, v = await asyncio.get_running_loop().run_in_executor(
                    None, src.load_blocks, hashes
                )
        except KeyError as e:
            await self._send_header(writer, {"error": f"block miss: {e}"})
            return
        kb, vb = _np_bytes(k), _np_bytes(v)
        # header + payload in ONE buffered write/drain: the pull RTT is
        # admission latency on the peer, every syscall batch counts
        hdr_body = msgpack.packb(
            {"n": len(hashes), "k_bytes": len(kb), "v_bytes": len(vb),
             "shape": list(k.shape), "dtype": str(k.dtype), "fmt": my_fmt},
            use_bin_type=True,
        )
        writer.write(_HDR.pack(_MAGIC, len(hdr_body)) + hdr_body)
        writer.write(kb)
        writer.write(vb)
        await asyncio.wait_for(writer.drain(), self.chunk_timeout)
        self.transfers_served += 1
        self.bytes_served += len(kb) + len(vb)

    async def _drain_payload(self, reader: asyncio.StreamReader, n: int):
        """Read and discard `n` payload bytes after a refused push so the
        keep-alive connection stays framed for the next request."""
        while n > 0:
            chunk = await asyncio.wait_for(
                reader.read(min(n, 1 << 20)), self.chunk_timeout
            )
            if not chunk:
                raise asyncio.IncompleteReadError(b"", n)
            n -= len(chunk)

    async def _serve_checkpoint(self, meta: dict,
                                reader: asyncio.StreamReader,
                                writer: asyncio.StreamWriter):
        """Session-checkpoint PUSH (kvbm/checkpoint.py): a peer replicates
        committed session blocks into OUR host tier so a death on its side
        resumes from here. Header carries hashes/parents/format/sizes; the
        block bytes follow on the same connection. Refusals (no tiers,
        kv_format mismatch, bad sizes) drain the payload and answer typed
        BEFORE any byte is interpreted — mixed-precision fleets fail
        loudly, never store misread rows."""
        hashes = [int(h) for h in meta.get("blocks") or []]
        parents = [
            None if p is None else int(p)
            for p in (meta.get("parents") or [None] * len(hashes))
        ]
        k_bytes = int(meta.get("k_bytes") or 0)
        v_bytes = int(meta.get("v_bytes") or 0)
        payload = k_bytes + v_bytes
        if (
            not hashes or len(hashes) > 4096 or len(parents) != len(hashes)
            or payload <= 0
        ):
            raise RuntimeError(f"bad checkpoint push ({len(hashes)} blocks, "
                               f"{payload} bytes)")
        if payload > CHECKPOINT_MAX_PAYLOAD:
            # oversized but well-formed: the declared size is bounded
            # enough to drain, so answer typed on the kept connection —
            # tearing it here would cost the pusher a reconnect AND
            # misattribute a sizing bug as a dead peer (quarantine)
            if payload > 2 * CHECKPOINT_MAX_PAYLOAD:
                raise RuntimeError(
                    f"checkpoint push payload absurd ({payload} bytes)"
                )
            await self._drain_payload(reader, payload)
            await self._send_header(
                writer, {"error": f"checkpoint payload too large "
                                  f"({payload} > {CHECKPOINT_MAX_PAYLOAD})",
                         "peer_blameless": True}
            )
            return
        src = self.kvbm_source
        if src is None:
            await self._drain_payload(reader, payload)
            await self._send_header(
                writer, {"error": "no kvbm tier here", "ckpt_ineligible": True}
            )
            return
        my_fmt = str(getattr(src, "kv_format", "none"))
        want_fmt = str(meta.get("fmt", "none"))
        if want_fmt != my_fmt:
            await self._drain_payload(reader, payload)
            await self._send_header(
                writer,
                {"error": f"kv_format mismatch: holding {my_fmt}, "
                          f"peer pushes {want_fmt}",
                 "fmt_mismatch": True, "fmt": my_fmt},
            )
            return
        np_dtype = np.dtype(src.dtype)
        expect = int(np.prod(src.block_shape)) * np_dtype.itemsize * len(hashes)
        if k_bytes != expect or v_bytes != expect:
            # block geometry (dtype/page size/layers) is static for a
            # process's lifetime: same structural class as a kv_format
            # mismatch, so the pusher must exclude us durably — a TTL
            # quarantine would re-offer the same doomed bytes forever
            await self._drain_payload(reader, payload)
            await self._send_header(
                writer, {"error": f"checkpoint size mismatch "
                                  f"({k_bytes}+{v_bytes} != 2x{expect})",
                         "ckpt_ineligible": True}
            )
            return
        raw = await asyncio.wait_for(
            reader.readexactly(payload), self.chunk_timeout
        )
        shape = (len(hashes), *src.block_shape)
        k = np.frombuffer(raw, dtype=np_dtype,
                          count=expect // np_dtype.itemsize).reshape(shape)
        v = np.frombuffer(raw, dtype=np_dtype, offset=k_bytes).reshape(shape)

        def store():
            for i, h in enumerate(hashes):
                src.store(h, k[i], v[i], parent=parents[i])

        # tier stores do host memcpy (+ possible disk cascade): off the
        # event loop past the same small-read threshold the pull path uses
        if payload <= (256 << 10) and getattr(src, "disk", None) is None:
            store()
        else:
            await asyncio.get_running_loop().run_in_executor(None, store)
        self.checkpoint_pushes += 1
        self.checkpoint_blocks_received += len(hashes)
        if self.kvbm_distributed is not None:
            self.kvbm_distributed.note_checkpoint_received(hashes)
        await self._send_header(writer, {"ok": True, "stored": len(hashes)})

    async def _send_header(self, writer, header: dict):
        body = msgpack.packb(header, use_bin_type=True)
        writer.write(_HDR.pack(_MAGIC, len(body)) + body)
        await writer.drain()

    async def _stream(self, staged: _Staged, writer: asyncio.StreamWriter):
        desc = staged.desc
        # prefetch pipeline depth 1: extract chunk i+1 while chunk i drains
        # into the socket — the extract (device gather + host read) overlaps
        # the network transfer
        np_dtype = _np_dtype(desc.dtype)

        async def get(off: int):
            n = min(desc.chunk_pages, desc.n_pages - off)
            # streamed staging: hold until the producer commits these pages
            # (no-op for fully-staged transfers)
            await staged.wait_pages(off + n)
            k, v = await staged.extract(off, n, False)
            return off, n, np.asarray(k, np_dtype), np.asarray(v, np_dtype)

        nxt = asyncio.ensure_future(get(0)) if desc.n_pages else None
        while nxt is not None:
            off, n, k, v = await nxt
            f = faults.FAULTS
            if f.enabled and await f.on("kv_transfer.chunk") == "sever":
                # partial transfer: abort mid-stream so the peer sees a
                # broken pull (same surface as the reaped-deadline path)
                # and falls back to local prefill / retries
                raise RuntimeError("injected: kv transfer severed mid-stream")
            if staged.finished:
                # the reaper unstaged us (deadline hit) and the pages may
                # already be reused: abort mid-stream so the peer sees a
                # broken transfer instead of a "successful" corrupted one
                raise RuntimeError("transfer reaped mid-stream")
            after = off + n
            nxt = asyncio.ensure_future(get(after)) if after < desc.n_pages else None
            kb, vb = _np_bytes(k), _np_bytes(v)
            await self._send_header(
                writer,
                {"off": off, "n": n, "k_bytes": len(kb), "v_bytes": len(vb)},
            )
            writer.write(kb)
            writer.write(vb)
            # a peer that stops reading must not pin pages: deadline the drain
            await asyncio.wait_for(writer.drain(), self.chunk_timeout)
            self.bytes_served += len(kb) + len(vb)
            # a progressing transfer earns its keep — refresh the deadline so
            # slow-but-alive links are not reaped mid-pull
            staged.deadline = time.monotonic() + self.max_transfer_time
        await self._send_header(writer, {"eof": True})


class _ConnPool:
    """Keep-alive client connections to peer data planes. kvbm block
    pulls are request-response at ADMISSION rate — paying a TCP connect
    per onboarded request is pure overhead, so finished connections
    return to a small per-addr pool (the server keeps ranged/kvbm
    connections open, closing idle ones at its chunk timeout). Pools are
    scoped PER EVENT LOOP (weak-keyed): a connection created under one
    asyncio.run can never be handed to another loop, and a dead loop's
    pool drops with it."""

    def __init__(self, per_addr: int = 4):
        import weakref

        self._pools: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self.per_addr = per_addr

    def _free_map(self) -> Dict[str, list]:
        loop = asyncio.get_running_loop()
        pools = self._pools.get(loop)
        if pools is None:
            pools = {}
            self._pools[loop] = pools
        return pools

    def evict(self, addr: str):
        """Close every pooled connection to `addr` (stale-server retry:
        the whole pool is suspect, not just the one that failed)."""
        for reader, writer in self._free_map().pop(addr, []):
            writer.close()

    async def acquire(self, addr: str, connect_timeout: float,
                      fresh: bool = False):
        """Returns (reader, writer, reused). `fresh=True` bypasses (and
        evicts) the pool — the retry path after a stale keep-alive, where
        popping another pooled connection would likely be just as stale."""
        if fresh:
            self.evict(addr)
        else:
            free = self._free_map().get(addr)
            while free:
                reader, writer = free.pop()
                if writer.is_closing():
                    continue
                return reader, writer, True
        host, port = addr.rsplit(":", 1)
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, int(port)), connect_timeout
            )
        except (OSError, TimeoutError, asyncio.TimeoutError) as e:
            # gaierror/refused/unroutable: the advertised addr stopped
            # resolving — typed so callers fall back instead of crashing
            raise KvTransferError(
                f"kv data plane {addr} unreachable: {e}"
            ) from e
        _set_nodelay(writer)
        return reader, writer, False

    def release(self, addr: str, reader, writer):
        if writer.is_closing():
            return
        free = self._free_map().setdefault(addr, [])
        if len(free) >= self.per_addr:
            writer.close()
        else:
            free.append((reader, writer))


_CONN_POOL = _ConnPool()


# inject(page_offset, n_pages, k, v) — awaited per chunk as it lands
InjectFn = Callable[[int, int, Any, Any], Awaitable[None]]


async def pull_kv_range(
    addr: str,
    transfer_id: str,
    off: int,
    n: int,
    page_shape: list,
    dtype: str,
    connect_timeout: float = 10.0,
    chunk_timeout: float = 30.0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Fetch ONE chunk [off, off+n) of a staged transfer — the multi-host
    shard path: decode host h pulls its own shard's chunk from prefill host
    h's data plane, so no host ever hauls another host's bytes (the scaling
    property NIXL's point-to-point descriptors give the reference,
    lib/llm/src/block_manager/storage/nixl.rs). Returns (k, v) shaped
    [L, n, page, KH, D] (the SHARD's shape)."""
    staged = _LOCAL.get((addr, transfer_id))
    if staged is not None:
        staged.deadline = time.monotonic() + staged.max_transfer_time
        k, v = await staged.extract(off, n, True)
        np_dtype = _np_dtype(dtype)
        k, v = np.asarray(k, np_dtype), np.asarray(v, np_dtype)
        # mirror the socket path's accounting: the staging host DID serve
        # these bytes, even though they never touched a socket
        staged.count_serve(k.nbytes + v.nbytes)
        return k, v
    host, port = addr.rsplit(":", 1)
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, int(port)), connect_timeout
    )
    try:
        body = msgpack.packb({"tid": transfer_id, "off": off, "n": n}, use_bin_type=True)
        writer.write(_HDR.pack(_MAGIC_RANGE, len(body)) + body)
        await writer.drain()
        np_dtype = _np_dtype(dtype)
        shape = tuple(page_shape)
        max_bytes = int(np.prod(shape)) * np_dtype.itemsize * n
        hdr = await asyncio.wait_for(reader.readexactly(_HDR.size), chunk_timeout)
        magic, length = _HDR.unpack(hdr)
        if magic != _MAGIC or length > 65536:
            raise RuntimeError(f"bad kv range frame (magic {magic:#x})")
        header = msgpack.unpackb(
            await asyncio.wait_for(reader.readexactly(length), chunk_timeout),
            raw=False,
        )
        if header.get("error"):
            raise RuntimeError(f"kv range refused: {header['error']}")
        if header["k_bytes"] > max_bytes or header["v_bytes"] > max_bytes:
            raise RuntimeError("kv range frame larger than requested")
        k_raw = await asyncio.wait_for(reader.readexactly(header["k_bytes"]), chunk_timeout)
        v_raw = await asyncio.wait_for(reader.readexactly(header["v_bytes"]), chunk_timeout)
        chunk_shape = (shape[0], n, *shape[1:])
        k = np.frombuffer(k_raw, dtype=np_dtype).reshape(chunk_shape)
        v = np.frombuffer(v_raw, dtype=np_dtype).reshape(chunk_shape)
        return k, v
    finally:
        writer.close()


async def pull_kvbm_blocks(
    addr: str,
    hashes: Sequence[int],
    block_shape: tuple,
    dtype,
    connect_timeout: float = 10.0,
    chunk_timeout: float = 30.0,
    kv_format: str = "none",
) -> Tuple[np.ndarray, np.ndarray]:
    """Fetch tiered KV blocks by hash from a peer worker's data plane
    (distributed KVBM onboard; reference block_manager/distributed/
    worker.rs:137). Returns (k, v) stacked [n, *block_shape]. Raises
    KeyError on a block miss, KvTransferError on any transport failure
    (unreachable peer, severed stream) — both convert to recompute in the
    onboard path — and KvFormatError when the peer's tiers hold a
    DIFFERENT quantized page format (`kv_format` travels in the
    handshake; a mixed-precision fleet fails typed, never misreads
    packed rows). Connections come from a keep-alive pool; a stale pooled
    connection (server idled it out) earns exactly one fresh retry."""
    f = faults.FAULTS
    for attempt in (0, 1):
        reader, writer, reused = await _CONN_POOL.acquire(
            addr, connect_timeout, fresh=attempt > 0
        )
        try:
            body = msgpack.packb(
                {"blocks": [int(h) for h in hashes], "fmt": str(kv_format)},
                use_bin_type=True,
            )
            writer.write(_HDR.pack(_MAGIC_RANGE, len(body)) + body)
            await writer.drain()
            if f.enabled and await f.on("kv_transfer.pull") == "sever":
                # mid-peer-onboard sever (dynochaos): the request is on
                # the wire but we drop the connection before the payload
                # lands — the onboard path must fall back to local-tier/
                # recompute with a counted fallback, never a hung or
                # corrupted stream
                raise KvTransferError("injected: kvbm peer pull severed")
            np_dtype = np.dtype(dtype)
            expect = int(np.prod(block_shape)) * np_dtype.itemsize * len(hashes)
            hdr = await asyncio.wait_for(reader.readexactly(_HDR.size), chunk_timeout)
            magic, length = _HDR.unpack(hdr)
            if magic != _MAGIC or length > 65536:
                raise RuntimeError(f"bad kvbm frame (magic {magic:#x})")
            header = msgpack.unpackb(
                await asyncio.wait_for(reader.readexactly(length), chunk_timeout),
                raw=False,
            )
            if header.get("error"):
                # protocol-level refusal: the connection is still good
                _CONN_POOL.release(addr, reader, writer)
                if header.get("fmt_mismatch"):
                    raise KvFormatError(
                        f"kvbm peer {addr} serves kv_format="
                        f"{header.get('fmt')!r}, we run {kv_format!r}"
                    )
                raise KeyError(f"kvbm pull refused: {header['error']}")
            if header["k_bytes"] > expect or header["v_bytes"] > expect:
                raise RuntimeError("kvbm frame larger than expected")
            # k and v are contiguous on the wire: one read, split by offset
            raw = await asyncio.wait_for(
                reader.readexactly(header["k_bytes"] + header["v_bytes"]),
                chunk_timeout,
            )
            shape = (len(hashes), *block_shape)
            k = np.frombuffer(
                raw, dtype=np_dtype, count=header["k_bytes"] // np_dtype.itemsize
            ).reshape(shape)
            v = np.frombuffer(
                raw, dtype=np_dtype, offset=header["k_bytes"]
            ).reshape(shape)
            _CONN_POOL.release(addr, reader, writer)
            return k, v
        except (KeyError, KvFormatError):
            raise
        except (ConnectionError, asyncio.IncompleteReadError,
                TimeoutError, asyncio.TimeoutError) as e:
            writer.close()
            if reused and attempt == 0:
                continue  # stale keep-alive: the server idled it out
            raise KvTransferError(f"kvbm peer pull from {addr} failed: {e}") from e
        except BaseException:
            writer.close()
            raise


async def push_checkpoint_blocks(
    addr: str,
    hashes: Sequence[int],
    parents: Sequence[Optional[int]],
    k: np.ndarray,
    v: np.ndarray,
    kv_format: str = "none",
    connect_timeout: float = 2.0,
    chunk_timeout: float = 30.0,
) -> int:
    """Push session-checkpoint blocks into a peer's G2 (the replication
    half of durable decode sessions, kvbm/checkpoint.py). `k`/`v` are
    stacked [n, *block_shape] host rows in this worker's kv_format; the
    peer refuses a format mismatch typed (KvFormatError) before any byte
    is interpreted. Returns the number of blocks the peer stored. Raises
    KvTransferError on transport failure (the checkpointer quarantines
    the peer and drops the batch — replication is best-effort)."""
    k = np.ascontiguousarray(k)
    v = np.ascontiguousarray(v)
    for attempt in (0, 1):
        reader, writer, reused = await _CONN_POOL.acquire(
            addr, connect_timeout, fresh=attempt > 0
        )
        try:
            body = msgpack.packb(
                {"ckpt": {
                    "blocks": [int(h) for h in hashes],
                    "parents": [None if p is None else int(p) for p in parents],
                    "fmt": str(kv_format),
                    "k_bytes": int(k.nbytes),
                    "v_bytes": int(v.nbytes),
                }},
                use_bin_type=True,
            )
            writer.write(_HDR.pack(_MAGIC_RANGE, len(body)) + body)
            writer.write(_np_bytes(k))
            writer.write(_np_bytes(v))
            await asyncio.wait_for(writer.drain(), chunk_timeout)
            hdr = await asyncio.wait_for(reader.readexactly(_HDR.size), chunk_timeout)
            magic, length = _HDR.unpack(hdr)
            if magic != _MAGIC or length > 65536:
                raise RuntimeError(f"bad checkpoint reply (magic {magic:#x})")
            header = msgpack.unpackb(
                await asyncio.wait_for(reader.readexactly(length), chunk_timeout),
                raw=False,
            )
            if header.get("error"):
                _CONN_POOL.release(addr, reader, writer)
                if header.get("fmt_mismatch"):
                    raise KvFormatError(
                        f"checkpoint peer {addr} holds kv_format="
                        f"{header.get('fmt')!r}, we push {kv_format!r}"
                    )
                err = KvTransferError(
                    f"checkpoint push refused: {header['error']}"
                )
                # structural refusal (no kvbm tier there, block-geometry
                # mismatch): the caller excludes the peer durably instead
                # of TTL-quarantining; peer_blameless (our own oversized
                # batch) means the healthy peer must not be penalized in
                # ANY role — drop + count only
                err.ckpt_ineligible = bool(header.get("ckpt_ineligible"))
                err.peer_blameless = bool(header.get("peer_blameless"))
                raise err
            _CONN_POOL.release(addr, reader, writer)
            return int(header.get("stored") or 0)
        except (KvFormatError, KvTransferError):
            raise
        except (ConnectionError, asyncio.IncompleteReadError,
                TimeoutError, asyncio.TimeoutError) as e:
            writer.close()
            if reused and attempt == 0:
                continue  # stale keep-alive: one fresh retry
            raise KvTransferError(
                f"checkpoint push to {addr} failed: {e}"
            ) from e
        except BaseException:
            writer.close()
            raise


async def finish_transfer(
    addr: str, transfer_id: str, connect_timeout: float = 10.0
) -> None:
    """Tell the staging peer a range-pulled transfer is complete so its
    pages release immediately (the TTL reaper is the backstop)."""
    staged = _LOCAL.get((addr, transfer_id))
    if staged is not None:
        _LOCAL.pop((addr, transfer_id), None)
        staged.finish(True)
        return
    host, port = addr.rsplit(":", 1)
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, int(port)), connect_timeout
    )
    try:
        body = msgpack.packb({"tid": transfer_id, "fin": True}, use_bin_type=True)
        writer.write(_HDR.pack(_MAGIC_RANGE, len(body)) + body)
        await writer.drain()
        await asyncio.wait_for(reader.readexactly(_HDR.size), connect_timeout)
    finally:
        writer.close()


async def pull_kv(
    desc: KvTransferDescriptor,
    inject: InjectFn,
    connect_timeout: float = 10.0,
    chunk_timeout: float = 30.0,
) -> None:
    """Decode-side pull: stream chunks from the staging peer and inject each
    while the rest are still in flight. Raises on any failure (caller falls
    back to local prefill). In-process transfers short-circuit through the
    local registry and stay on device."""
    staged = _LOCAL.get((desc.addr, desc.transfer_id))
    if staged is not None and not staged.started:
        staged.started = True
        staged.deadline = time.monotonic() + staged.max_transfer_time
        try:
            off = 0
            while off < desc.n_pages:
                if staged.finished:
                    raise KvTransferError("transfer reaped mid-pull")
                n = min(desc.chunk_pages, desc.n_pages - off)
                # streamed staging: the producer is still prefilling —
                # hold at its watermark (no-op when fully staged)
                await staged.wait_pages(off + n)
                k, v = await staged.extract(off, n, True)
                if staged.failed or staged.finished:
                    # producer aborted while we extracted (its pages may
                    # be recycled): never inject the chunk
                    raise KvTransferError("transfer aborted mid-pull")
                await inject(off, n, k, v)
                if hasattr(k, "nbytes"):
                    staged.count_serve(k.nbytes + v.nbytes)
                off += n
                staged.deadline = time.monotonic() + staged.max_transfer_time
            if staged.failed:
                raise KvTransferError("transfer aborted mid-pull")
        except BaseException:
            staged.finish(False)
            raise
        finally:
            _LOCAL.pop((desc.addr, desc.transfer_id), None)
        staged.finish(True)
        return

    if desc.streamed:
        # producer-paced: chunks arrive as prefill commits pages, so the
        # inter-chunk gap is bounded by the producer's liveness budget,
        # not the plain network chunk timeout
        chunk_timeout = max(chunk_timeout, 120.0)
    host, port = desc.addr.rsplit(":", 1)
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, int(port)), connect_timeout
        )
    except (OSError, TimeoutError, asyncio.TimeoutError) as e:
        # gaierror/refused/unroutable: the advertised addr stopped
        # resolving — typed so callers fall back instead of crashing
        raise KvTransferError(f"kv data plane {desc.addr} unreachable: {e}") from e
    try:
        tid = desc.transfer_id.encode()
        writer.write(_HDR.pack(_MAGIC, len(tid)) + tid)
        await writer.drain()
        np_dtype = _np_dtype(desc.dtype)
        shape = tuple(desc.page_shape)
        # every frame size the peer sends is checked against what the
        # descriptor implies — a misbehaving peer cannot force a huge alloc
        max_chunk_bytes = (
            int(np.prod(shape)) * np_dtype.itemsize * max(desc.chunk_pages, 1)
        )
        while True:
            hdr = await asyncio.wait_for(reader.readexactly(_HDR.size), chunk_timeout)
            magic, length = _HDR.unpack(hdr)
            if magic != _MAGIC:
                raise RuntimeError(f"bad kv frame magic {magic:#x}")
            if length > 65536:
                raise RuntimeError(f"oversized kv frame header ({length} bytes)")
            header = msgpack.unpackb(
                await asyncio.wait_for(reader.readexactly(length), chunk_timeout),
                raw=False,
            )
            if header.get("error"):
                raise RuntimeError(f"kv transfer refused: {header['error']}")
            if header.get("eof"):
                return
            off, n = header["off"], header["n"]
            if not (0 <= off and 0 < n <= desc.chunk_pages and off + n <= desc.n_pages):
                raise RuntimeError(f"kv chunk out of range (off={off} n={n})")
            if header["k_bytes"] > max_chunk_bytes or header["v_bytes"] > max_chunk_bytes:
                raise RuntimeError(
                    f"kv frame larger than descriptor allows ({header['k_bytes']})"
                )
            k_raw = await asyncio.wait_for(
                reader.readexactly(header["k_bytes"]), chunk_timeout
            )
            v_raw = await asyncio.wait_for(
                reader.readexactly(header["v_bytes"]), chunk_timeout
            )
            chunk_shape = (shape[0], n, *shape[1:])
            k = np.frombuffer(k_raw, dtype=np_dtype).reshape(chunk_shape)
            v = np.frombuffer(v_raw, dtype=np_dtype).reshape(chunk_shape)
            await inject(off, n, k, v)
    finally:
        writer.close()
