"""Wire protocols for the LLM serving pipeline.

Mirrors reference lib/llm/src/protocols/: OpenAI request/response types
(chat + completions + embeddings), the engine-facing PreprocessedRequest /
LLMEngineOutput pair, and the Annotated<T> SSE event wrapper.
"""

from .openai import (
    ChatCompletionChunk,
    ChatCompletionRequest,
    ChatCompletionResponse,
    ChatMessage,
    Choice,
    ChoiceDelta,
    CompletionChoice,
    CompletionChunk,
    CompletionRequest,
    CompletionResponse,
    EmbeddingRequest,
    EmbeddingResponse,
    ModelInfo,
    ModelList,
    NvExt,
    Usage,
)
from .common import (
    Annotated,
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

__all__ = [
    "Annotated",
    "ChatCompletionChunk",
    "ChatCompletionRequest",
    "ChatCompletionResponse",
    "ChatMessage",
    "Choice",
    "ChoiceDelta",
    "CompletionChoice",
    "CompletionChunk",
    "CompletionRequest",
    "CompletionResponse",
    "EmbeddingRequest",
    "EmbeddingResponse",
    "FinishReason",
    "LLMEngineOutput",
    "ModelInfo",
    "ModelList",
    "NvExt",
    "PreprocessedRequest",
    "SamplingOptions",
    "StopConditions",
    "Usage",
]
