"""Worker forward-pass metrics structures + aggregation.

Role of the reference's ForwardPassMetrics family
(lib/bindings/python/src/dynamo/_core.pyi:231-335, published by
WorkerMetricsPublisher kv_router/publisher.rs:684 and scraped via NATS
$SRV.STATS transports/nats.rs:107): typed load stats each worker publishes
every interval, consumed by the KV router's scheduler and aggregated for
observability.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ...runtime.metrics import (
    KV_ACTIVE_BLOCKS,
    KV_TOTAL_BLOCKS,
    NUM_RUNNING_REQS,
    NUM_WAITING_REQS,
)


@dataclass
class WorkerStats:
    request_active_slots: int = 0
    request_total_slots: int = 0
    num_requests_waiting: int = 0
    data_parallel_rank: Optional[int] = None


@dataclass
class KvStats:
    kv_active_blocks: int = 0
    kv_total_blocks: int = 1
    gpu_cache_usage_perc: float = 0.0
    gpu_prefix_cache_hit_rate: float = 0.0


@dataclass
class SpecDecodeStats:
    num_spec_tokens: int = 0
    num_drafts: int = 0
    num_draft_tokens: int = 0
    num_accepted_tokens: int = 0
    num_accepted_tokens_per_pos: Optional[list] = None


@dataclass
class ForwardPassMetrics:
    worker_stats: WorkerStats = field(default_factory=WorkerStats)
    kv_stats: KvStats = field(default_factory=KvStats)
    spec_decode_stats: Optional[SpecDecodeStats] = None

    def to_dict(self) -> dict:
        d = {
            **dataclasses.asdict(self.worker_stats),
            **dataclasses.asdict(self.kv_stats),
        }
        if self.spec_decode_stats is not None:
            d["spec_decode"] = dataclasses.asdict(self.spec_decode_stats)
        return d

    @classmethod
    def from_stats_dict(cls, d: Dict[str, Any]) -> "ForwardPassMetrics":
        """Build from an engine stats() blob (unknown keys ignored, so engine
        dialects — vLLM-style names included — parse)."""
        ws = WorkerStats(
            request_active_slots=int(
                d.get("request_active_slots", d.get(NUM_RUNNING_REQS, 0))
            ),
            request_total_slots=int(d.get("request_total_slots", 0)),
            num_requests_waiting=int(
                d.get("num_requests_waiting", d.get(NUM_WAITING_REQS, 0))
            ),
            data_parallel_rank=d.get("data_parallel_rank"),
        )
        ks = KvStats(
            kv_active_blocks=int(d.get(KV_ACTIVE_BLOCKS, 0)),
            kv_total_blocks=max(int(d.get(KV_TOTAL_BLOCKS, 1)), 1),
            gpu_cache_usage_perc=float(d.get("gpu_cache_usage_perc", 0.0)),
            gpu_prefix_cache_hit_rate=float(d.get("gpu_prefix_cache_hit_rate", 0.0)),
        )
        sd = None
        if "spec_decode" in d:
            sd = SpecDecodeStats(**{
                k: v for k, v in d["spec_decode"].items()
                if k in {f.name for f in dataclasses.fields(SpecDecodeStats)}
            })
        return cls(worker_stats=ws, kv_stats=ks, spec_decode_stats=sd)


class KvMetricsAggregator:
    """Latest ForwardPassMetrics per worker, fed from the kv_metrics topic
    (reference KvMetricsAggregator _core.pyi; the router's scheduler keeps
    its own copy — this one serves observability endpoints)."""

    def __init__(self):
        self._by_worker: Dict[int, ForwardPassMetrics] = {}

    def update(self, worker_id: int, stats: Dict[str, Any]) -> None:
        self._by_worker[worker_id] = ForwardPassMetrics.from_stats_dict(stats)

    def remove_worker(self, worker_id: int) -> None:
        self._by_worker.pop(worker_id, None)

    @property
    def workers(self) -> Dict[int, ForwardPassMetrics]:
        return dict(self._by_worker)

    def totals(self) -> dict:
        ms = list(self._by_worker.values())
        if not ms:
            return {
                "num_workers": 0, "active_slots": 0, "total_slots": 0,
                "waiting": 0, "kv_active_blocks": 0, "kv_total_blocks": 0,
                "avg_cache_usage": 0.0,
            }
        return {
            "num_workers": len(ms),
            "active_slots": sum(m.worker_stats.request_active_slots for m in ms),
            "total_slots": sum(m.worker_stats.request_total_slots for m in ms),
            "waiting": sum(m.worker_stats.num_requests_waiting for m in ms),
            "kv_active_blocks": sum(m.kv_stats.kv_active_blocks for m in ms),
            "kv_total_blocks": sum(m.kv_stats.kv_total_blocks for m in ms),
            "avg_cache_usage": sum(m.kv_stats.gpu_cache_usage_perc for m in ms)
            / len(ms),
        }
