"""OpenAI-compatible API types (pydantic).

Mirrors the reference's vendored async-openai types + NVIDIA `nvext`
extension (lib/async-openai/src/types/, lib/llm/src/protocols/openai/).
Only the fields the serving path interprets are modeled strictly; unknown
fields are preserved (model_config extra="allow") for BYOT-style pass-through.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import BaseModel, ConfigDict, Field, StrictBool


class NvExt(BaseModel):
    """NVIDIA extension block (reference protocols/openai/nvext.rs):
    per-request knobs outside the OpenAI schema."""

    model_config = ConfigDict(extra="allow")

    ignore_eos: Optional[bool] = None
    greed_sampling: Optional[bool] = None
    annotations: Optional[List[str]] = None  # e.g. ["kv_hit_rate", "worker_id"]
    backend_instance_id: Optional[int] = None  # pin to a worker
    router_config_override: Optional[Dict[str, Any]] = None
    # guided decoding (reference nvext.rs:73-88); enforced natively by the
    # JAX engine via token-level FSM logit masks (llm/guided.py)
    guided_json: Optional[Union[Dict[str, Any], str]] = None
    guided_regex: Optional[str] = None
    guided_choice: Optional[List[str]] = None
    guided_grammar: Optional[str] = None  # EBNF: rejected with 400 (unsupported)
    # multi-LoRA: select a served adapter by name (models/lora.py; the
    # worker's model card advertises available adapters)
    lora_name: Optional[str] = None
    # scheduling priority under DYN_SCHED_POLICY=sla (engine/scheduler/):
    # each +1 halves the request's TTFT target, each -1 doubles it
    priority: Optional[int] = None


class FunctionCall(BaseModel):
    model_config = ConfigDict(extra="allow")
    name: Optional[str] = None
    arguments: Optional[str] = None


class ToolCall(BaseModel):
    model_config = ConfigDict(extra="allow")
    id: Optional[str] = None
    type: str = "function"
    function: Optional[FunctionCall] = None
    index: Optional[int] = None


class ChatMessage(BaseModel):
    model_config = ConfigDict(extra="allow")

    role: str
    content: Optional[Union[str, List[Dict[str, Any]]]] = None
    name: Optional[str] = None
    tool_calls: Optional[List[ToolCall]] = None
    tool_call_id: Optional[str] = None
    reasoning_content: Optional[str] = None


class StreamOptions(BaseModel):
    model_config = ConfigDict(extra="allow")
    include_usage: Optional[bool] = None


class ChatCompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")

    model: str
    messages: List[ChatMessage]
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None  # common extension
    n: Optional[int] = 1
    stream: Optional[bool] = False
    stream_options: Optional[StreamOptions] = None
    stop: Optional[Union[str, List[str]]] = None
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    min_tokens: Optional[int] = None
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    logit_bias: Optional[Dict[str, float]] = None
    logprobs: Optional[bool] = None
    top_logprobs: Optional[int] = None
    user: Optional[str] = None
    seed: Optional[int] = None
    tools: Optional[List[Dict[str, Any]]] = None
    tool_choice: Optional[Union[str, Dict[str, Any]]] = None
    parallel_tool_calls: Optional[bool] = None
    response_format: Optional[Dict[str, Any]] = None
    chat_template_args: Optional[Dict[str, Any]] = None
    nvext: Optional[NvExt] = None


class CompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")

    model: str
    prompt: Union[str, List[str], List[int], List[List[int]]]
    suffix: Optional[str] = None
    max_tokens: Optional[int] = None
    min_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    n: Optional[int] = 1
    stream: Optional[bool] = False
    stream_options: Optional[StreamOptions] = None
    # StrictBool first: an explicit `false` must survive parsing as False
    # (plain int would coerce it to 0 == the legacy sampled-token ask)
    logprobs: Optional[Union[StrictBool, int]] = None
    echo: Optional[bool] = False
    stop: Optional[Union[str, List[str]]] = None
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    seed: Optional[int] = None
    user: Optional[str] = None
    nvext: Optional[NvExt] = None


class Usage(BaseModel):
    model_config = ConfigDict(extra="allow")
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class LogProbEntry(BaseModel):
    model_config = ConfigDict(extra="allow")
    token: str
    logprob: float
    bytes: Optional[List[int]] = None
    top_logprobs: Optional[List[Dict[str, Any]]] = None


class ChoiceLogProbs(BaseModel):
    model_config = ConfigDict(extra="allow")
    content: Optional[List[LogProbEntry]] = None


def chat_logprobs(entries) -> Optional[ChoiceLogProbs]:
    """[{token, logprob}] (backend logprob_entries) → chat logprobs
    object — the ONE builder every chat surface uses."""
    if not entries:
        return None
    return ChoiceLogProbs(content=[LogProbEntry(**e) for e in entries])


def completion_logprobs(entries, base_offset: int = 0) -> Optional[Dict[str, Any]]:
    """[{token, logprob}] → the legacy completions logprobs object.
    `base_offset`: chars already streamed (offsets index the ACCUMULATED
    text, so chunked emission must carry the running total)."""
    if not entries:
        return None
    tops = None
    if any(e.get("top_logprobs") for e in entries):
        tops = [
            {t["token"]: t["logprob"] for t in e.get("top_logprobs") or []}
            for e in entries
        ]
    offsets, pos = [], base_offset
    for e in entries:
        offsets.append(pos)
        pos += len(e["token"])
    return {
        "tokens": [e["token"] for e in entries],
        "token_logprobs": [e["logprob"] for e in entries],
        "top_logprobs": tops,
        "text_offset": offsets,
    }


class ChoiceDelta(BaseModel):
    model_config = ConfigDict(extra="allow")
    role: Optional[str] = None
    content: Optional[str] = None
    reasoning_content: Optional[str] = None
    tool_calls: Optional[List[ToolCall]] = None


class StreamChoice(BaseModel):
    model_config = ConfigDict(extra="allow")
    index: int = 0
    delta: ChoiceDelta = Field(default_factory=ChoiceDelta)
    finish_reason: Optional[str] = None
    logprobs: Optional[ChoiceLogProbs] = None


class ChatCompletionChunk(BaseModel):
    model_config = ConfigDict(extra="allow")
    id: str
    object: str = "chat.completion.chunk"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[StreamChoice] = Field(default_factory=list)
    usage: Optional[Usage] = None
    system_fingerprint: Optional[str] = None


class Choice(BaseModel):
    model_config = ConfigDict(extra="allow")
    index: int = 0
    message: ChatMessage = Field(default_factory=lambda: ChatMessage(role="assistant"))
    finish_reason: Optional[str] = None
    logprobs: Optional[ChoiceLogProbs] = None


class ChatCompletionResponse(BaseModel):
    model_config = ConfigDict(extra="allow")
    id: str
    object: str = "chat.completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[Choice] = Field(default_factory=list)
    usage: Optional[Usage] = None


class CompletionChoice(BaseModel):
    model_config = ConfigDict(extra="allow")
    index: int = 0
    text: str = ""
    finish_reason: Optional[str] = None
    logprobs: Optional[Dict[str, Any]] = None


class CompletionChunk(BaseModel):
    model_config = ConfigDict(extra="allow")
    id: str
    object: str = "text_completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[CompletionChoice] = Field(default_factory=list)
    usage: Optional[Usage] = None


class CompletionResponse(BaseModel):
    model_config = ConfigDict(extra="allow")
    id: str
    object: str = "text_completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[CompletionChoice] = Field(default_factory=list)
    usage: Optional[Usage] = None


class EmbeddingRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    input: Union[str, List[str], List[int], List[List[int]]]
    encoding_format: Optional[Literal["float", "base64"]] = "float"
    dimensions: Optional[int] = None
    user: Optional[str] = None


class EmbeddingResponse(BaseModel):
    model_config = ConfigDict(extra="allow")
    object: str = "list"
    data: List[Dict[str, Any]] = Field(default_factory=list)
    model: str = ""
    usage: Optional[Usage] = None


class ModelInfo(BaseModel):
    model_config = ConfigDict(extra="allow")
    id: str
    object: str = "model"
    created: int = Field(default_factory=lambda: int(time.time()))
    owned_by: str = "dynamo-tpu"


class ModelList(BaseModel):
    object: str = "list"
    data: List[ModelInfo] = Field(default_factory=list)
