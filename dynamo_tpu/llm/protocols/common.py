"""Engine-facing protocol types.

Mirrors reference lib/llm/src/protocols/common/: `PreprocessedRequest` (the
tokenized request that crosses the network to workers), `LLMEngineOutput`
(per-step engine emission), `StopConditions`/`SamplingOptions`, and the
`Annotated<T>` event wrapper used on every response stream
(lib/llm/src/protocols/annotated.rs).

These are plain dicts on the wire (msgpack); the dataclasses here are the
typed construction/validation layer.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class FinishReason:
    STOP = "stop"
    LENGTH = "length"
    EOS = "eos"
    CANCELLED = "cancelled"
    CONTENT_FILTER = "content_filter"
    ERROR = "error"


@dataclass
class StopConditions:
    """When to stop generating (reference common/preprocessor.rs StopConditions)."""

    max_tokens: Optional[int] = None
    stop: Optional[List[str]] = None  # stop strings (detokenizer-side)
    stop_token_ids: Optional[List[int]] = None  # engine-side
    min_tokens: Optional[int] = None
    ignore_eos: bool = False

    def to_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items() if v not in (None, False)}


@dataclass
class SamplingOptions:
    """Sampling controls (reference common/preprocessor.rs SamplingOptions)."""

    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    seed: Optional[int] = None
    n: int = 1
    logprobs: Optional[int] = None

    def to_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items() if v is not None}


@dataclass
class PreprocessedRequest:
    """The tokenized request routed to engine workers
    (reference lib/llm/src/protocols/common/preprocessor.rs).

    `token_ids` is the full prompt; `batch_token_ids` reserved for n>1.
    `sampling_options`/`stop_conditions` are engine-interpretable;
    `annotations` request extra events (e.g. kv-hit-rate); `router` carries
    per-request router overrides (reference RouterConfigOverride);
    `disagg_params` carries the KV-transfer descriptors during
    prefill/decode disaggregation (NIXL-metadata role).
    """

    token_ids: List[int]
    model: str = ""
    sampling_options: Dict[str, Any] = field(default_factory=dict)
    stop_conditions: Dict[str, Any] = field(default_factory=dict)
    eos_token_ids: List[int] = field(default_factory=list)
    annotations: List[str] = field(default_factory=list)
    router: Dict[str, Any] = field(default_factory=dict)
    disagg_params: Optional[Dict[str, Any]] = None
    request_id: str = ""
    estimated_prefix_hit_num_blocks: Optional[int] = None
    # cluster KV fabric holder hint (KvPushRouter → worker): the worker
    # whose cache holds this request's longest prefix, per the router's
    # radix index — {"instance": id, "blocks": matched}. The admission
    # path uses it to pull those blocks from the holder's tiers over the
    # KV data plane instead of recomputing (docs/kvbm.md); advisory only,
    # a wrong/stale hint degrades to recompute.
    kv_holder: Optional[Dict[str, Any]] = None
    embed: bool = False  # embeddings request: engine returns {"embedding": [...]}
    # multimodal content parts extracted from the chat request (reference
    # multimodal E/P/D protocol surface, components/backends/trtllm):
    # [{"type": "image_url", "url": ..., "position": <token offset>}].
    # Engines without multimodal support must REJECT, not silently drop.
    multimodal: Optional[List[Dict[str, Any]]] = None
    # guided-decoding spec ({"kind": "regex"|"choice"|"json_schema"|
    # "json_object", ...}) normalized from response_format / nvext by
    # llm/guided.extract_guided_spec; engines compile it to a token FSM
    guided: Optional[Dict[str, Any]] = None
    # multi-LoRA adapter selection (nvext.lora_name). Salts the token
    # block hashes (reference protocols.rs:110-115 lora_id) so router +
    # prefix cache + KVBM never share KV across adapters.
    lora_name: Optional[str] = None
    # scheduling priority (nvext.priority, engine/scheduler/): each +1
    # halves the request's TTFT target (tighter EDF deadline), each -1
    # doubles it. 0 = default class. Only consulted under
    # DYN_SCHED_POLICY=sla; fifo ignores it.
    priority: int = 0
    # tenant key (dynogate, docs/overload.md): set by the frontend from
    # the DYN_GATE_TENANT_HEADER request header. Drives the gate's
    # weighted-fair queueing / rate limits at the edge and the
    # StepPlanner's per-tenant fairness tiebreak in the worker. None =
    # the 'default' tenant.
    tenant: Optional[str] = None
    # migration retry ordinal (llm/migration.py): > 0 marks a request
    # that RESUMES a stream lost to a worker death — token_ids is the
    # original prompt plus the tokens already delivered to the client.
    # Engines classify the resume source (checkpoint/peer/local/
    # recompute) and count what the death cost (docs/fault_tolerance.md).
    migration: int = 0

    def to_dict(self) -> dict:
        d = {
            "token_ids": self.token_ids,
            "model": self.model,
            "sampling_options": self.sampling_options,
            "stop_conditions": self.stop_conditions,
            "eos_token_ids": self.eos_token_ids,
            "request_id": self.request_id,
        }
        if self.annotations:
            d["annotations"] = self.annotations
        if self.router:
            d["router"] = self.router
        if self.disagg_params is not None:
            d["disagg_params"] = self.disagg_params
        if self.estimated_prefix_hit_num_blocks is not None:
            d["estimated_prefix_hit_num_blocks"] = self.estimated_prefix_hit_num_blocks
        if self.kv_holder is not None:
            d["kv_holder"] = self.kv_holder
        if self.embed:
            d["embed"] = True
        if self.multimodal:
            d["multimodal"] = self.multimodal
        if self.guided:
            d["guided"] = self.guided
        if self.lora_name:
            d["lora_name"] = self.lora_name
        if self.priority:
            d["priority"] = self.priority
        if self.tenant:
            d["tenant"] = self.tenant
        if self.migration:
            d["migration"] = self.migration
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "PreprocessedRequest":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclass
class LLMEngineOutput:
    """One engine emission: newly generated tokens for a request
    (reference lib/llm/src/protocols/common/llm_backend.rs LLMEngineOutput)."""

    token_ids: List[int] = field(default_factory=list)
    text: Optional[str] = None  # engines may pre-detokenize (mocker does not)
    cum_log_probs: Optional[float] = None
    log_probs: Optional[List[float]] = None
    top_logprobs: Optional[List[Dict[str, Any]]] = None
    finish_reason: Optional[str] = None
    kv_transfer_params: Optional[Dict[str, Any]] = None
    completion_usage: Optional[Dict[str, int]] = None
    disagg_info: Optional[Dict[str, Any]] = None
    # set by the parsers/jail layer, not by engines
    tool_calls: Optional[List[Dict[str, Any]]] = None
    reasoning_content: Optional[str] = None
    # set by the detokenizer backend when the request asked for logprobs:
    # [{"token": <delta text>, "logprob": f}] aligned with token_ids
    logprob_entries: Optional[List[Dict[str, Any]]] = None

    def to_dict(self) -> dict:
        d: Dict[str, Any] = {"token_ids": self.token_ids}
        for k in (
            "text",
            "cum_log_probs",
            "log_probs",
            "top_logprobs",
            "finish_reason",
            "kv_transfer_params",
            "completion_usage",
            "disagg_info",
            "tool_calls",
            "reasoning_content",
            "logprob_entries",
        ):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "LLMEngineOutput":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclass
class Annotated:
    """SSE event wrapper: data plus optional event name / comments
    (reference lib/llm/src/protocols/annotated.rs Annotated<T>).

    Events carry out-of-band annotations (kv-hit-rate, worker-id, errors)
    alongside the data stream without breaking OpenAI framing.
    """

    data: Optional[Any] = None
    id: Optional[str] = None
    event: Optional[str] = None
    comment: Optional[List[str]] = None

    def is_error(self) -> bool:
        return self.event == "error"

    def to_dict(self) -> dict:
        d: Dict[str, Any] = {}
        if self.data is not None:
            d["data"] = self.data
        if self.id is not None:
            d["id"] = self.id
        if self.event is not None:
            d["event"] = self.event
        if self.comment:
            d["comment"] = self.comment
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Annotated":
        if not isinstance(d, dict) or not (set(d) <= {"data", "id", "event", "comment"}):
            return cls(data=d)
        return cls(**d)

    @classmethod
    def from_error(cls, message: str) -> "Annotated":
        return cls(data=None, event="error", comment=[message])

    @classmethod
    def from_annotation(cls, name: str, value: Any) -> "Annotated":
        import json

        # compact separators: annotation comments ride the SSE stream
        return cls(
            data=None, event=name,
            comment=[json.dumps(value, separators=(",", ":"))],
        )
