"""KServe gRPC frontend (reference lib/llm/src/grpc/service/kserve.rs:33).

`kserve_pb2.py` is generated from kserve.proto by plain `protoc
--python_out` (the image has no grpc_tools plugin); service stubs are not
needed — service.py registers the RPC methods through grpc.aio generic
handlers. If the generated file is missing, import regenerates it.
"""

from __future__ import annotations


def _ensure_pb2():
    try:
        from . import kserve_pb2  # noqa: F401
    except ImportError:
        import pathlib
        import subprocess

        here = pathlib.Path(__file__).parent
        subprocess.run(
            ["protoc", "--python_out=.", "kserve.proto"],
            cwd=str(here), check=True,
        )


_ensure_pb2()

from . import kserve_pb2  # noqa: E402,F401
from .service import KserveGrpcService  # noqa: E402,F401

__all__ = ["KserveGrpcService", "kserve_pb2"]
