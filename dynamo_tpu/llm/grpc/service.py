"""KServe gRPC frontend: the L5 tensor-protocol surface.

Mirrors the reference KServe service (lib/llm/src/grpc/service/kserve.rs:33,
protos lib/llm/src/grpc/protos/kserve.proto): ServerLive/ServerReady/
ServerMetadata/ModelReady/ModelMetadata plus ModelInfer (unary) and
ModelStreamInfer (decoupled streaming) over the Open Inference Protocol v2.

LLM tensor mapping (Triton-style): input "text_input" (BYTES, [1]) with
request parameters max_tokens / temperature / ignore_eos; output
"text_output" (BYTES) plus completion token counts in response parameters.
Requests flow through the SAME ModelPipeline chain as the HTTP frontend
(preprocessor -> backend -> migration -> router), so routing, migration and
metrics behave identically across protocols.

No generated service stubs (the image lacks the protoc gRPC plugin):
methods register through grpc.aio generic handlers, which is wire-identical.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

import grpc

from ...runtime.engine import Context
from ..protocols import CompletionRequest
from . import kserve_pb2 as pb

logger = logging.getLogger(__name__)

SERVICE = "inference.GRPCInferenceService"

# max concurrently-served requests per decoupled stream; pipelined requests
# beyond this queue at the stream reader (backpressure via flow control)
MAX_STREAM_INFLIGHT = 32


def _param(p: "pb.InferParameter"):
    which = p.WhichOneof("parameter_choice")
    return getattr(p, which) if which else None


def _get_text_input(req: "pb.ModelInferRequest") -> str:
    for i, t in enumerate(req.inputs):
        if t.name == "text_input":
            if t.contents.bytes_contents:
                return t.contents.bytes_contents[0].decode("utf-8", "replace")
            if req.raw_input_contents and i < len(req.raw_input_contents):
                raw = req.raw_input_contents[i]
                # raw BYTES tensors are length-prefixed (little-endian u32)
                if len(raw) >= 4:
                    n = int.from_bytes(raw[:4], "little")
                    return raw[4 : 4 + n].decode("utf-8", "replace")
    raise ValueError("missing BYTES input tensor 'text_input'")


class KserveGrpcService:
    """The gRPC frontend server; runs beside the HTTP service on the same
    ModelManager."""

    def __init__(self, manager, host: str = "0.0.0.0", port: int = 8001):
        self.manager = manager
        self.host, self.port = host, port
        self._server: Optional[grpc.aio.Server] = None

    # -- unary handlers -------------------------------------------------- #

    async def _server_live(self, request, context) -> "pb.ServerLiveResponse":
        return pb.ServerLiveResponse(live=True)

    async def _server_ready(self, request, context) -> "pb.ServerReadyResponse":
        return pb.ServerReadyResponse(ready=bool(self.manager.names()))

    async def _server_metadata(self, request, context):
        return pb.ServerMetadataResponse(
            name="dynamo-tpu", version="0", extensions=["model_repository"]
        )

    async def _model_ready(self, request, context) -> "pb.ModelReadyResponse":
        return pb.ModelReadyResponse(
            ready=self.manager.get(request.name) is not None
        )

    async def _model_metadata(self, request, context):
        pipeline = self.manager.get(request.name)
        if pipeline is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"model {request.name!r} not found"
            )
        return pb.ModelMetadataResponse(
            name=request.name,
            versions=["1"],
            platform="dynamo-tpu",
            inputs=[
                pb.ModelMetadataResponse.TensorMetadata(
                    name="text_input", datatype="BYTES", shape=[1]
                )
            ],
            outputs=[
                pb.ModelMetadataResponse.TensorMetadata(
                    name="text_output", datatype="BYTES", shape=[1]
                )
            ],
        )

    # -- inference ------------------------------------------------------- #

    def _to_completion(self, req: "pb.ModelInferRequest") -> CompletionRequest:
        params = {k: _param(v) for k, v in req.parameters.items()}
        return CompletionRequest(
            model=req.model_name,
            prompt=_get_text_input(req),
            max_tokens=int(params.get("max_tokens") or 16),
            temperature=float(params.get("temperature") or 0.0),
            stream=False,
        )

    async def _run(self, req: "pb.ModelInferRequest", context, on_delta=None,
                   abort_on_error: bool = True):
        """abort_on_error=False (the streaming path) raises instead of
        aborting: context.abort tears down the WHOLE RPC, which on a
        multiplexed decoupled stream would kill the other in-flight
        requests sharing it."""
        pipeline = self.manager.get(req.model_name)
        if pipeline is None:
            if not abort_on_error:
                raise ValueError(f"model {req.model_name!r} not found")
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"model {req.model_name!r} not found"
            )
        creq = self._to_completion(req)
        ctx = Context()
        pre = await pipeline.preprocessor.preprocess_completion_async(creq)
        texts, n_out, finish = [], 0, "stop"
        try:
            async for ann in pipeline.generate_preprocessed(pre, ctx):
                if ann.is_error():
                    msg = (ann.comment or ["engine error"])[0]
                    if not abort_on_error:
                        raise RuntimeError(msg)
                    await context.abort(grpc.StatusCode.INTERNAL, msg)
                if ann.event is not None:
                    continue
                out = ann.data
                n_out += len(out.token_ids or [])
                if out.text:
                    texts.append(out.text)
                    if on_delta is not None:
                        await on_delta(out.text, n_out, None)
                if out.finish_reason:
                    finish = "stop" if out.finish_reason == "eos" else out.finish_reason
                    break
        finally:
            ctx.stop_generating()
        return "".join(texts), n_out, len(pre.token_ids), finish

    @staticmethod
    def _infer_response(
        req, text: str, n_out: int, n_in: int, finish: str, final: bool = True
    ) -> "pb.ModelInferResponse":
        resp = pb.ModelInferResponse(
            model_name=req.model_name, model_version="1", id=req.id
        )
        t = resp.outputs.add()
        t.name = "text_output"
        t.datatype = "BYTES"
        t.shape.append(1)
        t.contents.bytes_contents.append(text.encode())
        resp.parameters["completion_tokens"].int64_param = n_out
        resp.parameters["prompt_tokens"].int64_param = n_in
        resp.parameters["finish_reason"].string_param = finish
        resp.parameters["final"].bool_param = final
        return resp

    async def _model_infer(self, request, context) -> "pb.ModelInferResponse":
        try:
            text, n_out, n_in, finish = await self._run(request, context)
        except ValueError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return self._infer_response(request, text, n_out, n_in, finish)

    async def _model_stream_infer(self, request_iterator, context):
        """Decoupled streaming: requests pipelined on one stream run
        CONCURRENTLY — a task per incoming request, responses multiplexed
        onto the stream as they arrive (each response carries the request
        id, so interleaving is disambiguated). A request's sequence ends
        with final=true; the RPC ends when the client closes its side and
        every in-flight request has finished (the reference's decoupled
        semantics, kserve.rs:33)."""
        out: asyncio.Queue = asyncio.Queue()
        tasks: set = set()
        # backpressure: the old serialized handler held one request in
        # flight; concurrency must not mean a pipelining client can force
        # unbounded tasks + queued engine work
        gate = asyncio.Semaphore(MAX_STREAM_INFLIGHT)

        async def run_one(req):
            async def on_delta(text, n_out, _finish):
                out.put_nowait(
                    pb.ModelStreamInferResponse(
                        infer_response=self._infer_response(
                            req, text, n_out, 0, "", final=False
                        )
                    )
                )

            try:
                text, n_out, n_in, finish = await self._run(
                    req, context, on_delta=on_delta, abort_on_error=False
                )
                out.put_nowait(
                    pb.ModelStreamInferResponse(
                        infer_response=self._infer_response(
                            req, "", n_out, n_in, finish, final=True
                        )
                    )
                )
            except Exception as e:  # noqa: BLE001 — surfaced on-stream
                # error frame still carries the request id and final=true so
                # the client can attribute it and stop waiting on this id
                out.put_nowait(
                    pb.ModelStreamInferResponse(
                        error_message=str(e),
                        infer_response=self._infer_response(
                            req, "", 0, 0, "error", final=True
                        ),
                    )
                )
            finally:
                gate.release()

        async def pump():
            async for req in request_iterator:
                await gate.acquire()
                t = asyncio.create_task(run_one(req))
                tasks.add(t)
                t.add_done_callback(tasks.discard)
            if tasks:  # client closed its side; drain in-flight requests
                await asyncio.gather(*list(tasks), return_exceptions=True)
            out.put_nowait(None)

        pump_task = asyncio.create_task(pump())
        try:
            while True:
                item = await out.get()
                if item is None:
                    break
                yield item
        finally:
            pump_task.cancel()
            for t in list(tasks):
                t.cancel()

    # -- server lifecycle ------------------------------------------------ #

    def _handlers(self):
        rpcs = {
            "ServerLive": grpc.unary_unary_rpc_method_handler(
                self._server_live,
                request_deserializer=pb.ServerLiveRequest.FromString,
                response_serializer=pb.ServerLiveResponse.SerializeToString,
            ),
            "ServerReady": grpc.unary_unary_rpc_method_handler(
                self._server_ready,
                request_deserializer=pb.ServerReadyRequest.FromString,
                response_serializer=pb.ServerReadyResponse.SerializeToString,
            ),
            "ServerMetadata": grpc.unary_unary_rpc_method_handler(
                self._server_metadata,
                request_deserializer=pb.ServerMetadataRequest.FromString,
                response_serializer=pb.ServerMetadataResponse.SerializeToString,
            ),
            "ModelReady": grpc.unary_unary_rpc_method_handler(
                self._model_ready,
                request_deserializer=pb.ModelReadyRequest.FromString,
                response_serializer=pb.ModelReadyResponse.SerializeToString,
            ),
            "ModelMetadata": grpc.unary_unary_rpc_method_handler(
                self._model_metadata,
                request_deserializer=pb.ModelMetadataRequest.FromString,
                response_serializer=pb.ModelMetadataResponse.SerializeToString,
            ),
            "ModelInfer": grpc.unary_unary_rpc_method_handler(
                self._model_infer,
                request_deserializer=pb.ModelInferRequest.FromString,
                response_serializer=pb.ModelInferResponse.SerializeToString,
            ),
            "ModelStreamInfer": grpc.stream_stream_rpc_method_handler(
                self._model_stream_infer,
                request_deserializer=pb.ModelInferRequest.FromString,
                response_serializer=pb.ModelStreamInferResponse.SerializeToString,
            ),
        }
        return grpc.method_handlers_generic_handler(SERVICE, rpcs)

    async def start(self) -> int:
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((self._handlers(),))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        await self._server.start()
        logger.info("KServe gRPC service listening on %s:%d", self.host, self.port)
        return self.port

    async def stop(self):
        if self._server is not None:
            await self._server.stop(grace=1.0)
