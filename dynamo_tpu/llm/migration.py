"""Request migration: resume in-flight requests on surviving workers.

Mirrors reference lib/llm/src/migration.rs (Migration :26, RetryManager
:82-158): when a worker dies mid-stream (StreamLost), re-issue the request —
minus the tokens already produced — to another worker, up to
`migration_limit` times. The client sees one uninterrupted stream.

Durable decode sessions (docs/fault_tolerance.md "Request migration"):
the retry request is fabric-aware. It names the dead worker(s) in
`router.exclude_instances` (routers never re-dial the corpse, even while
its lease lingers), drops any `kv_holder` hint or per-attempt disagg
transfer descriptor that points at a dead instance (a stale hint would
pin the survivor's KV onboard to the corpse), and carries a `migration`
ordinal so the survivor classifies + counts the resume source
(checkpoint / peer / local / recompute). With incremental commit and
session checkpointing live, the survivor onboards the session prefix
through the three-arm onboard budget and recomputes only the
un-checkpointed tail — a death costs a tail, not a prefill.
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator, Optional

from ..runtime.backoff import Backoff
from ..runtime.engine import AsyncEngine, Context
from ..runtime.pipeline import Operator
from ..runtime.request_plane import DeadlineExceeded, StreamLost
from .protocols import Annotated, LLMEngineOutput, PreprocessedRequest

logger = logging.getLogger(__name__)


class MigrationMetrics:
    """Process-wide frontend migration counters, rendered onto /metrics
    beside the prometheus_client registry (dynogate's hand-assembled
    pattern). One instance per frontend process; plain ints mutated on
    the event loop only."""

    def __init__(self):
        self.migrations = 0            # retries actually issued
        self.replayed_tokens = 0       # emitted tokens re-sent in retry prompts
        self.exhausted = 0             # streams that died past the budget

    def render_prometheus(self) -> bytes:
        lines = [
            "# HELP dynamo_frontend_migrations_total Stream migrations "
            "(retries after a worker death)",
            "# TYPE dynamo_frontend_migrations_total counter",
            f"dynamo_frontend_migrations_total {self.migrations}",
            "# HELP dynamo_frontend_migration_replayed_tokens_total "
            "Already-delivered tokens re-sent in migration retry prompts",
            "# TYPE dynamo_frontend_migration_replayed_tokens_total counter",
            f"dynamo_frontend_migration_replayed_tokens_total "
            f"{self.replayed_tokens}",
            "# HELP dynamo_frontend_migrations_exhausted_total Streams "
            "lost after the migration budget ran out",
            "# TYPE dynamo_frontend_migrations_exhausted_total counter",
            f"dynamo_frontend_migrations_exhausted_total {self.exhausted}",
        ]
        return ("\n".join(lines) + "\n").encode()


MIGRATION_METRICS = MigrationMetrics()


class Migration(Operator):
    """Operator wrapping the network hop with retry-on-stream-death
    (reference Migration migration.rs:26). As a pipeline node it OWNS the
    downstream call (`around`) — a retry loop cannot be expressed as a
    stream wrapper; as a classic engine wrapper it uses `inner`."""

    def __init__(self, inner: Optional[AsyncEngine] = None, migration_limit: int = 3):
        self.inner = inner
        self.migration_limit = migration_limit

    async def generate(
        self, request: PreprocessedRequest, context: Context
    ) -> AsyncIterator[Annotated]:
        manager = RetryManager(self.inner, request, self.migration_limit)
        async for item in manager.run(context):
            yield item

    def around(self, next_engine, request: PreprocessedRequest, context: Context):
        return RetryManager(next_engine, request, self.migration_limit).run(context)


class RetryManager:
    """Tracks emitted tokens; on StreamLost builds the retry request with the
    produced tokens appended to the prompt (reference RetryManager
    migration.rs:82,99,130)."""

    def __init__(self, engine: AsyncEngine, request: PreprocessedRequest, limit: int):
        self.engine = engine
        self.request = request
        self.retries_left = limit
        self.attempts = 0
        self.emitted_tokens: list[int] = []
        # workers that lost a stream of THIS request: the retry excludes
        # them from re-routing and strips hints that point at them
        self.dead_instances: set[int] = set()
        # deterministic jitter, seeded per request: a fleet of retrying
        # streams spreads out, yet a chaos-test re-run reproduces exactly
        self.backoff = Backoff.seeded(
            request.request_id or "", base=0.02, max_delay=0.5
        )

    def _retry_request(self) -> PreprocessedRequest:
        req = PreprocessedRequest.from_dict(self.request.to_dict())
        req.token_ids = list(self.request.token_ids) + self.emitted_tokens
        stop = dict(req.stop_conditions)
        if stop.get("max_tokens") is not None:
            stop["max_tokens"] = max(1, stop["max_tokens"] - len(self.emitted_tokens))
        if stop.get("min_tokens") is not None:
            # the survivor's `generated` counter restarts at 0: without
            # this floor it would suppress eos for min_tokens MORE tokens
            # than the uninterrupted stream — a determinism break the
            # (seed, position) sampling contract cannot absorb
            stop["min_tokens"] = max(
                int(stop["min_tokens"]) - len(self.emitted_tokens), 0
            )
        req.stop_conditions = stop
        # the survivor classifies + counts the resume (engine stats:
        # migrations_resumed / resume_source_*)
        req.migration = self.attempts
        # fabric-aware re-route (docs/fault_tolerance.md): never dial the
        # corpse again, even while its lease lingers in discovery
        router = dict(req.router or {})
        # UNION with any caller-supplied exclusions: the first attempt
        # honored them, a retry that silently dropped them could route
        # to an instance the client explicitly ruled out
        caller_excluded = {
            int(i) for i in (router.get("exclude_instances") or ())
        }
        router["exclude_instances"] = sorted(
            caller_excluded | self.dead_instances
        )
        # an explicit per-request pin naming the corpse would make every
        # retry re-dial it (the pinned branch short-circuits routing) and
        # exhaust the budget against a dead worker: the pin dies with the
        # instance it named, the retry re-routes freely
        pin = router.get("backend_instance_id")
        if pin is not None and int(pin) in self.dead_instances:
            router.pop("backend_instance_id", None)
        req.router = router
        # a holder hint naming a dead instance would pin the survivor's
        # KV onboard to the corpse (connect-timeout per admission): drop
        # it and let the router attach a fresh one on the re-route
        holder = req.kv_holder or {}
        if int(holder.get("instance", -1)) in self.dead_instances:
            req.kv_holder = None
        # per-attempt disagg transfer descriptors died with the stream
        # (their staged pages were reaped/recycled); only the capability
        # flags survive a migration — the retry renegotiates transfers
        if req.disagg_params:
            keep = {
                k: v for k, v in req.disagg_params.items()
                if k in ("return_kv", "kv_pull", "kv_stream")
            }
            req.disagg_params = keep or None
        return req

    async def run(self, context: Context) -> AsyncIterator[Annotated]:
        request = self.request
        while True:
            try:
                stream = self.engine.generate(request, context)
                async for item in stream:
                    ann = item if isinstance(item, Annotated) else Annotated.from_dict(item)
                    if ann.data is not None:
                        data = (
                            ann.data.to_dict()
                            if isinstance(ann.data, LLMEngineOutput)
                            else ann.data
                        )
                        self.emitted_tokens.extend(data.get("token_ids", []))
                    yield ann
                return
            except DeadlineExceeded as e:
                yield Annotated.from_error(f"deadline exceeded: {e}")
                return
            except StreamLost as e:
                dead = getattr(context, "routed_instance", None)
                if dead is not None:
                    self.dead_instances.add(int(dead))
                if context.is_stopped() or context.is_killed():
                    return
                if self.retries_left <= 0:
                    logger.error("stream lost and migration budget exhausted: %s", e)
                    MIGRATION_METRICS.exhausted += 1
                    yield Annotated.from_error(f"stream lost, migration exhausted: {e}")
                    return
                if context.deadline_exceeded():
                    # retrying past the request budget only burns a worker
                    # slot the caller already gave up on — surface a clean
                    # terminal error instead
                    logger.error("stream lost past request deadline: %s", e)
                    yield Annotated.from_error(
                        f"stream lost and request deadline exceeded: {e}"
                    )
                    return
                self.retries_left -= 1
                self.attempts += 1
                request = self._retry_request()
                logger.warning(
                    "migrating request %s (%d tokens emitted, %d retries left, "
                    "excluding %s)",
                    self.request.request_id,
                    len(self.emitted_tokens),
                    self.retries_left,
                    [f"{i:x}" for i in sorted(self.dead_instances)],
                )
                if not await self.backoff.wait(context.deadline):
                    yield Annotated.from_error(
                        "stream lost and request deadline exceeded during backoff"
                    )
                    return
                # counted only once the retry is actually issued — a
                # deadline hit during backoff must not skew the
                # frontend-vs-survivor /metrics cross-check
                MIGRATION_METRICS.migrations += 1
                MIGRATION_METRICS.replayed_tokens += len(self.emitted_tokens)
