"""Request migration: resume in-flight requests on surviving workers.

Mirrors reference lib/llm/src/migration.rs (Migration :26, RetryManager
:82-158): when a worker dies mid-stream (StreamLost), re-issue the request —
minus the tokens already produced — to another worker, up to
`migration_limit` times. The client sees one uninterrupted stream.
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator, Optional

from ..runtime.backoff import Backoff
from ..runtime.engine import AsyncEngine, Context
from ..runtime.pipeline import Operator
from ..runtime.request_plane import DeadlineExceeded, StreamLost
from .protocols import Annotated, LLMEngineOutput, PreprocessedRequest

logger = logging.getLogger(__name__)


class Migration(Operator):
    """Operator wrapping the network hop with retry-on-stream-death
    (reference Migration migration.rs:26). As a pipeline node it OWNS the
    downstream call (`around`) — a retry loop cannot be expressed as a
    stream wrapper; as a classic engine wrapper it uses `inner`."""

    def __init__(self, inner: Optional[AsyncEngine] = None, migration_limit: int = 3):
        self.inner = inner
        self.migration_limit = migration_limit

    async def generate(
        self, request: PreprocessedRequest, context: Context
    ) -> AsyncIterator[Annotated]:
        manager = RetryManager(self.inner, request, self.migration_limit)
        async for item in manager.run(context):
            yield item

    def around(self, next_engine, request: PreprocessedRequest, context: Context):
        return RetryManager(next_engine, request, self.migration_limit).run(context)


class RetryManager:
    """Tracks emitted tokens; on StreamLost builds the retry request with the
    produced tokens appended to the prompt (reference RetryManager
    migration.rs:82,99,130)."""

    def __init__(self, engine: AsyncEngine, request: PreprocessedRequest, limit: int):
        self.engine = engine
        self.request = request
        self.retries_left = limit
        self.emitted_tokens: list[int] = []
        # deterministic jitter, seeded per request: a fleet of retrying
        # streams spreads out, yet a chaos-test re-run reproduces exactly
        self.backoff = Backoff.seeded(
            request.request_id or "", base=0.02, max_delay=0.5
        )

    def _retry_request(self) -> PreprocessedRequest:
        req = PreprocessedRequest.from_dict(self.request.to_dict())
        req.token_ids = list(self.request.token_ids) + self.emitted_tokens
        stop = dict(req.stop_conditions)
        if stop.get("max_tokens") is not None:
            stop["max_tokens"] = max(1, stop["max_tokens"] - len(self.emitted_tokens))
        req.stop_conditions = stop
        return req

    async def run(self, context: Context) -> AsyncIterator[Annotated]:
        request = self.request
        while True:
            try:
                stream = self.engine.generate(request, context)
                async for item in stream:
                    ann = item if isinstance(item, Annotated) else Annotated.from_dict(item)
                    if ann.data is not None:
                        data = (
                            ann.data.to_dict()
                            if isinstance(ann.data, LLMEngineOutput)
                            else ann.data
                        )
                        self.emitted_tokens.extend(data.get("token_ids", []))
                    yield ann
                return
            except DeadlineExceeded as e:
                yield Annotated.from_error(f"deadline exceeded: {e}")
                return
            except StreamLost as e:
                if context.is_stopped() or context.is_killed():
                    return
                if self.retries_left <= 0:
                    logger.error("stream lost and migration budget exhausted: %s", e)
                    yield Annotated.from_error(f"stream lost, migration exhausted: {e}")
                    return
                if context.deadline_exceeded():
                    # retrying past the request budget only burns a worker
                    # slot the caller already gave up on — surface a clean
                    # terminal error instead
                    logger.error("stream lost past request deadline: %s", e)
                    yield Annotated.from_error(
                        f"stream lost and request deadline exceeded: {e}"
                    )
                    return
                self.retries_left -= 1
                request = self._retry_request()
                logger.warning(
                    "migrating request %s (%d tokens emitted, %d retries left)",
                    self.request.request_id,
                    len(self.emitted_tokens),
                    self.retries_left,
                )
                if not await self.backoff.wait(context.deadline):
                    yield Annotated.from_error(
                        "stream lost and request deadline exceeded during backoff"
                    )
                    return
