"""OpenAI request preprocessing: chat template + tokenize → PreprocessedRequest,
and the response path assembling OpenAI deltas from engine output.

Mirrors reference lib/llm/src/preprocessor.rs (OpenAIPreprocessor :96,
preprocess_request :153, apply_template :217) and the DeltaGenerator on the
backward path. Template rendering uses jinja2 (reference uses minijinja with
HF chat-template semantics).
"""

from __future__ import annotations

import json
import logging
import secrets
import time
from typing import Any, AsyncIterator, Dict, List, Optional, Union

import jinja2

from ..runtime.engine import Context
from .model_card import ModelDeploymentCard
from .protocols import (
    Annotated,
    ChatCompletionChunk,
    ChatCompletionRequest,
    ChoiceDelta,
    CompletionChoice,
    CompletionChunk,
    CompletionRequest,
    PreprocessedRequest,
    Usage,
)
from .protocols.openai import StreamChoice, ToolCall
from .tokenizers import Tokenizer

logger = logging.getLogger(__name__)

# Default template: ChatML-style, the shape most instruct models use.
DEFAULT_CHAT_TEMPLATE = """\
{%- for message in messages -%}
<|im_start|>{{ message.role }}
{{ message.content }}<|im_end|>
{% endfor -%}
{%- if add_generation_prompt -%}
<|im_start|>assistant
{% endif -%}"""


def _content_to_text(content: Union[str, List[Dict[str, Any]], None]) -> str:
    if content is None:
        return ""
    if isinstance(content, str):
        return content
    # multimodal content parts: concatenate text parts
    return "".join(
        part.get("text", "") for part in content if part.get("type") == "text"
    )


class OpenAIPreprocessor:
    """Forward: OpenAI request → PreprocessedRequest (template+tokenize).
    Backward: engine outputs → OpenAI SSE chunks (reference preprocessor.rs:96)."""

    def __init__(self, card: ModelDeploymentCard, tokenizer: Tokenizer):
        self.card = card
        self.tokenizer = tokenizer
        env = jinja2.Environment(keep_trailing_newline=True)
        env.globals["raise_exception"] = self._raise_template_error
        self._template = env.from_string(card.chat_template or DEFAULT_CHAT_TEMPLATE)

    @staticmethod
    def _raise_template_error(msg: str):
        raise jinja2.TemplateError(msg)

    # ------------------------------------------------------------------ #
    # forward path
    # ------------------------------------------------------------------ #

    def apply_template(self, request: ChatCompletionRequest) -> str:
        """Render the chat template (reference apply_template :217)."""
        messages = [
            {
                "role": m.role,
                "content": _content_to_text(m.content),
                **({"name": m.name} if m.name else {}),
            }
            for m in request.messages
        ]
        args = dict(request.chat_template_args or {})
        args.setdefault("add_generation_prompt", True)
        return self._template.render(
            messages=messages, tools=request.tools, **args
        )

    @staticmethod
    def _extract_multimodal(request: ChatCompletionRequest) -> list:
        """Collect non-text content parts (reference multimodal protocol
        surface: image_url/input_audio parts ride the preprocessed request
        to the engine; components/backends/trtllm multimodal flows)."""
        parts = []
        for m in request.messages:
            if isinstance(m.content, list):
                for p in m.content:
                    if not isinstance(p, dict) or p.get("type") == "text":
                        continue
                    if p.get("type") == "image_url":
                        url = (p.get("image_url") or {}).get("url", "")
                        parts.append({"type": "image_url", "url": url})
                    else:
                        parts.append(dict(p))
        return parts

    def preprocess_chat(self, request: ChatCompletionRequest) -> PreprocessedRequest:
        prompt = self.apply_template(request)
        token_ids = self.tokenizer.encode(prompt)
        pre = self._build_common(request, token_ids)
        mm = self._extract_multimodal(request)
        if mm:
            if pre.guided:
                raise ValueError(
                    "guided decoding cannot be combined with multimodal "
                    "content parts"
                )
            if pre.lora_name:
                raise ValueError(
                    "LoRA adapters cannot be combined with multimodal "
                    "content parts yet"
                )
            pre.multimodal = mm
        return pre

    async def preprocess_chat_async(
        self, request: ChatCompletionRequest
    ) -> PreprocessedRequest:
        """Template render + tokenize on the compute pool (reference rayon
        offload, lib/runtime/src/compute/pool.rs): a long-prompt flood must
        not stall the frontend's event loop."""
        from ..runtime.compute import ComputePool

        return await ComputePool.get().run(self.preprocess_chat, request)

    def preprocess_completion(self, request: CompletionRequest) -> PreprocessedRequest:
        prompt = request.prompt
        if isinstance(prompt, str):
            token_ids = self.tokenizer.encode(prompt)
        elif prompt and isinstance(prompt[0], int):
            token_ids = list(prompt)  # pre-tokenized
        else:
            raise ValueError("batch prompts must be fanned out before preprocessing")
        return self._build_common(request, token_ids)

    async def preprocess_completion_async(
        self, request: CompletionRequest
    ) -> PreprocessedRequest:
        if not isinstance(request.prompt, str):
            return self.preprocess_completion(request)  # pre-tokenized: cheap
        from ..runtime.compute import ComputePool

        return await ComputePool.get().run(self.preprocess_completion, request)

    def _build_common(self, request, token_ids: List[int]) -> PreprocessedRequest:
        """Apply sampling defaults + stop conditions (reference
        preprocess_request :153)."""
        if len(token_ids) >= self.card.context_length:
            raise ValueError(
                f"prompt ({len(token_ids)} tokens) exceeds the model context "
                f"length ({self.card.context_length})"
            )
        stop = request.stop
        if isinstance(stop, str):
            stop = [stop]
        max_tokens = getattr(request, "max_completion_tokens", None) or request.max_tokens
        if max_tokens is None:
            max_tokens = self.card.context_length - len(token_ids)
        max_tokens = min(max_tokens, self.card.context_length - len(token_ids))

        sampling: Dict[str, Any] = {}
        for key in (
            "temperature",
            "top_p",
            "top_k",
            "frequency_penalty",
            "presence_penalty",
            "repetition_penalty",
            "seed",
            "n",
        ):
            v = getattr(request, key, None)
            if v is not None:
                sampling[key] = v
        nvext = getattr(request, "nvext", None)
        ignore_eos = bool(nvext.ignore_eos) if nvext and nvext.ignore_eos else False
        annotations = list(nvext.annotations) if nvext and nvext.annotations else []
        router = dict(nvext.router_config_override) if nvext and nvext.router_config_override else {}

        stop_conditions: Dict[str, Any] = {"max_tokens": max_tokens}
        if stop:
            stop_conditions["stop"] = stop
        if getattr(request, "min_tokens", None):
            stop_conditions["min_tokens"] = request.min_tokens
        if ignore_eos:
            stop_conditions["ignore_eos"] = True

        # unimplemented knobs must 400, not silently drop (the discipline
        # the embeddings handler applies to `dimensions`; r4 verdict weak #7)
        if getattr(request, "logit_bias", None):
            raise ValueError("logit_bias is not supported")
        if (getattr(request, "n", None) or 1) > 1 and isinstance(
            request, CompletionRequest
        ):
            # chat n>1 fans out at the service layer (prefix cache +
            # skip-ahead dedupe the prompt compute); legacy completions
            # n×prompts batching is not implemented
            raise ValueError(
                "n > 1 is not supported on /v1/completions; use "
                "/v1/chat/completions or issue parallel requests"
            )
        # logprobs: raw-model logprob of each sampled token, plus up to 5
        # top alternatives (chat `logprobs: true` + `top_logprobs: n`;
        # completions `logprobs: k` — its legacy top-k meaning, k=0 =
        # sampled-token only; an explicit false parses as StrictBool and
        # stays disabled).
        logprobs = getattr(request, "logprobs", None)
        top_n = getattr(request, "top_logprobs", None) or 0
        if isinstance(logprobs, int) and not isinstance(logprobs, bool):
            top_n = max(top_n, logprobs)  # completions legacy top-k ask
        if top_n > 5:
            raise ValueError("top_logprobs is capped at 5")
        if top_n and logprobs in (None, False):
            raise ValueError("top_logprobs requires logprobs to be set")
        if logprobs is not None and logprobs is not False:
            sampling["logprobs"] = True
            if top_n:
                sampling["top_logprobs"] = int(top_n)
        if getattr(request, "echo", False):
            raise ValueError("echo is not supported")
        if getattr(request, "suffix", None):
            raise ValueError("suffix (fill-in-the-middle) is not supported")

        from .guided import extract_guided_spec

        guided = extract_guided_spec(
            getattr(request, "response_format", None), nvext
        )
        lora_name = getattr(nvext, "lora_name", None) if nvext else None
        if lora_name and guided:
            raise ValueError(
                "guided decoding with a LoRA adapter is not supported yet"
            )
        # scheduling priority (engine/scheduler/): bounded so a client
        # cannot collapse its TTFT deadline to zero (or push it to years)
        priority = getattr(nvext, "priority", None) if nvext else None
        if priority is not None:
            try:
                priority = int(priority)
            except (TypeError, ValueError):
                raise ValueError("nvext.priority must be an integer")
            if not -8 <= priority <= 8:
                raise ValueError("nvext.priority must be in [-8, 8]")

        return PreprocessedRequest(
            token_ids=token_ids,
            model=request.model,
            sampling_options=sampling,
            stop_conditions=stop_conditions,
            eos_token_ids=list(self.tokenizer.eos_token_ids),
            annotations=annotations,
            router=router,
            guided=guided,
            lora_name=lora_name,
            priority=priority or 0,
            request_id=secrets.token_hex(8),
        )


# ---------------------------------------------------------------------- #
# backward path — delta generators
# ---------------------------------------------------------------------- #

#: compact JSON separators for everything that goes on the wire/SSE path —
#: the default ", "/": " pads every token chunk with dead bytes
COMPACT = (",", ":")


def _cjson(obj: Any) -> str:
    return json.dumps(obj, separators=COMPACT, ensure_ascii=False)


class ChatDeltaGenerator:
    """Assemble OpenAI chat.completion.chunk SSE events from detokenized
    engine deltas (reference DeltaGenerator protocols/openai/chat_completions/
    delta.rs)."""

    def __init__(self, model: str, request_id: Optional[str] = None,
                 include_usage: bool = True, index: int = 0):
        self.id = f"chatcmpl-{request_id or secrets.token_hex(12)}"
        self.model = model
        self.created = int(time.time())
        self.include_usage = include_usage
        self.index = index  # choice index (n > 1 fan-out)
        self.prompt_tokens = 0
        self.completion_tokens = 0
        self._first = True
        # preserialized chunk template: everything but the delta fields is
        # static per request, so the SSE hot loop serializes ONLY the delta
        # (one small json.dumps per batch) instead of running a pydantic
        # model_dump per token
        self._tmpl = (
            f'{{"id":{_cjson(self.id)},"object":"chat.completion.chunk",'
            f'"created":{self.created},"model":{_cjson(self.model)},'
            f'"choices":[{{"index":{self.index},"delta":'
        )

    def role_chunk(self) -> ChatCompletionChunk:
        return ChatCompletionChunk(
            id=self.id,
            model=self.model,
            created=self.created,
            choices=[StreamChoice(index=self.index, delta=ChoiceDelta(role="assistant", content=""))],
        )

    def text_chunk(self, text: str, n_tokens: int = 1,
                   logprob_entries=None) -> ChatCompletionChunk:
        self.completion_tokens += n_tokens
        delta = ChoiceDelta(content=text)
        if self._first:
            delta.role = "assistant"
            self._first = False
        from .protocols.openai import chat_logprobs

        lp = chat_logprobs(logprob_entries)
        return ChatCompletionChunk(
            id=self.id,
            model=self.model,
            created=self.created,
            choices=[StreamChoice(index=self.index, delta=delta, logprobs=lp)],
        )

    def text_chunk_json(self, text: str, n_tokens: int = 1) -> str:
        """Preserialized fast path for plain content deltas (the steady-
        state decode chunk); semantically identical to
        `text_chunk(...).model_dump_json(exclude_none=True)`."""
        self.completion_tokens += n_tokens
        delta: Dict[str, str] = {"content": text}
        if self._first:
            delta = {"role": "assistant", "content": text}
            self._first = False
        return f"{self._tmpl}{_cjson(delta)}}}]}}"

    def finish_chunk_json(self, reason: str) -> str:
        reason = "stop" if reason == "eos" else reason
        return f'{self._tmpl}{{}},"finish_reason":{_cjson(reason)}}}]}}'

    def reasoning_chunk(self, text: str, n_tokens: int = 0) -> ChatCompletionChunk:
        self.completion_tokens += n_tokens
        delta = ChoiceDelta(reasoning_content=text)
        if self._first:
            delta.role = "assistant"
            self._first = False
        return ChatCompletionChunk(
            id=self.id,
            model=self.model,
            created=self.created,
            choices=[StreamChoice(index=self.index, delta=delta)],
        )

    def tool_calls_chunk(self, tool_calls: list) -> ChatCompletionChunk:
        # streaming deltas require `index` for client-side aggregation
        calls = []
        for i, tc in enumerate(tool_calls):
            call = ToolCall.model_validate(tc)
            call.index = i
            calls.append(call)
        delta = ChoiceDelta(tool_calls=calls)
        if self._first:
            delta.role = "assistant"
            self._first = False
        return ChatCompletionChunk(
            id=self.id,
            model=self.model,
            created=self.created,
            choices=[StreamChoice(index=self.index, delta=delta)],
        )

    def finish_chunk(self, reason: str) -> ChatCompletionChunk:
        reason = "stop" if reason == "eos" else reason
        return ChatCompletionChunk(
            id=self.id,
            model=self.model,
            created=self.created,
            choices=[StreamChoice(index=self.index, delta=ChoiceDelta(), finish_reason=reason)],
        )

    def usage_chunk(self) -> ChatCompletionChunk:
        return ChatCompletionChunk(
            id=self.id,
            model=self.model,
            created=self.created,
            choices=[],
            usage=Usage(
                prompt_tokens=self.prompt_tokens,
                completion_tokens=self.completion_tokens,
                total_tokens=self.prompt_tokens + self.completion_tokens,
            ),
        )


class CompletionDeltaGenerator:
    """text_completion chunks (reference completions delta path)."""

    def __init__(self, model: str, request_id: Optional[str] = None):
        self.id = f"cmpl-{request_id or secrets.token_hex(12)}"
        self.model = model
        self.created = int(time.time())
        self.prompt_tokens = 0
        self.completion_tokens = 0
        self._chars_sent = 0  # running text_offset base across chunks
        # preserialized template (same contract as ChatDeltaGenerator)
        self._tmpl = (
            f'{{"id":{_cjson(self.id)},"object":"text_completion",'
            f'"created":{self.created},"model":{_cjson(self.model)},'
            f'"choices":[{{"index":0,"text":'
        )

    def text_chunk_json(self, text: str, n_tokens: int = 1) -> str:
        """Preserialized fast path for plain text deltas (no logprobs)."""
        self.completion_tokens += n_tokens
        self._chars_sent += len(text)
        return f"{self._tmpl}{_cjson(text)}}}]}}"

    def finish_chunk_json(self, reason: str) -> str:
        reason = "stop" if reason == "eos" else reason
        return f'{self._tmpl}"","finish_reason":{_cjson(reason)}}}]}}'

    def text_chunk(self, text: str, n_tokens: int = 1,
                   logprob_entries=None) -> CompletionChunk:
        self.completion_tokens += n_tokens
        from .protocols.openai import completion_logprobs

        lp = completion_logprobs(logprob_entries, self._chars_sent)
        self._chars_sent += len(text)
        return CompletionChunk(
            id=self.id,
            model=self.model,
            created=self.created,
            choices=[CompletionChoice(index=0, text=text, logprobs=lp)],
        )

    def finish_chunk(self, reason: str) -> CompletionChunk:
        reason = "stop" if reason == "eos" else reason
        return CompletionChunk(
            id=self.id,
            model=self.model,
            created=self.created,
            choices=[CompletionChoice(index=0, text="", finish_reason=reason)],
        )

    def usage_chunk(self) -> CompletionChunk:
        return CompletionChunk(
            id=self.id,
            model=self.model,
            created=self.created,
            choices=[],
            usage=Usage(
                prompt_tokens=self.prompt_tokens,
                completion_tokens=self.completion_tokens,
                total_tokens=self.prompt_tokens + self.completion_tokens,
            ),
        )
