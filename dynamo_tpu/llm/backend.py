"""Backend operator: incremental detokenization + stop-condition handling.

Mirrors reference lib/llm/src/backend.rs (Backend :55, Decoder :282): sits
between the preprocessor and the network/router, turning the engine's token
stream into text deltas and enforcing stop strings that the engine can't see
(engines enforce token-level stops; string stops need detok state).
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator, Dict, List, Optional

from ..runtime.engine import AsyncEngine, Context
from ..runtime.pipeline import Operator
from .protocols import Annotated, LLMEngineOutput, PreprocessedRequest
from .tokenizers import Tokenizer

logger = logging.getLogger(__name__)


class Decoder:
    """Per-request incremental decode state (reference Decoder backend.rs:282)."""

    def __init__(self, tokenizer: Tokenizer, stop_strings: Optional[List[str]] = None):
        self._stream = tokenizer.decode_stream()
        self._stop_strings = stop_strings or []
        self._pending = ""  # text withheld because it may begin a stop string

    def _holdback_len(self, text: str) -> int:
        """Length of the longest suffix of `text` that is a proper prefix of
        any stop string (must be withheld until disambiguated)."""
        best = 0
        for s in self._stop_strings:
            for k in range(min(len(s) - 1, len(text)), 0, -1):
                if text.endswith(s[:k]):
                    best = max(best, k)
                    break
        return best

    def step(self, token_id: int) -> tuple[Optional[str], bool]:
        """Returns (text_delta, hit_stop_string). On a stop hit, the delta is
        trimmed up to the stop string start; partial stop-string matches are
        never leaked."""
        delta = self._stream.step(token_id)
        if delta is None:
            return None, False
        if not self._stop_strings:
            return delta, False
        window = self._pending + delta
        for s in self._stop_strings:
            idx = window.find(s)
            if idx != -1:
                self._pending = ""
                return (window[:idx] or None), True
        hold = self._holdback_len(window)
        emit = window[: len(window) - hold] if hold else window
        self._pending = window[len(window) - hold :] if hold else ""
        return (emit or None), False


class Backend(Operator):
    """Detokenization operator (reference Backend.fwd/bwd backend.rs:55):
    forward passes the request through untouched; backward turns the token
    stream into text deltas and enforces stop strings. Usable either as a
    node in runtime.pipeline.compose() or as a classic engine wrapper
    (`inner` given)."""

    def __init__(self, inner: Optional[AsyncEngine] = None,
                 tokenizer: Optional[Tokenizer] = None):
        self.inner = inner
        self.tokenizer = tokenizer

    async def generate(
        self, request: PreprocessedRequest, context: Context
    ) -> AsyncIterator[Annotated]:
        async for item in self.backward(
            self.inner.generate(request, context), request, context
        ):
            yield item

    async def backward(
        self, stream, request: PreprocessedRequest, context: Context
    ) -> AsyncIterator[Annotated]:
        stop_strings = request.stop_conditions.get("stop") or []
        decoder = Decoder(self.tokenizer, stop_strings)
        stopped = False
        async for item in stream:
            ann = item if isinstance(item, Annotated) else Annotated.from_dict(item)
            if ann.data is None:
                yield ann  # pure annotation/error event passes through
                continue
            out = (
                ann.data
                if isinstance(ann.data, LLMEngineOutput)
                else LLMEngineOutput.from_dict(ann.data)
            )
            text_parts: List[str] = []
            lp_entries: List[dict] = []
            for idx, tok in enumerate(out.token_ids):
                delta, hit = decoder.step(tok)
                if delta:
                    text_parts.append(delta)
                if out.log_probs is not None and idx < len(out.log_probs):
                    # per-token pairing happens HERE. The entry's token
                    # string decodes the id directly — the incremental
                    # delta can be empty (multi-byte UTF-8 split, stop-
                    # string holdback) and entries must stay 1:1 with
                    # tokens for legacy-completions alignment
                    entry = {"token": self.tokenizer.decode([tok]),
                             "logprob": out.log_probs[idx]}
                    tops = out.top_logprobs
                    if tops and idx < len(tops) and tops[idx]:
                        entry["top_logprobs"] = [
                            {
                                "token": self.tokenizer.decode([tid]),
                                "logprob": tlp,
                            }
                            for tid, tlp in zip(
                                tops[idx]["ids"], tops[idx]["logprobs"]
                            )
                        ]
                    lp_entries.append(entry)
                if hit:
                    stopped = True
                    break
            if out.text is None:
                out.text = "".join(text_parts) if text_parts else None
            if lp_entries:
                out.logprob_entries = lp_entries
            if stopped and out.finish_reason is None:
                out.finish_reason = "stop"
            yield Annotated(data=out, id=ann.id, event=ann.event, comment=ann.comment)
            if stopped:
                context.stop_generating()
                return
            if out.finish_reason is not None:
                return
