"""Backend operator: incremental detokenization + stop-condition handling.

Mirrors reference lib/llm/src/backend.rs (Backend :55, Decoder :282): sits
between the preprocessor and the network/router, turning the engine's token
stream into text deltas and enforcing stop strings that the engine can't see
(engines enforce token-level stops; string stops need detok state).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator, Dict, List, Optional

from ..runtime.compute import ComputePool
from ..runtime.config import _env
from ..runtime.engine import AsyncEngine, Context
from ..runtime.pipeline import Operator
from .protocols import Annotated, LLMEngineOutput, PreprocessedRequest
from .tokenizers import Tokenizer

logger = logging.getLogger(__name__)


def _mergeable_ids(ann: Annotated) -> Optional[List[int]]:
    """token_ids if `ann` is a PURE token delta (no event/text/logprobs/
    finish/usage riders) — the only shape safe to concatenate."""
    if ann.event is not None or ann.comment:
        return None
    d = ann.data
    if isinstance(d, LLMEngineOutput):
        d = d.to_dict()
    if not isinstance(d, dict) or set(d) - {"token_ids"}:
        return None
    return d.get("token_ids") or None


async def merge_token_deltas(
    stream: AsyncIterator[Any], max_items: int = 0
) -> AsyncIterator[Annotated]:
    """Merge already-ready pure-token items into one delta batch.

    A pump task drains the upstream while the consumer works; each
    iteration takes everything the pump has ready (never waiting, so a
    slow stream's latency is untouched) and concatenates consecutive
    token-only deltas. Engines that emit per-token (the mocker; a real
    engine between block boundaries) thus still reach the detokenizer and
    SSE assembler as batches — O(1) frontend work per event-loop tick."""
    if max_items <= 0:
        max_items = max(_env("DYN_STREAM_COALESCE_MAX_ITEMS", 64, int), 1)
    done = object()
    queue: asyncio.Queue = asyncio.Queue()

    async def pump():
        try:
            async for item in stream:
                queue.put_nowait(item)
            queue.put_nowait(done)
        except asyncio.CancelledError:
            raise
        except BaseException as e:  # noqa: BLE001 — re-raised consumer-side
            queue.put_nowait(e)

    pump_task = asyncio.create_task(pump())
    try:
        held: Optional[Annotated] = None
        while True:
            if held is not None:
                item, held = held, None
            else:
                item = await queue.get()
            if item is done:
                return
            if isinstance(item, BaseException):
                raise item
            ann = item if isinstance(item, Annotated) else Annotated.from_dict(item)
            ids = _mergeable_ids(ann)
            if ids is None:
                yield ann
                continue
            merged = list(ids)
            terminal = None
            while len(merged) < max_items:
                try:
                    nxt = queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if nxt is done or isinstance(nxt, BaseException):
                    terminal = nxt
                    break
                nann = nxt if isinstance(nxt, Annotated) else Annotated.from_dict(nxt)
                nids = _mergeable_ids(nann)
                if nids is None:
                    held = nann
                    break
                merged.extend(nids)
            yield Annotated(data={"token_ids": merged}, id=ann.id)
            if terminal is not None:
                if terminal is done:
                    return
                raise terminal
    finally:
        pump_task.cancel()


class Decoder:
    """Per-request incremental decode state (reference Decoder backend.rs:282)."""

    def __init__(self, tokenizer: Tokenizer, stop_strings: Optional[List[str]] = None):
        self._stream = tokenizer.decode_stream()
        self._stop_strings = stop_strings or []
        self._pending = ""  # text withheld because it may begin a stop string

    def _holdback_len(self, text: str) -> int:
        """Length of the longest suffix of `text` that is a proper prefix of
        any stop string (must be withheld until disambiguated)."""
        best = 0
        for s in self._stop_strings:
            for k in range(min(len(s) - 1, len(text)), 0, -1):
                if text.endswith(s[:k]):
                    best = max(best, k)
                    break
        return best

    def step(self, token_id: int) -> tuple[Optional[str], bool]:
        """Returns (text_delta, hit_stop_string). On a stop hit, the delta is
        trimmed up to the stop string start; partial stop-string matches are
        never leaked."""
        delta = self._stream.step(token_id)
        if delta is None:
            return None, False
        return self._scan(delta)

    def _scan(self, delta: str) -> tuple[Optional[str], bool]:
        """Stop-string scan + holdback over newly decoded text."""
        if not self._stop_strings:
            return delta, False
        window = self._pending + delta
        for s in self._stop_strings:
            idx = window.find(s)
            if idx != -1:
                self._pending = ""
                return (window[:idx] or None), True
        hold = self._holdback_len(window)
        emit = window[: len(window) - hold] if hold else window
        self._pending = window[len(window) - hold :] if hold else ""
        return (emit or None), False

    def step_batch(self, token_ids: List[int]) -> tuple[Optional[str], int, bool]:
        """Feed a whole delta batch; returns (text_delta, n_consumed, hit).

        One tokenizer decode for the batch; stop-string holdback applies to
        the joined text, so a stop string straddling a batch boundary is
        caught exactly as in per-token stepping. On a hit the batch replays
        per-token from a state snapshot to attribute the hit to its token —
        `n_consumed` then counts tokens up to and including it, so usage
        accounting matches the singleton-emission path. The replay happens
        at most once per request (at stream end)."""
        if not token_ids:
            return None, 0, False
        if not self._stop_strings:
            return self._stream.step_batch(token_ids), len(token_ids), False
        snap = self._stream.snapshot()
        pending = self._pending
        delta = self._stream.step_batch(token_ids)
        window = pending + (delta or "")
        if not any(s in window for s in self._stop_strings):
            if delta is None:
                return None, len(token_ids), False
            emit, _hit = self._scan(delta)
            return emit, len(token_ids), False
        # a stop string completed somewhere inside the batch: replay to
        # find WHICH token finished it (tokens past it were never "said")
        self._stream.restore(snap)
        self._pending = pending
        parts: List[str] = []
        for n, tok in enumerate(token_ids, start=1):
            emit, hit = self.step(tok)
            if emit:
                parts.append(emit)
            if hit:
                return ("".join(parts) or None), n, True
        return ("".join(parts) or None), len(token_ids), False


class Backend(Operator):
    """Detokenization operator (reference Backend.fwd/bwd backend.rs:55):
    forward passes the request through untouched; backward turns the token
    stream into text deltas and enforces stop strings. Usable either as a
    node in runtime.pipeline.compose() or as a classic engine wrapper
    (`inner` given)."""

    def __init__(self, inner: Optional[AsyncEngine] = None,
                 tokenizer: Optional[Tokenizer] = None):
        self.inner = inner
        self.tokenizer = tokenizer
        # detok offload (docs/frontend_scaleout.md): batches big enough to
        # amortize the executor hop — and every stop-string scan, whose
        # worst case (replay on a hit, long holdback windows) is exactly
        # the work that must not stall the shared event loop — run on the
        # bounded compute pool; tiny batches stay inline where the hop
        # would cost more than it frees. Read per-instance so test
        # clusters can flip the env after import.
        self._pool = bool(_env("DYN_DETOK_POOL", True, bool))
        self._pool_min = max(_env("DYN_DETOK_POOL_MIN_TOKENS", 8, int), 1)

    async def generate(
        self, request: PreprocessedRequest, context: Context
    ) -> AsyncIterator[Annotated]:
        async for item in self.backward(
            self.inner.generate(request, context), request, context
        ):
            yield item

    async def backward(
        self, stream, request: PreprocessedRequest, context: Context
    ) -> AsyncIterator[Annotated]:
        stop_strings = request.stop_conditions.get("stop") or []
        decoder = Decoder(self.tokenizer, stop_strings)
        stopped = False
        async for ann in merge_token_deltas(stream):
            if ann.data is None:
                yield ann  # pure annotation/error event passes through
                continue
            out = (
                ann.data
                if isinstance(ann.data, LLMEngineOutput)
                else LLMEngineOutput.from_dict(ann.data)
            )
            text_parts: List[str] = []
            lp_entries: List[dict] = []
            if out.log_probs is None:
                # batched fast path: one tokenizer call for the whole
                # delta batch; tokens past a stop-string hit are dropped
                # so usage accounting matches per-token stepping. The
                # decoder is confined to this coroutine, so pool execution
                # is sequential per request — byte-identical to inline.
                ids = out.token_ids
                if self._pool and ids and (
                    stop_strings or len(ids) >= self._pool_min
                ):
                    delta, n_used, stopped = await ComputePool.get().run(
                        decoder.step_batch, ids
                    )
                else:
                    delta, n_used, stopped = decoder.step_batch(ids)
                if n_used < len(out.token_ids):
                    out.token_ids = out.token_ids[:n_used]
                if delta:
                    text_parts.append(delta)
            else:
                for idx, tok in enumerate(out.token_ids):
                    delta, hit = decoder.step(tok)
                    if delta:
                        text_parts.append(delta)
                    # per-token pairing happens HERE. The entry's token
                    # string decodes the id directly — the incremental
                    # delta can be empty (multi-byte UTF-8 split, stop-
                    # string holdback) and entries must stay 1:1 with
                    # tokens for legacy-completions alignment
                    if idx < len(out.log_probs):
                        entry = {"token": self.tokenizer.decode([tok]),
                                 "logprob": out.log_probs[idx]}
                        tops = out.top_logprobs
                        if tops and idx < len(tops) and tops[idx]:
                            entry["top_logprobs"] = [
                                {
                                    "token": self.tokenizer.decode([tid]),
                                    "logprob": tlp,
                                }
                                for tid, tlp in zip(
                                    tops[idx]["ids"], tops[idx]["logprobs"]
                                )
                            ]
                        lp_entries.append(entry)
                    if hit:
                        stopped = True
                        # truncate the batch at the hit token: 1:1 entry
                        # alignment and token counts end where the text did
                        out.token_ids = out.token_ids[: idx + 1]
                        out.log_probs = out.log_probs[: idx + 1]
                        if out.top_logprobs:
                            out.top_logprobs = out.top_logprobs[: idx + 1]
                        break
            if out.text is None:
                out.text = "".join(text_parts) if text_parts else None
            if lp_entries:
                out.logprob_entries = lp_entries
            if stopped and out.finish_reason is None:
                out.finish_reason = "stop"
            yield Annotated(data=out, id=ann.id, event=ann.event, comment=ann.comment)
            if stopped:
                context.stop_generating()
                return
            if out.finish_reason is not None:
                return
