"""ModelDeploymentCard (MDC) — what a model IS for the serving plane.

Mirrors reference lib/llm/src/model_card.rs:93: name, tokenizer, prompt
formatter/chat template, context length, kv block size, migration limit,
runtime config. Cards are published to discovery under `v1/mdc/...` by
workers (`register_llm`) and watched by frontends (ModelWatcher).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..runtime.component import MODEL_ROOT, Endpoint


class ModelInput:
    TOKENS = "tokens"  # worker takes PreprocessedRequest (frontend tokenizes)
    TEXT = "text"  # worker takes raw OpenAI request


class ModelType:
    CHAT = "chat"
    COMPLETIONS = "completions"
    EMBEDDINGS = "embeddings"
    CHAT_AND_COMPLETIONS = "chat+completions"


@dataclass
class ModelDeploymentCard:
    """Reference model_card.rs:93 — stored as JSON in discovery."""

    name: str
    tokenizer: str = "byte"  # spec for tokenizers.load_tokenizer
    model_input: str = ModelInput.TOKENS
    model_type: str = ModelType.CHAT_AND_COMPLETIONS
    context_length: int = 8192
    kv_cache_block_size: int = 64
    migration_limit: int = 3
    chat_template: Optional[str] = None  # jinja2 source; None = default
    runtime_config: Dict[str, Any] = field(default_factory=dict)
    checksum: Optional[str] = None
    # LoRA adapters this worker serves (select via nvext.lora_name;
    # reference lora_id in kv_router/protocols.rs:110-115)
    lora_adapters: List[str] = field(default_factory=list)

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "ModelDeploymentCard":
        d = json.loads(raw)
        known = cls.__dataclass_fields__.keys()
        return cls(**{k: v for k, v in d.items() if k in known})

    def slug(self) -> str:
        return self.name.replace("/", "--")


def mdc_key(endpoint: Endpoint, card: ModelDeploymentCard) -> str:
    """Discovery key for a card published by an endpoint's worker
    (reference MODEL_ROOT_PATH v1/mdc/).

    The key is PER-INSTANCE: without the instance-id suffix, N replicas of
    the same model share one key whose lease belongs to whichever replica
    registered LAST — when that replica drains (planner scale-down kills
    newest-first), its lease revoke deletes the shared card and the
    frontend 404s the model while live replicas still serve it. With
    per-instance keys the ModelWatcher's existing refcount keeps the model
    up until the LAST replica leaves."""
    return (
        f"{MODEL_ROOT}{endpoint.component.namespace}/"
        f"{endpoint.component.name}/{endpoint.name}/{card.slug()}/"
        f"{endpoint.drt.instance_id:x}"
    )


async def register_llm(
    endpoint: Endpoint,
    card: ModelDeploymentCard,
) -> str:
    """Publish the model card under the worker's primary lease
    (reference register_llm bindings lib.rs:211). Returns the key."""
    drt = endpoint.drt
    key = mdc_key(endpoint, card)
    payload = dict(json.loads(card.to_json()))
    payload["endpoint"] = {
        "namespace": endpoint.component.namespace,
        "component": endpoint.component.name,
        "endpoint": endpoint.name,
        "instance_id": drt.instance_id,
    }
    await drt.put_leased(key, json.dumps(payload).encode())
    return key
