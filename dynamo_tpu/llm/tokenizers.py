"""Tokenizer abstraction with incremental decode.

Mirrors reference lib/llm/src/tokenizers.rs: a `Tokenizer` trait with
encode/decode plus a `DecodeStream` for incremental, UTF-8-safe streaming
detokenization (the reference wraps HF `tokenizers`' DecodeStream).

Backends:
  * HfTokenizer — HF `tokenizers` json file (tokenizer.json)
  * ByteTokenizer — self-contained byte-level tokenizer (id = byte + offset)
    with BOS/EOS/PAD specials; used for tests and weight-free benchmarks
    (this image has no HF hub access).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List, Optional, Protocol, Sequence


class Tokenizer(Protocol):
    def encode(self, text: str) -> List[int]: ...
    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str: ...
    def decode_stream(self, skip_special_tokens: bool = True) -> "DecodeStream": ...
    @property
    def vocab_size(self) -> int: ...
    @property
    def eos_token_ids(self) -> List[int]: ...
    @property
    def bos_token_id(self) -> Optional[int]: ...


class DecodeStream:
    """Incremental detokenizer: feed token ids one at a time, get text deltas
    that are valid UTF-8 and stable (reference DecodeStream in tokenizers.rs).

    Implementation: keep a window of undecoded ids; a delta is emitted when
    decoding the window extends the previously yielded text and ends outside
    a UTF-8 replacement char (pending multi-byte sequences stay buffered).
    """

    def __init__(self, tokenizer: "Tokenizer", skip_special_tokens: bool = True):
        self._tok = tokenizer
        self._skip = skip_special_tokens
        self._ids: List[int] = []
        self._prefix_text = ""
        self._prefix_index = 0

    def step(self, token_id: int) -> Optional[str]:
        return self.step_batch([token_id])

    def step_batch(self, token_ids: Sequence[int]) -> Optional[str]:
        """Feed a whole delta batch with ONE tokenizer decode call.

        Equivalent to concatenating `step()` deltas for the same ids:
        decode is prefix-stable (decoding more ids only extends the text),
        so the joined delta is identical — this is what makes engine-side
        emit batching free for the detokenizer instead of K× cost."""
        if not token_ids:
            return None
        self._ids.extend(token_ids)
        text = self._tok.decode(self._ids[self._prefix_index :], self._skip)
        # withhold the trailing pending-multibyte run (replacement chars):
        # those bytes stay buffered until later tokens complete them; the
        # decodable prefix before the run IS stable and emits now
        emit_upto = len(text)
        while emit_upto > 0 and text[emit_upto - 1] == "�":
            emit_upto -= 1
        stable = text[:emit_upto]
        if len(stable) <= len(self._prefix_text):
            # no new visible text yet (special token skipped / all pending)
            return None
        delta = stable[len(self._prefix_text) :]
        # slide the window to bound cost — only when nothing is pending,
        # so buffered partial bytes keep decoding against their prefix
        if (
            emit_upto == len(text)
            and len(self._ids) - self._prefix_index > 16
            and delta
        ):
            self._prefix_index = len(self._ids)
            self._prefix_text = ""
        else:
            self._prefix_text = stable
        return delta or None

    def snapshot(self) -> tuple:
        """Cheap state capture for replay (stop-string hit attribution:
        llm/backend.py re-steps a batch per-token to find the hit index)."""
        return (len(self._ids), self._prefix_text, self._prefix_index)

    def restore(self, state: tuple) -> None:
        n_ids, prefix_text, prefix_index = state
        del self._ids[n_ids:]
        self._prefix_text = prefix_text
        self._prefix_index = prefix_index


class ByteTokenizer:
    """Byte-level tokenizer: token id = byte value + 3 specials.

    ids: 0=PAD, 1=BOS, 2=EOS, 3..258 = bytes 0..255. Deterministic, needs no
    assets; round-trips arbitrary UTF-8. Vocab padded to 32000 by default so
    model shapes look realistic; ids in the padded region decode to a
    distinct printable placeholder (U+0100 + id) rather than disappearing —
    silently dropping generated tokens would make a stream look stalled
    (and break token accounting for any client counting content chunks).
    """

    PAD, BOS, EOS = 0, 1, 2
    OFFSET = 3
    PLACEHOLDER_BASE = 0x100  # Latin Extended-A onward: printable, 1 char/id

    def __init__(self, vocab_size: int = 32000):
        self._vocab_size = max(vocab_size, 256 + self.OFFSET)

    def encode(self, text: str) -> List[int]:
        return [b + self.OFFSET for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        parts: List[str] = []
        run: List[int] = []  # pending byte-range ids

        def flush():
            if run:
                parts.append(bytes(run).decode("utf-8", errors="replace"))
                run.clear()

        for i in ids:
            if self.OFFSET <= i < self.OFFSET + 256:
                run.append(i - self.OFFSET)
            elif i >= self.OFFSET + 256:
                flush()
                cp = self.PLACEHOLDER_BASE + (i - self.OFFSET - 256)
                if cp >= 0xD800:
                    # skip the UTF-16 surrogate block: chr() there makes a
                    # lone surrogate that no JSON/UTF-8 serializer accepts
                    # (a 128k-vocab id sampled into it crashed the SSE
                    # stream serializer mid-benchmark)
                    cp += 0x800
                parts.append(chr(cp))
            # specials (PAD/BOS/EOS) are always dropped
        flush()
        return "".join(parts)

    def decode_stream(self, skip_special_tokens: bool = True) -> DecodeStream:
        return DecodeStream(self, skip_special_tokens)

    @property
    def vocab_size(self) -> int:
        return self._vocab_size

    @property
    def eos_token_ids(self) -> List[int]:
        return [self.EOS]

    @property
    def bos_token_id(self) -> Optional[int]:
        return self.BOS


class HfTokenizer:
    """HF `tokenizers`-backed tokenizer loaded from a tokenizer.json
    (reference tokenizers/hf.rs)."""

    def __init__(self, path: str):
        from tokenizers import Tokenizer as _HfTok

        self._tok = _HfTok.from_file(path)
        self._eos_ids = self._find_eos(path)

    def _find_eos(self, path: str) -> List[int]:
        # check sibling config files for eos ids (generation_config/config.json)
        eos: List[int] = []
        folder = Path(path).parent
        for name in ("generation_config.json", "config.json"):
            p = folder / name
            if p.exists():
                try:
                    cfg = json.loads(p.read_text())
                except json.JSONDecodeError:
                    continue
                v = cfg.get("eos_token_id")
                if isinstance(v, int):
                    eos.append(v)
                elif isinstance(v, list):
                    eos.extend(int(x) for x in v)
                if eos:
                    break
        return eos

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text, add_special_tokens=False).ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def decode_stream(self, skip_special_tokens: bool = True) -> DecodeStream:
        return DecodeStream(self, skip_special_tokens)

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    @property
    def eos_token_ids(self) -> List[int]:
        return list(self._eos_ids)

    @property
    def bos_token_id(self) -> Optional[int]:
        return None


class GgufTokenizer:
    """Tokenizer over a GGUF file's embedded vocabulary (reference
    gguf_tokenizer.rs): greedy longest-match over the token list, with the
    llama.cpp `▁`-for-space convention. Enough for serving a .gguf model
    card end-to-end without external tokenizer files."""

    SPACE = "▁"  # '▁'

    def __init__(self, gguf_path: str):
        from .gguf import read_gguf

        g = read_gguf(gguf_path)
        tokens = g.tokens
        if not tokens:
            raise ValueError(f"{gguf_path}: no embedded tokenizer vocabulary")
        self._tokens = tokens
        self._ids = {t: i for i, t in enumerate(tokens)}
        self._max_len = max(len(t) for t in tokens)
        self._eos = [g.eos_token_id] if g.eos_token_id is not None else []
        self._bos = g.bos_token_id
        self._unk = 0 if tokens and tokens[0].startswith("<") else None

    def encode(self, text: str) -> List[int]:
        s = text.replace(" ", self.SPACE)
        out: List[int] = []
        i = 0
        while i < len(s):
            match = None
            for n in range(min(self._max_len, len(s) - i), 0, -1):
                tid = self._ids.get(s[i : i + n])
                if tid is not None:
                    match = (tid, n)
                    break
            if match is None:
                if self._unk is not None:
                    out.append(self._unk)
                i += 1
            else:
                out.append(match[0])
                i += match[1]
        return out

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        parts = []
        for i in ids:
            if 0 <= i < len(self._tokens):
                t = self._tokens[i]
                if skip_special_tokens and t.startswith("<") and t.endswith(">"):
                    continue
                parts.append(t)
        return "".join(parts).replace(self.SPACE, " ")

    def decode_stream(self, skip_special_tokens: bool = True) -> DecodeStream:
        return DecodeStream(self, skip_special_tokens)

    @property
    def vocab_size(self) -> int:
        return len(self._tokens)

    @property
    def eos_token_ids(self) -> List[int]:
        return list(self._eos)

    @property
    def bos_token_id(self) -> Optional[int]:
        return self._bos


def load_tokenizer(spec: str) -> Tokenizer:
    """Resolve a tokenizer spec: 'byte' | 'byte:<vocab>' | 'gguf:<path>'
    (embedded vocab) | path to tokenizer.json | model folder."""
    if spec == "byte":
        return ByteTokenizer()
    if spec.startswith("byte:"):
        return ByteTokenizer(int(spec.split(":", 1)[1]))
    if spec.startswith("gguf:"):
        return GgufTokenizer(spec.split(":", 1)[1])
    p = Path(spec)
    if p.is_dir():
        p = p / "tokenizer.json"
    if p.exists():
        return HfTokenizer(str(p))
    raise FileNotFoundError(f"no tokenizer at {spec!r} (use 'byte' for the builtin)")
