"""In-process CPU engine on torch/transformers.

The role of the reference's in-process Rust engines (llamacpp
lib/engines/llamacpp/src/lib.rs, mistralrs lib/engines/mistralrs): a real
token-generating engine linked into the launcher process for CPU smoke
serving and latency-path testing — no TPU, no subprocess, no fake timing
(the mocker's job). Runs a Hugging Face causal LM on CPU:

  * `model_path` given: `from_pretrained(..., local_files_only=True)` — a
    real local checkpoint (zero-egress environments load what's on disk);
  * otherwise: a tiny random-init LlamaForCausalLM built `from_config`,
    paired with the byte tokenizer — deterministic greedy output with no
    assets at all.

Implements the MockEngine-compatible `generate(request, context)`
interface (token-ids in, per-step token dicts out), so it slots behind the
same preprocessor/backend pipeline as every other engine. The blocking
torch forward runs on the compute pool so the serving loop stays live.
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator, Optional

logger = logging.getLogger(__name__)


class HfCpuEngine:
    """Greedy/temperature incremental decoding with a KV cache, on CPU."""

    def __init__(self, model_path: Optional[str] = None, vocab_size: int = 512):
        import torch
        from transformers import LlamaConfig, LlamaForCausalLM

        torch.manual_seed(0)
        self.torch = torch
        self.model_name = model_path or "hf-cpu-tiny"
        if model_path:
            from transformers import AutoModelForCausalLM

            self.model = AutoModelForCausalLM.from_pretrained(
                model_path, local_files_only=True, torch_dtype=torch.float32
            )
        else:
            cfg = LlamaConfig(
                vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=4096,
            )
            self.model = LlamaForCausalLM(cfg)
        self.model.eval()
        eos = self.model.config.eos_token_id
        if isinstance(eos, list):
            self.eos_ids = set(eos)
        else:
            # explicit None check: token id 0 is a legitimate EOS in some vocabs
            self.eos_ids = {eos if eos is not None else -1}

    def _step(self, input_ids, past, temperature: float):
        """One forward + sample (blocking; runs on the compute pool)."""
        torch = self.torch
        with torch.no_grad():
            out = self.model(
                input_ids=input_ids, past_key_values=past, use_cache=True
            )
            logits = out.logits[0, -1]
            if temperature and temperature > 0:
                probs = torch.softmax(logits / temperature, dim=-1)
                tok = int(torch.multinomial(probs, 1))
            else:
                tok = int(torch.argmax(logits))
            return tok, out.past_key_values

    async def generate(self, request: Any, context) -> AsyncIterator[dict]:
        from ...runtime.compute import ComputePool

        req = request if isinstance(request, dict) else request.to_dict()
        if req.get("multimodal"):
            # protocol contract (protocols/common.py): engines without
            # multimodal support must REJECT, not silently answer from the
            # text tokens alone
            from ..protocols.common import Annotated

            yield Annotated.from_error(
                f"model {self.model_name!r} (hf-cpu) is text-only; request "
                f"carries {len(req['multimodal'])} multimodal content part(s)"
            ).to_dict()
            return
        if req.get("guided"):
            # same contract for structured output: enforcing it here would
            # require the FSM sampler the JAX engine owns — reject rather
            # than return unconstrained text
            from ..protocols.common import Annotated

            yield Annotated.from_error(
                "guided decoding is not supported by the hf-cpu engine; "
                "serve the model on the JAX engine (out=jax)"
            ).to_dict()
            return
        token_ids = list(req.get("token_ids") or [])
        stop = req.get("stop_conditions") or {}
        sampling = req.get("sampling_options") or {}
        max_tokens = int(stop.get("max_tokens") or 64)
        ignore_eos = bool(stop.get("ignore_eos"))
        temperature = float(sampling.get("temperature") or 0.0)
        eos = self.eos_ids | set(req.get("eos_token_ids") or [])

        torch = self.torch
        pool = ComputePool.get()
        ids = torch.tensor([token_ids], dtype=torch.long)
        past = None
        for i in range(max_tokens):
            if context is not None and context.is_stopped():
                return
            tok, past = await pool.run(self._step, ids, past, temperature)
            finished = (not ignore_eos and tok in eos) or i == max_tokens - 1
            yield {
                "data": {
                    "token_ids": [tok],
                    **({"finish_reason": "stop" if tok in eos else "length"}
                       if finished else {}),
                }
            }
            if finished:
                return
            ids = torch.tensor([[tok]], dtype=torch.long)
