"""In-process CPU engines (reference lib/engines/{llamacpp,mistralrs}:
engines linked into the launcher process for CPU smoke serving)."""

from .hf_cpu import HfCpuEngine

__all__ = ["HfCpuEngine"]
