"""GGUF file parsing: header, metadata KVs, embedded tokenizer.

Role of the reference's gguf module (lib/llm/src/gguf/{content,
gguf_metadata,gguf_tokenizer}.rs): read enough of a .gguf checkpoint to
build a ModelDeploymentCard — architecture, context length, block/head
dims, and the embedded tokenizer vocabulary — without loading tensor data.
Spec: https://github.com/ggml-org/ggml/blob/master/docs/gguf.md
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, BinaryIO, Dict, List, Optional

GGUF_MAGIC = b"GGUF"

# metadata value type ids (gguf spec)
T_U8, T_I8, T_U16, T_I16, T_U32, T_I32, T_F32, T_BOOL = range(8)
T_STRING, T_ARRAY, T_U64, T_I64, T_F64 = 8, 9, 10, 11, 12

_SCALARS = {
    T_U8: ("<B", 1), T_I8: ("<b", 1), T_U16: ("<H", 2), T_I16: ("<h", 2),
    T_U32: ("<I", 4), T_I32: ("<i", 4), T_F32: ("<f", 4), T_BOOL: ("<?", 1),
    T_U64: ("<Q", 8), T_I64: ("<q", 8), T_F64: ("<d", 8),
}


def _read_scalar(f: BinaryIO, vtype: int):
    fmt, size = _SCALARS[vtype]
    return struct.unpack(fmt, f.read(size))[0]


def _read_string(f: BinaryIO) -> str:
    (n,) = struct.unpack("<Q", f.read(8))
    return f.read(n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int):
    if vtype in _SCALARS:
        return _read_scalar(f, vtype)
    if vtype == T_STRING:
        return _read_string(f)
    if vtype == T_ARRAY:
        (elem_type,) = struct.unpack("<I", f.read(4))
        (count,) = struct.unpack("<Q", f.read(8))
        return [_read_value(f, elem_type) for _ in range(count)]
    raise ValueError(f"unknown gguf metadata type {vtype}")


@dataclass
class GgufContent:
    version: int
    tensor_count: int
    metadata: Dict[str, Any] = field(default_factory=dict)

    # -- typed accessors over the conventional keys ------------------------
    @property
    def architecture(self) -> Optional[str]:
        return self.metadata.get("general.architecture")

    @property
    def name(self) -> Optional[str]:
        return self.metadata.get("general.name")

    def arch_key(self, suffix: str):
        arch = self.architecture
        return self.metadata.get(f"{arch}.{suffix}") if arch else None

    @property
    def context_length(self) -> Optional[int]:
        return self.arch_key("context_length")

    @property
    def num_layers(self) -> Optional[int]:
        return self.arch_key("block_count")

    @property
    def num_heads(self) -> Optional[int]:
        return self.arch_key("attention.head_count")

    @property
    def num_kv_heads(self) -> Optional[int]:
        return self.arch_key("attention.head_count_kv") or self.num_heads

    @property
    def hidden_size(self) -> Optional[int]:
        return self.arch_key("embedding_length")

    # -- embedded tokenizer (gguf_tokenizer.rs role) -----------------------
    @property
    def tokenizer_model(self) -> Optional[str]:
        return self.metadata.get("tokenizer.ggml.model")

    @property
    def tokens(self) -> Optional[List[str]]:
        return self.metadata.get("tokenizer.ggml.tokens")

    @property
    def bos_token_id(self) -> Optional[int]:
        return self.metadata.get("tokenizer.ggml.bos_token_id")

    @property
    def eos_token_id(self) -> Optional[int]:
        return self.metadata.get("tokenizer.ggml.eos_token_id")

    @property
    def chat_template(self) -> Optional[str]:
        return self.metadata.get("tokenizer.chat_template")


def read_gguf(path) -> GgufContent:
    """Parse header + metadata (tensor infos and data are skipped)."""
    with open(path, "rb") as f:
        if f.read(4) != GGUF_MAGIC:
            raise ValueError(f"{path}: not a GGUF file")
        (version,) = struct.unpack("<I", f.read(4))
        if version < 2:
            raise ValueError(f"{path}: gguf v{version} unsupported (need >= 2)")
        (tensor_count,) = struct.unpack("<Q", f.read(8))
        (kv_count,) = struct.unpack("<Q", f.read(8))
        meta: Dict[str, Any] = {}
        for _ in range(kv_count):
            key = _read_string(f)
            (vtype,) = struct.unpack("<I", f.read(4))
            meta[key] = _read_value(f, vtype)
    return GgufContent(version=version, tensor_count=tensor_count, metadata=meta)


def mdc_from_gguf(path, kv_cache_block_size: int = 64):
    """Build a ModelDeploymentCard from a .gguf file (reference
    LocalModelBuilder's GGUF path, local_model.rs:44)."""
    from .model_card import ModelDeploymentCard

    g = read_gguf(path)
    name = g.name or Path(path).stem
    card = ModelDeploymentCard(
        name=name,
        tokenizer=f"gguf:{path}",
        context_length=g.context_length or 8192,
        kv_cache_block_size=kv_cache_block_size,
        chat_template=g.chat_template,
    )
    card.runtime_config["gguf"] = {
        "architecture": g.architecture,
        "num_layers": g.num_layers,
        "num_heads": g.num_heads,
        "num_kv_heads": g.num_kv_heads,
        "hidden_size": g.hidden_size,
        "tokenizer_model": g.tokenizer_model,
        "bos_token_id": g.bos_token_id,
        "eos_token_id": g.eos_token_id,
    }
    return card


def write_gguf(path, metadata: Dict[str, Any], tensor_count: int = 0) -> None:
    """Minimal GGUF writer (metadata only) — testing and interchange."""

    def w_string(f, s: str):
        b = s.encode()
        f.write(struct.pack("<Q", len(b)))
        f.write(b)

    def w_value(f, v):
        if isinstance(v, bool):
            f.write(struct.pack("<I", T_BOOL))
            f.write(struct.pack("<?", v))
        elif isinstance(v, int):
            f.write(struct.pack("<I", T_I64))
            f.write(struct.pack("<q", v))
        elif isinstance(v, float):
            f.write(struct.pack("<I", T_F64))
            f.write(struct.pack("<d", v))
        elif isinstance(v, str):
            f.write(struct.pack("<I", T_STRING))
            w_string(f, v)
        elif isinstance(v, list):
            f.write(struct.pack("<I", T_ARRAY))
            if v and isinstance(v[0], str):
                f.write(struct.pack("<I", T_STRING))
                f.write(struct.pack("<Q", len(v)))
                for s in v:
                    w_string(f, s)
            else:
                f.write(struct.pack("<I", T_I64))
                f.write(struct.pack("<Q", len(v)))
                for x in v:
                    f.write(struct.pack("<q", x))
        else:
            raise TypeError(f"unsupported gguf value {type(v)}")

    with open(path, "wb") as f:
        f.write(GGUF_MAGIC)
        f.write(struct.pack("<I", 3))
        f.write(struct.pack("<Q", tensor_count))
        f.write(struct.pack("<Q", len(metadata)))
        for k, v in metadata.items():
            w_string(f, k)
            w_value(f, v)
