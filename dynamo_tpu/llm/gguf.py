"""GGUF file parsing: header, metadata KVs, embedded tokenizer, tensors.

Role of the reference's gguf module (lib/llm/src/gguf/{content,
gguf_metadata,gguf_tokenizer}.rs): read enough of a .gguf checkpoint to
build a ModelDeploymentCard. The reference stops at metadata (tensor
serving is delegated to llamacpp); here the tensor table + data are ALSO
readable (f32 / f16 / q8_0), so a .gguf checkpoint loads straight into
the JAX engine (models/loader.py gguf path) — no llamacpp needed.
Spec: https://github.com/ggml-org/ggml/blob/master/docs/gguf.md
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, BinaryIO, Dict, List, Optional

GGUF_MAGIC = b"GGUF"
GGUF_ALIGNMENT = 32  # spec default (general.alignment overrides)

# ggml tensor dtypes we read/write
GGML_F32, GGML_F16, GGML_Q8_0 = 0, 1, 8
Q8_0_BLOCK = 32  # elements per q8_0 block (f16 scale + 32 int8)

# metadata value type ids (gguf spec)
T_U8, T_I8, T_U16, T_I16, T_U32, T_I32, T_F32, T_BOOL = range(8)
T_STRING, T_ARRAY, T_U64, T_I64, T_F64 = 8, 9, 10, 11, 12

_SCALARS = {
    T_U8: ("<B", 1), T_I8: ("<b", 1), T_U16: ("<H", 2), T_I16: ("<h", 2),
    T_U32: ("<I", 4), T_I32: ("<i", 4), T_F32: ("<f", 4), T_BOOL: ("<?", 1),
    T_U64: ("<Q", 8), T_I64: ("<q", 8), T_F64: ("<d", 8),
}


def _read_scalar(f: BinaryIO, vtype: int):
    fmt, size = _SCALARS[vtype]
    return struct.unpack(fmt, f.read(size))[0]


def _read_string(f: BinaryIO) -> str:
    (n,) = struct.unpack("<Q", f.read(8))
    return f.read(n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int):
    if vtype in _SCALARS:
        return _read_scalar(f, vtype)
    if vtype == T_STRING:
        return _read_string(f)
    if vtype == T_ARRAY:
        (elem_type,) = struct.unpack("<I", f.read(4))
        (count,) = struct.unpack("<Q", f.read(8))
        return [_read_value(f, elem_type) for _ in range(count)]
    raise ValueError(f"unknown gguf metadata type {vtype}")


@dataclass
class GgufTensorInfo:
    name: str
    shape: tuple  # numpy order (ggml's ne[] is reversed: ne[0] = innermost)
    ggml_type: int
    offset: int  # within the aligned data blob


@dataclass
class GgufContent:
    version: int
    tensor_count: int
    metadata: Dict[str, Any] = field(default_factory=dict)
    # populated by read_gguf(with_tensors=True):
    tensors: Dict[str, GgufTensorInfo] = field(default_factory=dict)
    data_start: int = 0
    path: Optional[str] = None

    # -- typed accessors over the conventional keys ------------------------
    @property
    def architecture(self) -> Optional[str]:
        return self.metadata.get("general.architecture")

    @property
    def name(self) -> Optional[str]:
        return self.metadata.get("general.name")

    def arch_key(self, suffix: str):
        arch = self.architecture
        return self.metadata.get(f"{arch}.{suffix}") if arch else None

    @property
    def context_length(self) -> Optional[int]:
        return self.arch_key("context_length")

    @property
    def num_layers(self) -> Optional[int]:
        return self.arch_key("block_count")

    @property
    def num_heads(self) -> Optional[int]:
        return self.arch_key("attention.head_count")

    @property
    def num_kv_heads(self) -> Optional[int]:
        return self.arch_key("attention.head_count_kv") or self.num_heads

    @property
    def hidden_size(self) -> Optional[int]:
        return self.arch_key("embedding_length")

    # -- embedded tokenizer (gguf_tokenizer.rs role) -----------------------
    @property
    def tokenizer_model(self) -> Optional[str]:
        return self.metadata.get("tokenizer.ggml.model")

    @property
    def tokens(self) -> Optional[List[str]]:
        return self.metadata.get("tokenizer.ggml.tokens")

    @property
    def bos_token_id(self) -> Optional[int]:
        return self.metadata.get("tokenizer.ggml.bos_token_id")

    @property
    def eos_token_id(self) -> Optional[int]:
        return self.metadata.get("tokenizer.ggml.eos_token_id")

    @property
    def chat_template(self) -> Optional[str]:
        return self.metadata.get("tokenizer.chat_template")


def read_gguf(path, with_tensors: bool = False) -> GgufContent:
    """Parse header + metadata; with_tensors=True also parses the tensor
    table and records the aligned data-blob offset for load_tensor."""
    with open(path, "rb") as f:
        if f.read(4) != GGUF_MAGIC:
            raise ValueError(f"{path}: not a GGUF file")
        (version,) = struct.unpack("<I", f.read(4))
        if version < 2:
            raise ValueError(f"{path}: gguf v{version} unsupported (need >= 2)")
        (tensor_count,) = struct.unpack("<Q", f.read(8))
        (kv_count,) = struct.unpack("<Q", f.read(8))
        meta: Dict[str, Any] = {}
        for _ in range(kv_count):
            key = _read_string(f)
            (vtype,) = struct.unpack("<I", f.read(4))
            meta[key] = _read_value(f, vtype)
        tensors: Dict[str, GgufTensorInfo] = {}
        data_start = 0
        if with_tensors:
            for _ in range(tensor_count):
                name = _read_string(f)
                (n_dims,) = struct.unpack("<I", f.read(4))
                dims = struct.unpack(f"<{n_dims}Q", f.read(8 * n_dims))
                (ggml_type,) = struct.unpack("<I", f.read(4))
                (offset,) = struct.unpack("<Q", f.read(8))
                tensors[name] = GgufTensorInfo(
                    name=name, shape=tuple(reversed(dims)),
                    ggml_type=ggml_type, offset=offset,
                )
            align = int(meta.get("general.alignment", GGUF_ALIGNMENT))
            pos = f.tell()
            data_start = (pos + align - 1) // align * align
    return GgufContent(
        version=version, tensor_count=tensor_count, metadata=meta,
        tensors=tensors, data_start=data_start, path=str(path),
    )


def load_tensor(content: GgufContent, name: str):
    """Read one tensor as float32 numpy (f32 / f16 / q8_0)."""
    import numpy as np

    info = content.tensors[name]
    n = 1
    for d in info.shape:
        n *= d
    with open(content.path, "rb") as f:
        f.seek(content.data_start + info.offset)
        if info.ggml_type == GGML_F32:
            arr = np.fromfile(f, dtype="<f4", count=n)
        elif info.ggml_type == GGML_F16:
            arr = np.fromfile(f, dtype="<f2", count=n).astype(np.float32)
        elif info.ggml_type == GGML_Q8_0:
            if n % Q8_0_BLOCK:
                raise ValueError(f"{name}: q8_0 size {n} not /{Q8_0_BLOCK}")
            blocks = np.fromfile(
                f, dtype=np.dtype([("d", "<f2"), ("qs", "i1", (Q8_0_BLOCK,))]),
                count=n // Q8_0_BLOCK,
            )
            arr = (
                blocks["d"].astype(np.float32)[:, None]
                * blocks["qs"].astype(np.float32)
            ).reshape(-1)
        else:
            raise ValueError(
                f"{name}: ggml type {info.ggml_type} unsupported "
                f"(f32/f16/q8_0 only)"
            )
    return np.asarray(arr, np.float32).reshape(info.shape)


def mdc_from_gguf(path, kv_cache_block_size: int = 64):
    """Build a ModelDeploymentCard from a .gguf file (reference
    LocalModelBuilder's GGUF path, local_model.rs:44)."""
    from .model_card import ModelDeploymentCard

    g = read_gguf(path)
    name = g.name or Path(path).stem
    card = ModelDeploymentCard(
        name=name,
        tokenizer=f"gguf:{path}",
        context_length=g.context_length or 8192,
        kv_cache_block_size=kv_cache_block_size,
        chat_template=g.chat_template,
    )
    card.runtime_config["gguf"] = {
        "architecture": g.architecture,
        "num_layers": g.num_layers,
        "num_heads": g.num_heads,
        "num_kv_heads": g.num_kv_heads,
        "hidden_size": g.hidden_size,
        "tokenizer_model": g.tokenizer_model,
        "bos_token_id": g.bos_token_id,
        "eos_token_id": g.eos_token_id,
    }
    return card


def write_gguf(path, metadata: Dict[str, Any], tensor_count: int = 0,
               tensors: Optional[Dict[str, Any]] = None,
               tensor_types: Optional[Dict[str, int]] = None) -> None:
    """Minimal GGUF writer — testing and interchange. `tensors` maps
    name -> float32 ndarray; `tensor_types` picks GGML_F32 (default),
    GGML_F16 or GGML_Q8_0 per tensor (q8_0 quantizes on write)."""
    import numpy as np

    def w_string(f, s: str):
        b = s.encode()
        f.write(struct.pack("<Q", len(b)))
        f.write(b)

    def w_value(f, v):
        if isinstance(v, bool):
            f.write(struct.pack("<I", T_BOOL))
            f.write(struct.pack("<?", v))
        elif isinstance(v, int):
            f.write(struct.pack("<I", T_I64))
            f.write(struct.pack("<q", v))
        elif isinstance(v, float):
            f.write(struct.pack("<I", T_F64))
            f.write(struct.pack("<d", v))
        elif isinstance(v, str):
            f.write(struct.pack("<I", T_STRING))
            w_string(f, v)
        elif isinstance(v, list):
            f.write(struct.pack("<I", T_ARRAY))
            if v and isinstance(v[0], str):
                f.write(struct.pack("<I", T_STRING))
                f.write(struct.pack("<Q", len(v)))
                for s in v:
                    w_string(f, s)
            else:
                f.write(struct.pack("<I", T_I64))
                f.write(struct.pack("<Q", len(v)))
                for x in v:
                    f.write(struct.pack("<q", x))
        else:
            raise TypeError(f"unsupported gguf value {type(v)}")

    def encode_tensor(arr: "np.ndarray", t: int) -> bytes:
        flat = np.asarray(arr, np.float32).reshape(-1)
        if t == GGML_F32:
            return flat.astype("<f4").tobytes()
        if t == GGML_F16:
            return flat.astype("<f2").tobytes()
        if t == GGML_Q8_0:
            if flat.size % Q8_0_BLOCK:
                raise ValueError(f"q8_0 needs size /{Q8_0_BLOCK}")
            b = flat.reshape(-1, Q8_0_BLOCK)
            d = np.maximum(np.abs(b).max(axis=1), 1e-12) / 127.0
            qs = np.clip(np.round(b / d[:, None]), -127, 127).astype(np.int8)
            out = np.empty(
                b.shape[0],
                np.dtype([("d", "<f2"), ("qs", "i1", (Q8_0_BLOCK,))]),
            )
            out["d"] = d.astype("<f2")
            out["qs"] = qs
            return out.tobytes()
        raise ValueError(f"unsupported write type {t}")

    tensors = tensors or {}
    tensor_types = tensor_types or {}
    align = int(metadata.get("general.alignment", GGUF_ALIGNMENT))
    with open(path, "wb") as f:
        f.write(GGUF_MAGIC)
        f.write(struct.pack("<I", 3))
        f.write(struct.pack("<Q", tensor_count or len(tensors)))
        f.write(struct.pack("<Q", len(metadata)))
        for k, v in metadata.items():
            w_string(f, k)
            w_value(f, v)
        if tensors:
            blobs = []
            offset = 0
            for name, arr in tensors.items():
                t = tensor_types.get(name, GGML_F32)
                blob = encode_tensor(arr, t)
                w_string(f, name)
                dims = tuple(reversed(arr.shape))  # ggml ne order
                f.write(struct.pack("<I", len(dims)))
                f.write(struct.pack(f"<{len(dims)}Q", *dims))
                f.write(struct.pack("<I", t))
                f.write(struct.pack("<Q", offset))
                blobs.append(blob)
                offset += (len(blob) + align - 1) // align * align
            pos = f.tell()
            f.write(b"\x00" * (-pos % align))
            for blob in blobs:
                f.write(blob)
                f.write(b"\x00" * (-len(blob) % align))
