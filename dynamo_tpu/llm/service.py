"""Serving-pipeline assembly.

Mirrors reference lib/llm/src/entrypoint/input/common.rs:259-310
(build_routed_pipeline): the canonical chain

    OpenAIPreprocessor.fwd → Backend.fwd → Migration.fwd →
      ServiceBackend(PushRouter | KvPushRouter)   [network hop]
    → Migration.bwd → Backend.bwd → Preprocessor.bwd → frontend

Built on the generic operator-graph framework (runtime/pipeline.py:
Operator forward/backward/around + compose — the reference's pipeline.rs
node model): Backend contributes a backward stream transform, Migration
owns the downstream call (retry), ServiceBackend is the sink.
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator, Optional

from ..runtime.component import Client
from ..runtime.engine import AsyncEngine, Context
from ..runtime.push_router import PushRouter, RouterMode
from .backend import Backend
from .migration import Migration
from .model_card import ModelDeploymentCard
from .preprocessor import OpenAIPreprocessor
from .protocols import Annotated, PreprocessedRequest
from .tokenizers import Tokenizer, load_tokenizer

logger = logging.getLogger(__name__)


class ServiceBackend:
    """The network hop: adapt a PushRouter (or KvPushRouter) into an
    AsyncEngine over PreprocessedRequest dicts
    (reference ServiceBackend in pipeline nodes)."""

    def __init__(self, router):
        self.router = router

    async def generate(
        self, request: PreprocessedRequest, context: Context
    ) -> AsyncIterator[Any]:
        payload = request.to_dict() if isinstance(request, PreprocessedRequest) else request
        stream = await self.router.generate(payload, context)
        async for item in stream:
            yield item


class ModelPipeline:
    """A ready-to-serve model: preprocessor + backend + migration + router.
    Entry points: chat / completion streaming generators consumed by the
    HTTP service."""

    def __init__(
        self,
        card: ModelDeploymentCard,
        tokenizer: Tokenizer,
        engine: AsyncEngine,
        raw_engine: Optional[AsyncEngine] = None,
    ):
        self.card = card
        self.tokenizer = tokenizer
        self.preprocessor = OpenAIPreprocessor(card, tokenizer)
        self.engine = engine  # Backend(Migration(ServiceBackend(router)))
        # the chain below the detokenizer: embeddings and other non-token
        # responses must not pass through incremental detokenization
        self.raw_engine = raw_engine or engine

    def generate_preprocessed(
        self, request: PreprocessedRequest, context: Context
    ) -> AsyncIterator[Annotated]:
        return self.engine.generate(request, context)


def build_routed_pipeline(
    card: ModelDeploymentCard,
    client: Client,
    router_mode: RouterMode = RouterMode.ROUND_ROBIN,
    kv_router=None,
    busy_threshold: Optional[float] = None,
    encode_client: Optional[Client] = None,
    instance_prefer=None,
) -> ModelPipeline:
    """Assemble the canonical chain for one model
    (reference common.rs:259-310) via the operator graph.
    `encode_client`: endpoint client of a multimodal encode worker — adds
    the E hop of E/P/D ahead of the chain (llm/multimodal.py).
    `instance_prefer`: dynogate load-preference hook for the PushRouter
    (below-watermark instances dialed first, docs/overload.md)."""
    from ..runtime.pipeline import compose

    tokenizer = load_tokenizer(card.tokenizer)
    if router_mode == RouterMode.KV and kv_router is not None:
        router = kv_router
    else:
        router = PushRouter(client, router_mode, prefer=instance_prefer)
    sink = ServiceBackend(router)
    migration = Migration(migration_limit=card.migration_limit)
    backend = Backend(tokenizer=tokenizer)
    ops = [backend, migration]
    if encode_client is not None:
        from .multimodal import EncodeOperator

        ops.insert(0, EncodeOperator(
            PushRouter(encode_client, RouterMode.ROUND_ROBIN),
            tokenizer.vocab_size,
        ))
    engine = compose(ops, sink)
    raw_engine = compose([migration], sink)  # below the detokenizer
    return ModelPipeline(card, tokenizer, engine, raw_engine=raw_engine)
