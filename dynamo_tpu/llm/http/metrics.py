"""HTTP frontend Prometheus metrics.

Mirrors reference lib/llm/src/http/service/metrics.rs: request counters,
in-flight gauge, duration + TTFT + output-token histograms, disconnects —
labeled by model and endpoint type, exported at /metrics.
"""

from __future__ import annotations

import time

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)


class HttpMetrics:
    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        ns = "dynamo_frontend"
        self.requests_total = Counter(
            f"{ns}_requests_total",
            "Total HTTP LLM requests",
            ["model", "endpoint", "status"],
            registry=self.registry,
        )
        self.inflight = Gauge(
            f"{ns}_inflight_requests",
            "Requests currently being processed",
            ["model", "endpoint"],
            registry=self.registry,
        )
        self.request_duration = Histogram(
            f"{ns}_request_duration_seconds",
            "End-to-end request duration",
            ["model", "endpoint"],
            registry=self.registry,
            buckets=(0.05, 0.1, 0.25, 0.5, 1, 2, 4, 8, 16, 32, 64, 128),
        )
        self.ttft = Histogram(
            f"{ns}_time_to_first_token_seconds",
            "Time to first token",
            ["model"],
            registry=self.registry,
            buckets=(0.01, 0.025, 0.05, 0.1, 0.2, 0.4, 0.8, 1.6, 3.2, 6.4, 12.8),
        )
        self.output_tokens = Counter(
            f"{ns}_output_tokens_total",
            "Total generated tokens",
            ["model"],
            registry=self.registry,
        )
        self.input_tokens = Counter(
            f"{ns}_input_tokens_total",
            "Total prompt tokens",
            ["model"],
            registry=self.registry,
        )
        self.itl = Histogram(
            f"{ns}_inter_token_latency_seconds",
            "Mean inter-token latency per request",
            ["model"],
            registry=self.registry,
            buckets=(0.002, 0.005, 0.01, 0.02, 0.04, 0.08, 0.16, 0.32, 0.64, 1.28),
        )
        self.disconnects = Counter(
            f"{ns}_client_disconnects_total",
            "Client disconnects mid-stream",
            ["model"],
            registry=self.registry,
        )
        # token-path batching visibility: tokens per streamed delta batch
        # (= per SSE event). Mean > 1 in steady decode means the batched
        # emit/coalesce path is active end-to-end; mean == 1 flags a
        # serving plane paying per-token overhead again.
        self.tokens_per_frame = Histogram(
            f"{ns}_tokens_per_frame",
            "Generated tokens carried by each streamed delta batch",
            ["model"],
            registry=self.registry,
            buckets=(1, 2, 4, 8, 16, 32, 64, 128),
        )

    def request_start(self, model: str, endpoint: str):
        self.inflight.labels(model, endpoint).inc()

    def request_end(
        self,
        model: str,
        endpoint: str,
        t0: float,
        error: bool = False,
        output_tokens: int = 0,
        input_tokens: int = 0,
        first_token_at: float | None = None,
        last_token_at: float | None = None,
    ):
        self.inflight.labels(model, endpoint).dec()
        self.requests_total.labels(model, endpoint, "error" if error else "success").inc()
        now = time.monotonic()
        self.request_duration.labels(model, endpoint).observe(now - t0)
        if output_tokens:
            self.output_tokens.labels(model).inc(output_tokens)
        if input_tokens:
            self.input_tokens.labels(model).inc(input_tokens)
        # ITL over first→last token, not request end (post-stream work such
        # as [DONE]/usage frames must not inflate the planner's signal)
        if first_token_at is not None and last_token_at is not None and output_tokens > 1:
            self.itl.labels(model).observe(
                max(last_token_at - first_token_at, 0.0) / (output_tokens - 1)
            )

    def observe_ttft(self, model: str, seconds: float):
        self.ttft.labels(model).observe(seconds)

    def observe_tokens_per_frame(self, model: str, n_tokens: int):
        self.tokens_per_frame.labels(model).observe(n_tokens)

    def client_disconnect(self, model: str):
        self.disconnects.labels(model).inc()

    def render(self) -> bytes:
        return generate_latest(self.registry)
