from .service import HttpService

__all__ = ["HttpService"]
