"""OpenAI-compatible HTTP service.

Mirrors reference lib/llm/src/http/service/: route assembly
(service_v2.rs:319-339), chat/completions handlers (openai.rs), SSE
streaming with client-disconnect detection (disconnect.rs), Prometheus
metrics (metrics.rs), and the clear-kv-blocks admin route.

aiohttp replaces axum; a dropped client cancels the pipeline context
(kill), which propagates over the request plane to the worker.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Any, AsyncIterator, Optional

from aiohttp import web

from ...runtime.engine import Context
from ..discovery import ModelManager
from ..parsers import JailedStream
from ..preprocessor import ChatDeltaGenerator, CompletionDeltaGenerator
from ..protocols import (
    Annotated,
    ChatCompletionRequest,
    ChatCompletionResponse,
    ChatMessage,
    Choice,
    CompletionChoice,
    CompletionRequest,
    CompletionResponse,
    EmbeddingRequest,
    EmbeddingResponse,
    LLMEngineOutput,
    ModelInfo,
    ModelList,
    Usage,
)
from .metrics import HttpMetrics

logger = logging.getLogger(__name__)


#: compact separators on every wire-bound json.dumps — SSE framing bytes
#: are pure per-token overhead (llm/preprocessor.py COMPACT is the same
#: contract for the chunk templates)
_COMPACT = (",", ":")


def _sse_event(event: str, data: dict) -> bytes:
    """Named SSE event frame (Responses API framing)."""
    return (
        f"event: {event}\ndata: "
        f"{json.dumps(data, separators=_COMPACT)}\n\n".encode()
    )


def _content_text(message: dict) -> str:
    """Flatten a Responses-API message's content (string or typed parts)."""
    content = message.get("content", "")
    if isinstance(content, str):
        return content
    return "".join(
        p.get("text", "") for p in content if isinstance(p, dict)
    )


# chat n>1 fan-out bound (OpenAI caps n at 128; engine slots are the real
# limit here — one HTTP request must not monopolize the worker batch)
MAX_N_CHOICES = 8


def _sse(data: str) -> bytes:
    return f"data: {data}\n\n".encode()


class HttpService:
    """The frontend HTTP server (reference HttpService service_v2.rs)."""

    def __init__(
        self,
        manager: ModelManager,
        host: str = "0.0.0.0",
        port: int = 8000,
        enable_responses: bool = True,
        gate=None,
    ):
        self.manager = manager
        self.host, self.port = host, port
        self.metrics = HttpMetrics()
        # dynogate admission control (gate/, docs/overload.md): consulted
        # BEFORE tokenization on every token-generating route. None (or a
        # DYN_GATE=0 gate) = the pre-gate request path, byte-identical.
        self.gate = gate
        self.app = web.Application(client_max_size=64 * 1024 * 1024)
        self._runner: Optional[web.AppRunner] = None
        self._setup_routes()

    def _setup_routes(self):
        # reference route assembly: service_v2.rs:319-339
        self.app.router.add_post("/v1/chat/completions", self.chat_completions)
        self.app.router.add_post("/v1/completions", self.completions)
        self.app.router.add_post("/v1/embeddings", self.embeddings)
        self.app.router.add_post("/v1/responses", self.responses)
        self.app.router.add_get("/v1/models", self.list_models)
        self.app.router.add_get("/health", self.health)
        self.app.router.add_get("/live", self.live)
        self.app.router.add_get("/metrics", self.prometheus)
        # admin: flush every worker's reusable KV blocks (reference
        # clear_kv_blocks route assembly, service_v2.rs:319-339)
        self.app.router.add_post("/clear-kv-blocks", self.clear_kv_blocks)

    async def start(self) -> int:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in site._server.sockets:  # resolve ephemeral port
            self.port = s.getsockname()[1]
            break
        logger.info("HTTP service listening on %s:%d", self.host, self.port)
        return self.port

    async def stop(self):
        if self._runner:
            await self._runner.cleanup()

    # ------------------------------------------------------------------ #
    # handlers
    # ------------------------------------------------------------------ #

    async def health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "healthy", "models": self.manager.names()})

    async def live(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def prometheus(self, request: web.Request) -> web.Response:
        from ..migration import MIGRATION_METRICS

        body = self.metrics.render()
        if self.gate is not None and self.gate.config.enabled:
            body += self.gate.render_prometheus()
        # migration observability (docs/fault_tolerance.md): what worker
        # deaths cost this frontend's streams
        body += MIGRATION_METRICS.render_prometheus()
        return web.Response(
            body=body, content_type="text/plain", charset="utf-8"
        )

    # ------------------------------------------------------------------ #
    # dynogate admission (docs/overload.md)
    # ------------------------------------------------------------------ #

    def _gate_tenant(self, request: web.Request) -> str:
        header = self.gate.config.tenant_header
        return (request.headers.get(header, "") if header else "") or "default"

    @staticmethod
    def _gate_priority(body: dict) -> int:
        nvext = body.get("nvext")
        raw = nvext.get("priority") if isinstance(nvext, dict) else None
        try:
            return int(raw) if raw is not None else 0
        except (TypeError, ValueError):
            return 0  # the preprocessor 400s it later; the gate is lenient

    async def _gate_admit(
        self, request: web.Request, model: str, body: dict, endpoint: str, t0
    ):
        """Run the admission gate ahead of tokenization. Returns
        (None, tenant) when admitted, or (a finished 429 response, tenant)
        when the request is rejected/shed — the body carries the decision
        detail and the Retry-After header tells the client exactly when
        to come back (docs/overload.md)."""
        if self.gate is None or not self.gate.config.enabled:
            return None, None
        from ...gate import retry_after_header

        tenant = self._gate_tenant(request)
        priority = self._gate_priority(body)
        decision = await self.gate.admit(model, tenant, priority)
        if decision.admitted:
            return None, tenant
        self.metrics.request_start(model, endpoint)
        self.metrics.request_end(model, endpoint, t0, error=True)
        detail = {
            "message": (
                f"overloaded: admission {decision.reason} for tenant "
                f"{tenant!r} (retry after {decision.retry_after_s:.1f}s)"
            ),
            "type": "overloaded",
            "code": 429,
            "reason": decision.reason,
            "tenant": tenant,
            "priority": priority,
            "retry_after_s": round(decision.retry_after_s, 3),
        }
        if decision.projected_ttft_ms is not None:
            detail["projected_ttft_ms"] = round(decision.projected_ttft_ms, 1)
        resp = web.json_response(
            {"error": detail},
            status=429,
            headers={"Retry-After": retry_after_header(decision.retry_after_s)},
        )
        return resp, tenant

    async def clear_kv_blocks(self, request: web.Request) -> web.Response:
        """Tell every worker instance of every (or one given) model to drop
        its reusable KV blocks; returns per-instance cleared counts."""
        model_filter = request.query.get("model")
        results: dict = {}
        for name in self.manager.names():
            if model_filter and name != model_filter:
                continue
            client = self.manager.client_for(name)
            if client is None:
                continue
            per_model: dict = {}
            for inst in client.instance_ids():
                try:
                    ctx = Context()
                    stream = await client.direct(
                        {"annotations": ["clear_kv_blocks"], "token_ids": []},
                        inst,
                        ctx,
                    )
                    cleared = None
                    async for item in stream:
                        ev = item.get("event") if isinstance(item, dict) else None
                        if ev == "clear_kv_blocks":
                            cleared = int((item.get("comment") or ["0"])[0])
                    per_model[f"{inst:x}"] = cleared if cleared is not None else "no-op"
                except Exception as e:  # noqa: BLE001 — report per instance
                    per_model[f"{inst:x}"] = f"error: {e}"
            results[name] = per_model
        return web.json_response({"cleared": results})

    async def _embed_one(self, pipeline, token_ids: list[int]) -> list[float]:
        """One embed round-trip below the detokenizer; raises on engine
        errors (including migration-exhausted annotations)."""
        from ..protocols import PreprocessedRequest

        ctx = Context()
        pre = PreprocessedRequest(
            token_ids=token_ids,
            embed=True,
            stop_conditions={"max_tokens": 1},
        )
        try:
            async for out in pipeline.raw_engine.generate(pre, ctx):
                if hasattr(out, "is_error") and out.is_error():
                    raise RuntimeError((out.comment or ["engine error"])[0])
                d = out.data if hasattr(out, "data") else out
                if isinstance(d, dict) and "embedding" in d:
                    return d["embedding"]
        finally:
            ctx.stop_generating()
        raise RuntimeError(
            "engine returned no embedding (model not embedding-capable?)"
        )

    async def embeddings(self, request: web.Request) -> web.Response:
        """/v1/embeddings (reference openai.rs embeddings handler): tokenize
        each input, embed all inputs concurrently below the detokenizer, and
        assemble the OpenAI embedding list."""
        t0 = time.monotonic()
        try:
            body = await request.json()
            req = EmbeddingRequest.model_validate(body)
        except Exception as e:  # noqa: BLE001
            return self._error(400, f"invalid request: {e}")
        if req.encoding_format not in (None, "float"):
            return self._error(
                400, f"encoding_format {req.encoding_format!r} not supported"
            )
        if req.dimensions is not None:
            return self._error(400, "dimensions parameter not supported")
        pipeline = self.manager.get(req.model)
        if pipeline is None:
            return self._error(404, f"model {req.model!r} not found", "model_not_found")
        self.metrics.request_start(req.model, "embeddings")
        error_msg = None
        prompt_tokens = 0
        data: list[dict] = []
        try:
            inputs = req.input if isinstance(req.input, list) else [req.input]
            if inputs and isinstance(inputs[0], int):  # single pre-tokenized prompt
                inputs = [inputs]
            token_lists = [
                pipeline.tokenizer.encode(item) if isinstance(item, str) else list(item)
                for item in inputs
            ]
            prompt_tokens = sum(len(t) for t in token_lists)
            results = await asyncio.gather(
                *(self._embed_one(pipeline, t) for t in token_lists),
                return_exceptions=True,
            )
            for i, emb in enumerate(results):
                if isinstance(emb, BaseException):
                    error_msg = str(emb)
                    break
                data.append({"object": "embedding", "index": i, "embedding": emb})
        except Exception as e:  # noqa: BLE001
            error_msg = str(e)
        finally:
            self.metrics.request_end(
                req.model, "embeddings", t0, error=bool(error_msg),
                input_tokens=prompt_tokens,
            )
        if error_msg:
            return self._error(500, error_msg, "engine_error")
        resp = EmbeddingResponse(
            data=data,
            model=req.model,
            usage=Usage(
                prompt_tokens=prompt_tokens, completion_tokens=0,
                total_tokens=prompt_tokens,
            ),
        )
        return web.json_response(resp.model_dump(exclude_none=True))

    async def responses(self, request: web.Request) -> web.StreamResponse:
        """/v1/responses (reference service_v2.rs:319-339 responses route,
        async-openai Responses types): `input` (string or message list) runs
        through the chat pipeline; unary returns a `response` object, stream
        emits response.created / response.output_text.delta /
        response.completed SSE events."""
        import secrets as _secrets

        t0 = time.monotonic()
        try:
            body = await request.json()
            model = body["model"]
            raw_input = body.get("input", "")
            stream_mode = bool(body.get("stream", False))
            max_tokens = body.get("max_output_tokens") or body.get("max_tokens")
        except Exception as e:  # noqa: BLE001
            return self._error(400, f"invalid request: {e}")
        pipeline = self.manager.get(model)
        if pipeline is None:
            return self._error(404, f"model {model!r} not found", "model_not_found")

        try:
            if isinstance(raw_input, str):
                messages = [{"role": "user", "content": raw_input}]
            elif isinstance(raw_input, list):
                messages = [
                    {"role": m.get("role", "user"), "content": _content_text(m)}
                    if isinstance(m, dict)
                    else {"role": "user", "content": str(m)}
                    for m in raw_input
                ]
            else:
                raise ValueError(f"input must be a string or list, got {type(raw_input).__name__}")
            if body.get("instructions"):
                messages.insert(0, {"role": "system", "content": body["instructions"]})
            chat_req = ChatCompletionRequest(
                model=model, messages=messages, max_tokens=max_tokens,
                temperature=body.get("temperature"), top_p=body.get("top_p"),
            )
        except Exception as e:  # noqa: BLE001 — malformed request, not a 500
            return self._error(400, f"invalid request: {e}")
        reject, tenant = await self._gate_admit(
            request, model, body, "responses", t0
        )
        if reject is not None:
            return reject
        self.metrics.request_start(model, "responses")
        ctx = Context()
        try:
            pre = await pipeline.preprocessor.preprocess_chat_async(chat_req)
        except ValueError as e:
            self.metrics.request_end(model, "responses", t0, error=True)
            return self._error(400, str(e))
        if tenant and tenant != "default":
            pre.tenant = tenant
        resp_id = f"resp_{_secrets.token_hex(12)}"
        engine_stream = pipeline.generate_preprocessed(pre, ctx)
        # same structured-output jail as the chat path (reasoning models must
        # not leak thinking tags into output_text)
        reasoning_parser = pipeline.card.runtime_config.get("reasoning_parser")
        if reasoning_parser:
            engine_stream = JailedStream(
                engine_stream, reasoning_parser=reasoning_parser
            ).__aiter__()

        texts: list[str] = []
        n_out = 0
        error_msg = None
        first_token_at = None
        last_token_at = None

        def response_obj(status: str) -> dict:
            return {
                "id": resp_id,
                "object": "response",
                "created_at": int(time.time()),
                "status": status,
                "model": model,
                "output": [
                    {
                        "type": "message",
                        "id": f"msg_{resp_id[5:]}",
                        "role": "assistant",
                        "status": status,
                        "content": [
                            {"type": "output_text", "text": "".join(texts),
                             "annotations": []}
                        ],
                    }
                ],
                "usage": {
                    "input_tokens": len(pre.token_ids),
                    "output_tokens": n_out,
                    "total_tokens": len(pre.token_ids) + n_out,
                },
            }

        sse_resp: Optional[web.StreamResponse] = None
        try:
            if stream_mode:
                sse_resp = web.StreamResponse(
                    status=200, headers={"Content-Type": "text/event-stream"}
                )
                await sse_resp.prepare(request)
                await sse_resp.write(
                    _sse_event("response.created",
                               {"type": "response.created",
                                "response": response_obj("in_progress")})
                )
            async for ann in engine_stream:
                if ann.is_error():
                    error_msg = (ann.comment or ["engine error"])[0]
                    break
                if ann.event is not None or ann.data is None:
                    continue
                out: LLMEngineOutput = ann.data
                if out.token_ids:
                    last_token_at = time.monotonic()
                    if first_token_at is None:
                        first_token_at = last_token_at
                        self.metrics.observe_ttft(model, first_token_at - t0)
                n_out += len(out.token_ids)
                if out.text:
                    texts.append(out.text)
                    if sse_resp is not None:
                        await sse_resp.write(
                            _sse_event(
                                "response.output_text.delta",
                                {"type": "response.output_text.delta",
                                 "item_id": f"msg_{resp_id[5:]}",
                                 "output_index": 0, "content_index": 0,
                                 "delta": out.text},
                            )
                        )
                if out.finish_reason:
                    break
        except (ConnectionResetError, asyncio.CancelledError):
            ctx.kill()
            self.metrics.client_disconnect(model)
            raise
        finally:
            ctx.stop_generating()
            self.metrics.request_end(
                model, "responses", t0, error=bool(error_msg),
                output_tokens=n_out, input_tokens=len(pre.token_ids),
                first_token_at=first_token_at, last_token_at=last_token_at,
            )
        if sse_resp is not None:
            ev = "response.failed" if error_msg else "response.completed"
            final = response_obj("failed" if error_msg else "completed")
            if error_msg:
                final["error"] = {"message": error_msg}
            await sse_resp.write(_sse_event(ev, {"type": ev, "response": final}))
            return sse_resp
        if error_msg:
            return self._error(500, error_msg, "engine_error")
        return web.json_response(response_obj("completed"))

    async def list_models(self, request: web.Request) -> web.Response:
        models = ModelList(data=[ModelInfo(id=name) for name in self.manager.names()])
        return web.json_response(models.model_dump())

    def _error(self, status: int, message: str, err_type: str = "invalid_request_error"):
        return web.json_response(
            {"error": {"message": message, "type": err_type, "code": status}},
            status=status,
        )

    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        t0 = time.monotonic()
        try:
            body = await request.json()
            req = ChatCompletionRequest.model_validate(body)
        except Exception as e:  # noqa: BLE001
            return self._error(400, f"invalid request: {e}")
        pipeline = self.manager.get(req.model)
        if pipeline is None:
            return self._error(404, f"model {req.model!r} not found", "model_not_found")
        # admission control BEFORE tokenization: a rejected request must
        # not spend compute-pool time on the chat template (docs/overload.md)
        reject, tenant = await self._gate_admit(request, req.model, body, "chat", t0)
        if reject is not None:
            return reject
        self.metrics.request_start(req.model, "chat")
        ctx = Context()
        try:
            pre = await pipeline.preprocessor.preprocess_chat_async(req)
        except ValueError as e:
            self.metrics.request_end(req.model, "chat", t0, error=True)
            return self._error(400, str(e))
        if tenant and tenant != "default":
            pre.tenant = tenant  # rides to the worker's fairness tiebreak
        include_usage = bool(
            req.stream_options and req.stream_options.include_usage
        )
        rc = pipeline.card.runtime_config
        tool_parser = rc.get("tool_call_parser") if req.tools else None
        reasoning_parser = rc.get("reasoning_parser")

        def mk_stream(p, c=None):
            s = pipeline.generate_preprocessed(p, c or ctx)
            # structured-output jail: hold tool-call/reasoning tokens out
            # of the content stream, release them parsed (parsers/jail.py)
            if tool_parser or reasoning_parser:
                s = JailedStream(
                    s, tool_parser=tool_parser,
                    reasoning_parser=reasoning_parser,
                ).__aiter__()
            return s

        n = req.n or 1
        if n > MAX_N_CHOICES:
            self.metrics.request_end(req.model, "chat", t0, error=True)
            return self._error(
                400, f"n is capped at {MAX_N_CHOICES} (got {n})"
            )
        if n > 1:
            # parallel sampling: n engine requests over the SAME prompt —
            # the prefix cache + in-flight skip-ahead dedupe the prompt
            # compute, so choices cost ~decode only (vLLM n>1 role).
            # Each choice runs under its OWN child context: a stop-string
            # hit on one choice must not cancel its siblings (parent
            # kill/stop still propagates to all).
            import dataclasses as _dc

            pres = []
            for i in range(n):
                p = _dc.replace(
                    pre,
                    request_id=f"{pre.request_id}-{i}",
                    sampling_options=dict(pre.sampling_options),
                )
                seed = p.sampling_options.get("seed")
                if seed is not None:
                    p.sampling_options["seed"] = int(seed) + i
                pres.append(p)
            gens = [
                ChatDeltaGenerator(
                    req.model, pre.request_id,
                    include_usage=include_usage, index=i,
                )
                for i in range(n)
            ]
            for g in gens:
                g.prompt_tokens = len(pre.token_ids)
            streams = [mk_stream(p, ctx.child()) for p in pres]
            try:
                if req.stream:
                    return await self._stream_chat_multi(
                        request, req, streams, gens, ctx, t0
                    )
                return await self._unary_chat_multi(
                    req, streams, gens, ctx, t0
                )
            finally:
                ctx.stop_generating()

        gen = ChatDeltaGenerator(
            req.model, pre.request_id, include_usage=include_usage,
        )
        gen.prompt_tokens = len(pre.token_ids)
        stream = mk_stream(pre)
        try:
            if req.stream:
                return await self._stream_chat(request, req, stream, gen, ctx, t0)
            return await self._unary_chat(req, stream, gen, ctx, t0)
        finally:
            ctx.stop_generating()

    async def _stream_chat(
        self, http_req, req, stream: AsyncIterator[Annotated], gen, ctx: Context, t0
    ) -> web.StreamResponse:
        """Single-choice streaming == the multi path with one stream (kept
        as an alias so chunk-handling fixes live in ONE place)."""
        return await self._stream_chat_multi(
            http_req, req, [stream], [gen], ctx, t0
        )

    async def _stream_chat_multi(
        self, http_req, req, streams, gens, ctx: Context, t0
    ) -> web.StreamResponse:
        """n>1 streaming: merge the per-choice streams into one SSE flow;
        every chunk carries its choice index (OpenAI multi-choice chunks)."""
        resp = web.StreamResponse(
            status=200,
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"},
        )
        await resp.prepare(http_req)
        n = len(streams)
        queue: asyncio.Queue = asyncio.Queue()

        async def pump(i, s):
            try:
                async for ann in s:
                    await queue.put((i, ann))
            finally:
                # synchronous: an await here is a cancellation delivery
                # point and the end-of-choice marker must always land
                queue.put_nowait((i, None))

        tasks = [asyncio.create_task(pump(i, s)) for i, s in enumerate(streams)]
        first_token_at = None
        last_token_at = None
        error = False
        done = 0
        finished = [False] * n
        try:
            while done < n:
                i, ann = await queue.get()
                gen = gens[i]
                if ann is None:
                    done += 1
                    if not finished[i] and not error:
                        await resp.write(_sse(gen.finish_chunk_json("stop")))
                        finished[i] = True
                    continue
                if ann.is_error():
                    error = True
                    msg = (ann.comment or ["engine error"])[0]
                    await resp.write(_sse(json.dumps(
                        {"error": {"message": msg}}, separators=_COMPACT)))
                    break
                if ann.event is not None:
                    await resp.write(
                        f": {ann.event} "
                        f"{json.dumps(ann.comment, separators=_COMPACT)}"
                        "\n\n".encode()
                    )
                    continue
                out: LLMEngineOutput = ann.data
                if out.token_ids:
                    last_token_at = time.monotonic()
                    if first_token_at is None:
                        first_token_at = last_token_at
                        self.metrics.observe_ttft(
                            req.model, first_token_at - t0)
                    self.metrics.observe_tokens_per_frame(
                        req.model, len(out.token_ids))
                if out.reasoning_content:
                    await resp.write(_sse(gen.reasoning_chunk(
                        out.reasoning_content).model_dump_json(
                            exclude_none=True)))
                if out.tool_calls:
                    await resp.write(_sse(gen.tool_calls_chunk(
                        out.tool_calls).model_dump_json(exclude_none=True)))
                if out.text or out.logprob_entries:
                    # one SSE event per delta batch; the preserialized
                    # template path serializes only the delta fields
                    if out.logprob_entries:
                        payload = gen.text_chunk(
                            out.text or "", len(out.token_ids),
                            logprob_entries=out.logprob_entries,
                        ).model_dump_json(exclude_none=True)
                    else:
                        payload = gen.text_chunk_json(
                            out.text or "", len(out.token_ids))
                    await resp.write(_sse(payload))
                elif out.token_ids:
                    gen.completion_tokens += len(out.token_ids)
                if out.finish_reason and not finished[i]:
                    await resp.write(_sse(gen.finish_chunk_json(
                        out.finish_reason)))
                    finished[i] = True
            if not error and gens[0].include_usage:
                usage = gens[0].usage_chunk()
                usage.usage.completion_tokens = sum(
                    g.completion_tokens for g in gens)
                usage.usage.total_tokens = (
                    gens[0].prompt_tokens + usage.usage.completion_tokens)
                await resp.write(_sse(usage.model_dump_json(exclude_none=True)))
            await resp.write(b"data: [DONE]\n\n")
        except (ConnectionResetError, asyncio.CancelledError):
            ctx.kill()
            self.metrics.client_disconnect(req.model)
            raise
        finally:
            for t in tasks:
                t.cancel()
            self.metrics.request_end(
                req.model, "chat", t0, error=error,
                output_tokens=sum(g.completion_tokens for g in gens),
                input_tokens=gens[0].prompt_tokens,
                first_token_at=first_token_at, last_token_at=last_token_at,
            )
        return resp

    async def _unary_chat_multi(
        self, req, streams, gens, ctx: Context, t0
    ) -> web.Response:
        """n>1 non-streamed: collect every choice, answer once."""
        from ..protocols.openai import chat_logprobs

        async def collect(s):
            texts, reasoning, tools, lp_entries = [], [], [], []
            finish, n_out, err = "stop", 0, None
            async for ann in s:
                if ann.is_error():
                    err = (ann.comment or ["engine error"])[0]
                    break
                if ann.event is not None:
                    continue
                out: LLMEngineOutput = ann.data
                n_out += len(out.token_ids)
                if out.reasoning_content:
                    reasoning.append(out.reasoning_content)
                if out.tool_calls:
                    tools.extend(out.tool_calls)
                if out.text:
                    texts.append(out.text)
                if out.logprob_entries:
                    lp_entries.extend(out.logprob_entries)
                if out.finish_reason:
                    finish = ("stop" if out.finish_reason == "eos"
                              else out.finish_reason)
                    break
            return texts, reasoning, tools, lp_entries, finish, n_out, err

        results = await asyncio.gather(*[collect(s) for s in streams])
        total_out = sum(r[5] for r in results)
        self.metrics.request_end(
            req.model, "chat", t0, error=any(r[6] for r in results),
            output_tokens=total_out, input_tokens=gens[0].prompt_tokens,
        )
        for r in results:
            if r[6]:
                return self._error(500, r[6], "engine_error")
        choices = []
        for i, (texts, reasoning, tools, lp_entries, finish, _n, _e) in \
                enumerate(results):
            message = ChatMessage(role="assistant", content="".join(texts))
            if reasoning:
                message.reasoning_content = "".join(reasoning)
            if tools:
                from ..protocols.openai import ToolCall

                message.tool_calls = [
                    ToolCall.model_validate(tc) for tc in tools]
                message.content = message.content or None
            choices.append(Choice(
                index=i, message=message, finish_reason=finish,
                logprobs=chat_logprobs(lp_entries),
            ))
        response = ChatCompletionResponse(
            id=gens[0].id,
            model=req.model,
            choices=choices,
            usage=Usage(
                prompt_tokens=gens[0].prompt_tokens,
                completion_tokens=total_out,
                total_tokens=gens[0].prompt_tokens + total_out,
            ),
        )
        return web.json_response(response.model_dump(exclude_none=True))

    async def _unary_chat(
        self, req, stream: AsyncIterator[Annotated], gen, ctx: Context, t0
    ) -> web.Response:
        texts: list[str] = []
        finish = "stop"
        n_out = 0
        error_msg = None
        first_token_at = None
        last_token_at = None
        reasoning_parts: list[str] = []
        tool_calls: list = []
        lp_entries: list = []
        async for ann in stream:
            if ann.is_error():
                error_msg = (ann.comment or ["engine error"])[0]
                break
            if ann.event is not None:
                continue
            out: LLMEngineOutput = ann.data
            if out.token_ids:
                last_token_at = time.monotonic()
                if first_token_at is None:
                    first_token_at = last_token_at
                    self.metrics.observe_ttft(req.model, first_token_at - t0)
            n_out += len(out.token_ids)
            if out.reasoning_content:
                reasoning_parts.append(out.reasoning_content)
            if out.tool_calls:
                tool_calls.extend(out.tool_calls)
            if out.text:
                texts.append(out.text)
            if out.logprob_entries:
                lp_entries.extend(out.logprob_entries)
            if out.finish_reason:
                finish = "stop" if out.finish_reason == "eos" else out.finish_reason
                break
        self.metrics.request_end(
            req.model, "chat", t0, error=bool(error_msg), output_tokens=n_out,
            input_tokens=gen.prompt_tokens, first_token_at=first_token_at,
            last_token_at=last_token_at,
        )
        if error_msg:
            return self._error(500, error_msg, "engine_error")
        message = ChatMessage(role="assistant", content="".join(texts))
        if reasoning_parts:
            message.reasoning_content = "".join(reasoning_parts)
        if tool_calls:
            from ..protocols.openai import ToolCall

            message.tool_calls = [ToolCall.model_validate(tc) for tc in tool_calls]
            message.content = message.content or None
        from ..protocols.openai import chat_logprobs

        chat_lp = chat_logprobs(lp_entries)
        response = ChatCompletionResponse(
            id=gen.id,
            model=req.model,
            choices=[
                Choice(
                    index=0,
                    message=message,
                    finish_reason=finish,
                    logprobs=chat_lp,
                )
            ],
            usage=Usage(
                prompt_tokens=gen.prompt_tokens,
                completion_tokens=n_out,
                total_tokens=gen.prompt_tokens + n_out,
            ),
        )
        return web.json_response(response.model_dump(exclude_none=True))

    async def completions(self, request: web.Request) -> web.StreamResponse:
        t0 = time.monotonic()
        try:
            body = await request.json()
            req = CompletionRequest.model_validate(body)
        except Exception as e:  # noqa: BLE001
            return self._error(400, f"invalid request: {e}")
        pipeline = self.manager.get(req.model)
        if pipeline is None:
            return self._error(404, f"model {req.model!r} not found", "model_not_found")
        reject, tenant = await self._gate_admit(
            request, req.model, body, "completions", t0
        )
        if reject is not None:
            return reject
        self.metrics.request_start(req.model, "completions")
        ctx = Context()
        try:
            pre = await pipeline.preprocessor.preprocess_completion_async(req)
        except ValueError as e:
            self.metrics.request_end(req.model, "completions", t0, error=True)
            return self._error(400, str(e))
        if tenant and tenant != "default":
            pre.tenant = tenant
        gen = CompletionDeltaGenerator(req.model, pre.request_id)
        gen.prompt_tokens = len(pre.token_ids)
        stream = pipeline.generate_preprocessed(pre, ctx)
        try:
            if req.stream:
                return await self._stream_completion(request, req, stream, gen, ctx, t0)
            return await self._unary_completion(req, stream, gen, ctx, t0)
        finally:
            ctx.stop_generating()

    async def _stream_completion(
        self, http_req, req, stream, gen, ctx: Context, t0
    ) -> web.StreamResponse:
        resp = web.StreamResponse(
            status=200, headers={"Content-Type": "text/event-stream"}
        )
        await resp.prepare(http_req)
        error = False
        first_token_at = None
        last_token_at = None
        try:
            finish_sent = False
            async for ann in stream:
                if ann.is_error():
                    error = True
                    msg = (ann.comment or ["engine error"])[0]
                    await resp.write(_sse(json.dumps(
                        {"error": {"message": msg}}, separators=_COMPACT)))
                    break
                if ann.event is not None:
                    await resp.write(
                        f": {ann.event} "
                        f"{json.dumps(ann.comment, separators=_COMPACT)}"
                        "\n\n".encode()
                    )
                    continue
                out: LLMEngineOutput = ann.data
                if out.token_ids:
                    last_token_at = time.monotonic()
                    if first_token_at is None:
                        first_token_at = last_token_at
                        self.metrics.observe_ttft(req.model, first_token_at - t0)
                    self.metrics.observe_tokens_per_frame(
                        req.model, len(out.token_ids))
                if out.text or out.logprob_entries:
                    if out.logprob_entries:
                        payload = gen.text_chunk(
                            out.text or "", len(out.token_ids),
                            logprob_entries=out.logprob_entries,
                        ).model_dump_json(exclude_none=True)
                    else:
                        payload = gen.text_chunk_json(
                            out.text or "", len(out.token_ids))
                    await resp.write(_sse(payload))
                elif out.token_ids:
                    # batch fully held back (mid multi-byte sequence /
                    # stop-string holdback): no chunk, but the tokens
                    # still count toward usage — same as the chat path
                    gen.completion_tokens += len(out.token_ids)
                if out.finish_reason:
                    await resp.write(_sse(gen.finish_chunk_json(out.finish_reason)))
                    finish_sent = True
                    break
            if not error and not finish_sent:
                await resp.write(_sse(gen.finish_chunk_json("stop")))
            if not error and req.stream_options \
                    and req.stream_options.include_usage:
                # completions parity with the chat route (and the KServe
                # stream's completion_tokens): a final usage chunk on ask
                await resp.write(
                    _sse(gen.usage_chunk().model_dump_json(exclude_none=True))
                )
            await resp.write(b"data: [DONE]\n\n")
        except (ConnectionResetError, asyncio.CancelledError):
            ctx.kill()
            self.metrics.client_disconnect(req.model)
            raise
        finally:
            self.metrics.request_end(
                req.model, "completions", t0, error=error,
                output_tokens=gen.completion_tokens,
                input_tokens=gen.prompt_tokens, first_token_at=first_token_at,
                last_token_at=last_token_at,
            )
        return resp

    async def _unary_completion(self, req, stream, gen, ctx: Context, t0) -> web.Response:
        texts: list[str] = []
        finish = "stop"
        n_out = 0
        error_msg = None
        first_token_at = None
        last_token_at = None
        lp_entries: list = []
        async for ann in stream:
            if ann.is_error():
                error_msg = (ann.comment or ["engine error"])[0]
                break
            if ann.event is not None:
                continue
            out: LLMEngineOutput = ann.data
            if out.token_ids:
                last_token_at = time.monotonic()
                if first_token_at is None:
                    first_token_at = last_token_at
                    self.metrics.observe_ttft(req.model, first_token_at - t0)
            n_out += len(out.token_ids)
            if out.text:
                texts.append(out.text)
            if out.logprob_entries:
                lp_entries.extend(out.logprob_entries)
            if out.finish_reason:
                finish = "stop" if out.finish_reason == "eos" else out.finish_reason
                break
        self.metrics.request_end(
            req.model, "completions", t0, error=bool(error_msg), output_tokens=n_out,
            input_tokens=gen.prompt_tokens, first_token_at=first_token_at,
            last_token_at=last_token_at,
        )
        if error_msg:
            return self._error(500, error_msg, "engine_error")
        from ..protocols.openai import completion_logprobs

        lp = completion_logprobs(lp_entries)
        response = CompletionResponse(
            id=gen.id,
            model=req.model,
            choices=[
                CompletionChoice(index=0, text="".join(texts),
                                 finish_reason=finish, logprobs=lp)
            ],
            usage=Usage(
                prompt_tokens=gen.prompt_tokens,
                completion_tokens=n_out,
                total_tokens=gen.prompt_tokens + n_out,
            ),
        )
        return web.json_response(response.model_dump(exclude_none=True))
