"""Model discovery: watch MDC records, keep per-model pipelines current.

Mirrors reference lib/llm/src/discovery/: `ModelWatcher::watch` (watcher.rs
:101) follows `v1/mdc/` in discovery, building a serving pipeline when the
first worker for a model appears and tearing it down when the last leaves;
`ModelManager` (model_manager.rs:35) holds the live pipelines the HTTP
service dispatches to.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Callable, Dict, Optional

from ..runtime.component import MODEL_ROOT, Client, DistributedRuntime
from ..runtime.push_router import RouterMode
from .model_card import ModelDeploymentCard
from .service import ModelPipeline, build_routed_pipeline

logger = logging.getLogger(__name__)


class ModelManager:
    """Live models by name (reference ModelManager model_manager.rs:35)."""

    def __init__(self):
        self._pipelines: Dict[str, ModelPipeline] = {}
        self._clients: Dict[str, Client] = {}
        self._kv_routers: Dict[str, object] = {}

    def get(self, model: str) -> Optional[ModelPipeline]:
        return self._pipelines.get(model)

    def names(self):
        return sorted(self._pipelines.keys())

    def add(self, model: str, pipeline: ModelPipeline, client: Client):
        self._pipelines[model] = pipeline
        self._clients[model] = client

    async def remove(self, model: str):
        self._pipelines.pop(model, None)
        client = self._clients.pop(model, None)
        router = self._kv_routers.pop(model, None)
        if router is not None and hasattr(router, "close"):
            await router.close()
        if client is not None:
            await client.close()

    def kv_router_for(self, model: str):
        return self._kv_routers.get(model)

    def client_for(self, model: str) -> Optional[Client]:
        return self._clients.get(model)


class ModelWatcher:
    """Watch v1/mdc/ and maintain the ModelManager
    (reference ModelWatcher watcher.rs:101)."""

    def __init__(
        self,
        drt: DistributedRuntime,
        manager: ModelManager,
        router_mode: RouterMode = RouterMode.ROUND_ROBIN,
        kv_router_factory: Optional[Callable] = None,
        encoder: Optional[str] = None,
        gate=None,
    ):
        self.drt = drt
        self.manager = manager
        self.router_mode = router_mode
        self.kv_router_factory = kv_router_factory
        # "namespace/component/endpoint" of a multimodal encode worker:
        # adds the E hop (llm/multimodal.py) to every model pipeline
        self.encoder = encoder
        # dynogate (gate/, docs/overload.md): each discovered model's
        # backend component is registered so the gate follows its load
        # signals; its watermark preference feeds the PushRouter
        self.gate = gate
        self._task: Optional[asyncio.Task] = None
        self._card_keys: Dict[str, str] = {}  # key -> model name

    async def start(self):
        assert self.drt.discovery is not None, "model watching needs discovery"
        watch = await self.drt.discovery.watch_prefix(MODEL_ROOT)
        for item in watch.snapshot:
            await self._on_put(item["key"], item["value"])
        self._task = asyncio.create_task(self._loop(watch))

    async def _loop(self, watch):
        async for event in watch:
            try:
                if event.type == "put":
                    await self._on_put(event.key, event.value)
                else:
                    await self._on_delete(event.key)
            except Exception:  # noqa: BLE001 — watcher must survive bad cards
                logger.exception("model watcher failed handling %s", event.key)

    async def _on_put(self, key: str, raw: bytes):
        payload = json.loads(raw)
        card = ModelDeploymentCard.from_json(raw)
        ep_info = payload.get("endpoint") or {}
        if self.manager.get(card.name) is not None:  # dynolint: disable=race-await-atomicity -- the model watcher is one serial task: _loop awaits each _on_put to completion
            self._card_keys[key] = card.name
            return  # another worker instance of an already-live model
        ns = ep_info.get("namespace", "dynamo")
        comp = ep_info.get("component", "backend")
        endpoint = (
            self.drt.namespace(ns)
            .component(comp)
            .endpoint(ep_info.get("endpoint", "generate"))
        )
        client = await endpoint.client()
        instance_prefer = None
        if self.gate is not None and self.gate.config.enabled:
            try:
                await self.gate.track_model(card.name, ns, comp, client)
                instance_prefer = self.gate.signals.prefer_below_watermark(
                    ns, comp)
            except Exception:  # noqa: BLE001 — the gate must FAIL OPEN
                # a metrics-subscribe hiccup leaves the gate signal-blind
                # for this model (it then admits everything); it must not
                # abort model registration or crash the watcher snapshot
                logger.warning(
                    "admission gate could not follow %s load signals; "
                    "gate stays fail-open for it", card.name, exc_info=True,
                )
        kv_router = None
        if self.router_mode == RouterMode.KV and self.kv_router_factory is not None:
            kv_router = await self.kv_router_factory(self.drt, card, client)
            self.manager._kv_routers[card.name] = kv_router
        encode_client = None
        if self.encoder:
            seg = self.encoder.split("/")
            if len(seg) == 1:
                ns, comp, ep = "dynamo", seg[0], "encode"
            elif len(seg) == 2:
                ns, comp, ep = seg[0], seg[1], "encode"
            else:
                ns, comp, ep = seg[0], seg[1], seg[2]
            encode_client = await (
                self.drt.namespace(ns).component(comp).endpoint(ep).client()
            )
        pipeline = build_routed_pipeline(
            card, client, self.router_mode, kv_router=kv_router,
            encode_client=encode_client, instance_prefer=instance_prefer,
        )
        self.manager.add(card.name, pipeline, client)
        self._card_keys[key] = card.name
        logger.info("model added: %s (router=%s)", card.name, self.router_mode.value)

    async def _on_delete(self, key: str):
        model = self._card_keys.pop(key, None)
        if model is None:
            return
        # remove only when no other card keys reference the model
        if model not in self._card_keys.values():
            await self.manager.remove(model)
            if self.gate is not None:
                await self.gate.untrack_model(model)
            logger.info("model removed: %s", model)

    async def stop(self):
        if self._task:
            self._task.cancel()
