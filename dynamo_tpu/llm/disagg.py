"""Disaggregated prefill/decode serving.

Mirrors reference disagg flow (SURVEY.md §3.3): the DECODE worker
orchestrates — it decides per-request whether to prefill remotely
(conditional disaggregation, disagg_router.rs:135,230), calls a prefill
worker with max_tokens=1 + disagg params, and continues decoding locally
from the transferred KV.

TPU KV-transfer path (NIXL replacement, SURVEY §7 step 6): host-staged —
the prefill worker's engine extracts the sequence's KV pages to host and
returns them ON the response stream, which is already a direct prefill→
decode TCP connection (our request plane), so the transfer is one hop with
no extra rendezvous; descriptors ride the same frames. ICI/DCN direct
device-to-device transfer is the planned fast path behind the same
interface.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


@dataclass
class DisaggConfig:
    """Conditional-disagg thresholds (reference DisaggregatedRouter
    disagg_router.rs:135)."""

    enabled: bool = True
    # remote prefill iff (prompt_len - prefix_hit_tokens) > threshold
    remote_prefill_threshold_tokens: int = 64
    # skip remote if the prefill pool is this backed up
    max_prefill_queue: int = 64


class DisaggregatedRouter:
    """Decide local vs remote prefill (reference prefill_remote
    disagg_router.rs:230)."""

    def __init__(self, config: Optional[DisaggConfig] = None):
        self.config = config or DisaggConfig()
        self.prefill_queue_depth = 0  # updated from prefill worker metrics

    def update_queue_depth(self, depth: int):
        self.prefill_queue_depth = depth

    def prefill_remote(self, prompt_len: int, prefix_hit_tokens: int, have_prefill_workers: bool) -> bool:
        if not self.config.enabled or not have_prefill_workers:
            return False
        if self.prefill_queue_depth > self.config.max_prefill_queue:
            return False
        return (prompt_len - prefix_hit_tokens) > self.config.remote_prefill_threshold_tokens


# ---------------------------------------------------------------------- #
# KV wire format (the "NIXL descriptor + payload" role)
# ---------------------------------------------------------------------- #


def pack_kv_payload(
    kv_k: np.ndarray, kv_v: np.ndarray, n_tokens: int, page_size: int
) -> Dict[str, Any]:
    """Serialize extracted KV pages [L, n_pages, page_size, KH, D] for the
    response stream (msgpack-safe: raw bytes + shape/dtype header)."""
    return {
        "k": kv_k.tobytes(),
        "v": kv_v.tobytes(),
        "shape": list(kv_k.shape),
        "dtype": str(kv_k.dtype),
        "n_tokens": n_tokens,
        "page_size": page_size,
    }


def unpack_kv_payload(payload: Dict[str, Any]) -> Tuple[np.ndarray, np.ndarray, int]:
    dtype = payload["dtype"]
    if dtype == "bfloat16":
        import ml_dtypes

        np_dtype = ml_dtypes.bfloat16
    else:
        np_dtype = np.dtype(dtype)
    shape = tuple(payload["shape"])
    kv_k = np.frombuffer(payload["k"], dtype=np_dtype).reshape(shape)
    kv_v = np.frombuffer(payload["v"], dtype=np_dtype).reshape(shape)
    return kv_k, kv_v, int(payload["n_tokens"])
