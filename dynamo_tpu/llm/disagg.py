"""Disaggregated prefill/decode serving.

Mirrors reference disagg flow (SURVEY.md §3.3): the DECODE worker
orchestrates — it decides per-request whether to prefill remotely
(conditional disaggregation, disagg_router.rs:135,230), calls a prefill
worker with max_tokens=1 + disagg params, and continues decoding locally
from the transferred KV.

TPU KV-transfer path (NIXL replacement, SURVEY §7 step 6): host-staged —
the prefill worker's engine extracts the sequence's KV pages to host and
returns them ON the response stream, which is already a direct prefill→
decode TCP connection (our request plane), so the transfer is one hop with
no extra rendezvous; descriptors ride the same frames. ICI/DCN direct
device-to-device transfer is the planned fast path behind the same
interface.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


@dataclass
class DisaggConfig:
    """Conditional-disagg thresholds (reference DisaggregatedRouter
    disagg_router.rs:135)."""

    enabled: bool = True
    # remote prefill iff (prompt_len - prefix_hit_tokens) > threshold
    remote_prefill_threshold_tokens: int = 64
    # skip remote if the prefill pool is this backed up
    max_prefill_queue: int = 64
    # a published queue depth older than this is UNKNOWN, not gospel: a
    # depth published just before a prefill worker died would otherwise
    # pin the routing decision forever (the decision falls back to the
    # threshold/SLA rule, exactly as if no depth had ever been published)
    queue_depth_ttl_s: float = 5.0
    # scheduler-informed routing floor: prompts with at most this many
    # uncached tokens never go remote (the KV transfer would cost more
    # than the prefill), regardless of the local TTFT estimate
    min_remote_tokens: int = 16
    # offload when the estimated LOCAL prefill wait eats this fraction of
    # the TTFT target (the remote hop must still leave budget for the
    # transfer + decode admission)
    ttft_headroom: float = 0.5


class DisaggregatedRouter:
    """Decide local vs remote prefill (reference prefill_remote
    disagg_router.rs:230). Two signals:

      * prefill-pool backpressure — published queue depth, with a
        staleness TTL so a dead worker's last report decays to "unknown";
      * the local engine scheduler's estimated TTFT (queue depth x cost
        model, JaxEngine.estimated_prefill_wait_ms) — when available it
        ADDS a queue-pressure offload trigger on top of the static token
        threshold: a prompt below the threshold still goes remote when
        the local queue would spend the TTFT budget. Big prompts keep
        going remote regardless (prefill-interference avoidance, the
        Nexus rationale), except tiny uncached remainders under
        min_remote_tokens, where the KV transfer costs more than the
        prefill.
    """

    def __init__(self, config: Optional[DisaggConfig] = None):
        self.config = config or DisaggConfig()
        self.prefill_queue_depth = 0  # updated from prefill worker metrics
        self._depth_at: Optional[float] = None  # monotonic publish time

    def update_queue_depth(self, depth: int, now: Optional[float] = None):
        self.prefill_queue_depth = depth
        self._depth_at = time.monotonic() if now is None else now

    def queue_depth_known(self, now: Optional[float] = None) -> bool:
        """True while the last published depth is fresh enough to act on."""
        if self._depth_at is None:
            return False
        now = time.monotonic() if now is None else now
        return (now - self._depth_at) <= self.config.queue_depth_ttl_s

    def invalidate(self, reason: str = "") -> None:
        """Forget the published queue depth NOW instead of waiting out the
        staleness TTL — called when the prefill instance set changes under
        us (a worker drained, died, or role-morphed away): the depth a
        departed lane published says nothing about the lanes that remain,
        and during a role flip it is wrong in BOTH directions — it can pin
        remote prefill off while fresh capacity sits idle, or on while the
        pool it describes no longer exists (docs/disagg_serving.md "Role
        morphing")."""
        self.prefill_queue_depth = 0
        self._depth_at = None
        if reason:
            logger.info("disagg: prefill queue depth invalidated (%s)", reason)

    def prefill_remote(
        self,
        prompt_len: int,
        prefix_hit_tokens: int,
        have_prefill_workers: bool,
        *,
        local_ttft_est_ms: Optional[float] = None,
        ttft_target_ms: Optional[float] = None,
        now: Optional[float] = None,
    ) -> bool:
        if not self.config.enabled or not have_prefill_workers:
            return False
        if self.queue_depth_known(now) and (
            self.prefill_queue_depth > self.config.max_prefill_queue
        ):
            return False
        uncached = prompt_len - prefix_hit_tokens
        if local_ttft_est_ms is not None and ttft_target_ms:
            # scheduler-informed: a below-threshold prompt still offloads
            # when the LOCAL queue leaves no room for its TTFT target;
            # above-threshold prompts fall through to the reference rule
            if uncached <= self.config.min_remote_tokens:
                return False
            if local_ttft_est_ms > self.config.ttft_headroom * ttft_target_ms:
                return True
        return uncached > self.config.remote_prefill_threshold_tokens


# ---------------------------------------------------------------------- #
# KV wire format (the "NIXL descriptor + payload" role)
# ---------------------------------------------------------------------- #


def pack_kv_payload(
    kv_k: np.ndarray, kv_v: np.ndarray, n_tokens: int, page_size: int,
    kv_format: str = "none",
) -> Dict[str, Any]:
    """Serialize extracted KV pages for the response stream (msgpack-safe:
    raw bytes + shape/dtype header). fp pages are [L, n_pages, page_size,
    KH, D]; a quantized pool's pages arrive PRE-PACKED as uint8
    [L, n_pages, PAGE_BYTES] rows (q bytes + per-page-per-head scales,
    ops/kv_quant.py) — `kv_format` names the layout so the decode side
    verifies before injecting (mixed-precision fleets fail typed)."""
    return {
        "k": kv_k.tobytes(),
        "v": kv_v.tobytes(),
        "shape": list(kv_k.shape),
        "dtype": str(kv_k.dtype),
        "fmt": str(kv_format),
        "n_tokens": n_tokens,
        "page_size": page_size,
    }


def unpack_kv_payload(payload: Dict[str, Any]) -> Tuple[np.ndarray, np.ndarray, int]:
    dtype = payload["dtype"]
    if dtype == "bfloat16":
        import ml_dtypes

        np_dtype = ml_dtypes.bfloat16
    else:
        np_dtype = np.dtype(dtype)
    shape = tuple(payload["shape"])
    kv_k = np.frombuffer(payload["k"], dtype=np_dtype).reshape(shape)
    kv_v = np.frombuffer(payload["v"], dtype=np_dtype).reshape(shape)
    return kv_k, kv_v, int(payload["n_tokens"])
