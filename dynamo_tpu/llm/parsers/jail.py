"""JailedStream: hold structured-output tokens out of the delta stream.

Role of the reference's jail operator
(lib/llm/src/protocols/openai/chat_completions/jail.rs, see
JAILED_STREAM_README.md): while the model is emitting a tool call (or
reasoning span), the raw text must NOT stream to the client as content —
it is accumulated ("jailed"), parsed when the span completes or the stream
ends, and released as structured `tool_calls` / `reasoning_content` fields
on the output.

Wraps an async iterator of Annotated[LLMEngineOutput]; text deltas are
routed through the reasoning parser first (incremental), then watched for
tool-call starts. Non-text emissions (annotations, errors, finish) pass
through.
"""

from __future__ import annotations

import dataclasses
from typing import AsyncIterator, List, Optional

import logging

from ..protocols.common import Annotated, LLMEngineOutput
from .reasoning import get_reasoning_parser
from .tool_calling import (
    ToolCallResult,
    find_tool_call_start,
    try_tool_call_parse,
)

logger = logging.getLogger(__name__)


class JailedStream:
    def __init__(
        self,
        stream: AsyncIterator[Annotated],
        tool_parser: Optional[str] = None,
        reasoning_parser: Optional[str] = None,
    ):
        self.stream = stream
        self.tool_parser = tool_parser
        if tool_parser is not None:
            try:
                find_tool_call_start("", tool_parser)
            except ValueError:
                # a typo'd model card must not abort live SSE streams —
                # degrade to plain text and say so
                logger.error("unknown tool parser %r; tool parsing disabled",
                             tool_parser)
                self.tool_parser = None
        try:
            self.reasoning = get_reasoning_parser(reasoning_parser)
        except ValueError:
            logger.error("unknown reasoning parser %r; reasoning parsing disabled",
                         reasoning_parser)
            self.reasoning = None
        self._jailed: List[str] = []
        self._jailing = False
        self._pending = ""  # tail that may be a split start marker
        self._released_any = False  # past message start: bare-JSON is content

    def _route_text(self, text: str) -> tuple[str, str]:
        """-> (reasoning_delta, content_delta) after the reasoning parser."""
        if self.reasoning is None:
            return "", text
        d = self.reasoning.feed(text)
        return d.reasoning, d.content

    def _check_jail(self, content: str) -> str:
        """Returns content safe to release now; jails the rest (including a
        trailing partial start marker, held in _pending)."""
        if self.tool_parser is None:
            return content
        if self._jailing:
            self._jailed.append(content)
            return ""
        text = self._pending + content
        self._pending = ""
        if not text:
            return ""
        idx, held = find_tool_call_start(
            text, self.tool_parser, allow_bare=not self._released_any
        )
        if idx is not None:
            self._jailing = True
            self._jailed.append(text[idx:])
            safe = text[:idx]
        elif held:
            self._pending = text[-held:]
            safe = text[:-held]
        else:
            safe = text
        if safe.strip():
            self._released_any = True
        return safe

    def _release(self) -> tuple[List[dict], str]:
        """Parse jailed text -> (tool_call dicts, leftover content)."""
        if not self._jailed:
            return [], ""
        text = "".join(self._jailed)
        self._jailed = []
        self._jailing = False
        calls, content = try_tool_call_parse(text, self.tool_parser)
        return (
            [
                {
                    "id": c.id,
                    "type": "function",
                    "function": {"name": c.name, "arguments": c.arguments},
                }
                for c in calls
            ],
            content,
        )

    def _drain(self) -> tuple[str, str, List[dict]]:
        """Release everything still held — reasoning tail, pending marker
        prefix, jailed tool-call text. -> (content, reasoning, tool_calls).
        Used by both the finish tick and the end-of-stream fallback."""
        content = ""
        reasoning = ""
        if self.reasoning is not None:
            tail = self.reasoning.flush()
            reasoning = tail.reasoning
            content += self._check_jail(tail.content)
        content += self._pending
        self._pending = ""
        calls, leftover = self._release()
        return content + leftover, reasoning, calls

    def _flush_end_of_stream(self) -> Optional[LLMEngineOutput]:
        """The stream ended without a finish tick: release held state."""
        content, reasoning, calls = self._drain()
        if not (content or reasoning or calls):
            return None
        return LLMEngineOutput(
            text=content or None,
            reasoning_content=reasoning or None,
            tool_calls=calls or None,
            finish_reason="tool_calls" if calls else None,
        )

    async def __aiter__(self):
        saw_finish = False
        async for ann in self.stream:
            if ann.data is None or ann.event is not None or ann.is_error():
                yield ann
                continue
            out: LLMEngineOutput = ann.data
            if out.text is None and not out.finish_reason:
                yield ann
                continue

            reasoning_delta, content = ("", "")
            if out.text:
                reasoning_delta, content = self._route_text(out.text)
            content = self._check_jail(content)

            if out.finish_reason:
                saw_finish = True
                d_content, d_reasoning, calls = self._drain()
                new = dataclasses.replace(
                    out,
                    text=(content + d_content) or None,
                    reasoning_content=(reasoning_delta + d_reasoning) or None,
                    tool_calls=calls or None,
                    finish_reason="tool_calls" if calls else out.finish_reason,
                )
                yield dataclasses.replace(ann, data=new)
                continue

            # always emit ticks that carry token_ids — downstream usage and
            # ITL accounting must see every token even when its text is jailed
            new = dataclasses.replace(
                out,
                text=content or None,
                reasoning_content=reasoning_delta or None,
            )
            if new.token_ids or new.text or new.reasoning_content:
                yield dataclasses.replace(ann, data=new)

        if not saw_finish:
            final = self._flush_end_of_stream()
            if final is not None:
                yield Annotated(data=final)
